"""Train a flax MLP on the MNIST Parquet dataset through the TPU-native loader.

The end-to-end acceptance flow (BASELINE.json config #1): make_reader ->
petastorm_tpu.jax.DataLoader -> jitted train step.  No reference equivalent
exists for JAX; the structure mirrors ``examples/mnist/pytorch_example.py``.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse
import time

import numpy as np
import optax

import jax
import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.jax import DataLoader
from petastorm_tpu.models.mlp import MLP


def train(dataset_url, epochs=3, batch_size=128, lr=1e-3):
    model = MLP()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))['params']
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({'params': p}, images)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state2 = tx.update(grads, opt_state)
        params2 = optax.apply_updates(params, updates)
        acc = jnp.mean(jnp.argmax(logits, -1) == labels)
        return params2, opt_state2, loss, acc

    for epoch in range(epochs):
        t0 = time.monotonic()
        losses, accs, rows = [], [], 0
        with make_reader(dataset_url, num_epochs=1, workers_count=4) as reader:
            for batch in DataLoader(reader, batch_size=batch_size,
                                    shuffling_queue_capacity=2048, seed=epoch):
                params, opt_state, loss, acc = train_step(
                    params, opt_state, batch['image'], batch['digit'])
                losses.append(float(loss)); accs.append(float(acc))
                rows += batch_size
        dt = time.monotonic() - t0
        print('epoch %d: loss=%.4f acc=%.3f (%.0f rows/s)'
              % (epoch, np.mean(losses), np.mean(accs[-20:]), rows / dt))
    return np.mean(accs[-20:])


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_petastorm')
    parser.add_argument('--epochs', type=int, default=3)
    parser.add_argument('--batch-size', type=int, default=128)
    args = parser.parse_args()
    final_acc = train(args.dataset_url, args.epochs, args.batch_size)
    print('final accuracy: %.3f' % final_acc)
