"""Train a flax MLP on the MNIST Parquet dataset through the TPU-native loader.

The end-to-end acceptance flow (BASELINE.json config #1): make_reader ->
petastorm_tpu.jax.DataLoader -> jitted train step.  No reference equivalent
exists for JAX; the structure mirrors ``examples/mnist/pytorch_example.py``.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse
import time

import numpy as np
import optax

import jax
import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.jax import DataLoader
from petastorm_tpu.models.mlp import MLP


def train(dataset_url, epochs=3, batch_size=128, lr=1e-3,
          checkpoint_dir=None, save_every=100):
    model = MLP()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))['params']
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    # --checkpoint-dir: the full train-state story (TrainStateManager) —
    # params ride as the orbax pytree; the optimizer state and the
    # loader's EXACT mid-epoch token ride as the data-plane blob, so a
    # restart resumes the stream at the batch it left (nothing re-read,
    # nothing skipped) with adam moments intact.
    mgr = None
    start_epoch, loader_token, global_step = 0, None, 0
    if checkpoint_dir:
        from petastorm_tpu.checkpoint import TrainStateManager
        mgr = TrainStateManager(checkpoint_dir, save_interval_steps=save_every,
                                max_to_keep=2)
        step, model_state, data_state = mgr.restore_latest()
        if step is not None:
            params = model_state['params']
            opt_state = jax.tree_util.tree_map(jnp.asarray, data_state['opt'])
            start_epoch, loader_token = data_state['epoch'], data_state['loader']
            global_step = step + 1
            print('resumed at step %d (epoch %d, mid-epoch token: %s)'
                  % (step, start_epoch, loader_token is not None))

    @jax.jit
    def train_step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({'params': p}, images)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state2 = tx.update(grads, opt_state)
        params2 = optax.apply_updates(params, updates)
        acc = jnp.mean(jnp.argmax(logits, -1) == labels)
        return params2, opt_state2, loss, acc

    if start_epoch >= epochs:
        print('checkpoint already covers all %d epochs — nothing to train'
              % epochs)
        if mgr is not None:
            mgr.close()
        return float('nan')

    for epoch in range(start_epoch, epochs):
        t0 = time.monotonic()
        losses, accs, rows = [], [], 0
        resume = loader_token if epoch == start_epoch else None
        loader_token = None  # consumed: later epochs start fresh
        with make_reader(dataset_url, num_epochs=1, workers_count=4,
                         resume_state=(resume or {}).get('reader')) as reader:
            loader = DataLoader(reader, batch_size=batch_size,
                                shuffling_queue_capacity=2048, seed=epoch,
                                resume_state=resume)
            for batch in loader:
                params, opt_state, loss, acc = train_step(
                    params, opt_state, batch['image'], batch['digit'])
                losses.append(float(loss)); accs.append(float(acc))
                rows += batch_size
                if mgr is not None and mgr.should_save(global_step):
                    mgr.save(global_step, {'params': params},
                             data_state={'epoch': epoch,
                                         'opt': jax.device_get(opt_state),
                                         'loader': loader.state_dict()})
                global_step += 1
        dt = time.monotonic() - t0
        if losses:
            print('epoch %d: loss=%.4f acc=%.3f (%.0f rows/s)'
                  % (epoch, np.mean(losses), np.mean(accs[-20:]), rows / dt))
        else:
            # a resume token taken at the stream's end yields no batches:
            # the epoch was already complete
            print('epoch %d: already complete at resume' % epoch)
    if mgr is not None:
        mgr.save(global_step, {'params': params},
                 data_state={'epoch': epochs, 'opt': jax.device_get(opt_state),
                             'loader': None}, force=True)
        mgr.wait_until_finished()
        mgr.close()
    return float(np.mean(accs[-20:])) if accs else float('nan')


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_petastorm')
    parser.add_argument('--epochs', type=int, default=3)
    parser.add_argument('--batch-size', type=int, default=128)
    parser.add_argument('--checkpoint-dir', default=None,
                        help='enable TrainStateManager checkpointing: '
                             'params + optimizer state + the loader\'s '
                             'exact mid-epoch token every '
                             '--save-every steps; rerun with the same dir '
                             'to resume at the batch the last save saw')
    parser.add_argument('--save-every', type=int, default=100)
    args = parser.parse_args()
    final_acc = train(args.dataset_url, args.epochs, args.batch_size,
                      checkpoint_dir=args.checkpoint_dir,
                      save_every=args.save_every)
    print('final accuracy: %.3f' % final_acc)
