"""Train a small torch MLP on the MNIST Parquet dataset (CPU).

Parity: reference ``examples/mnist/pytorch_example.py`` — the torch adapter
end-to-end flow (make_reader -> petastorm_tpu.pytorch.DataLoader -> train).
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np


def train(dataset_url, epochs=1, batch_size=128, lr=1e-3):
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    from petastorm_tpu import make_reader
    from petastorm_tpu.pytorch import DataLoader
    from petastorm_tpu.transform import TransformSpec

    model = nn.Sequential(nn.Flatten(), nn.Linear(28 * 28, 128), nn.ReLU(),
                          nn.Linear(128, 10))
    opt = torch.optim.Adam(model.parameters(), lr=lr)

    transform = TransformSpec(
        lambda row: {**row, 'image': (row['image'].astype(np.float32) / 255.0)})

    accs = []
    for epoch in range(epochs):
        reader = make_reader(dataset_url, num_epochs=1, workers_count=4,
                             transform_spec=transform)
        with DataLoader(reader, batch_size=batch_size,
                        shuffling_queue_capacity=2048) as loader:
            for batch in loader:
                images, labels = batch.image, batch.digit
                opt.zero_grad()
                logits = model(images)
                loss = F.cross_entropy(logits, labels)
                loss.backward()
                opt.step()
                accs.append((logits.argmax(-1) == labels).float().mean().item())
        print('epoch %d: loss=%.4f acc=%.3f' % (epoch, loss.item(), np.mean(accs[-20:])))
    return float(np.mean(accs[-20:]))


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_petastorm')
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--batch-size', type=int, default=128)
    args = parser.parse_args()
    print('final accuracy: %.3f' % train(args.dataset_url, args.epochs, args.batch_size))
