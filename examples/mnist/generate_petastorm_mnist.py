"""Write an MNIST-shaped petastorm dataset (acceptance config #1).

Parity: reference ``examples/mnist/generate_petastorm_mnist.py``, which
downloads real MNIST via torchvision and writes it with Spark.  This
environment has no network egress, so by default we synthesize MNIST-shaped
data whose pixel distribution depends on the label (so models demonstrably
learn); pass ``--mnist-data-dir`` pointing at a local torchvision MNIST copy
to use real digits.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.unischema import Unischema, UnischemaField

MnistSchema = Unischema('MnistSchema', [
    UnischemaField('idx', np.int64, (), None, False),
    UnischemaField('digit', np.int64, (), None, False),
    UnischemaField('image', np.uint8, (28, 28), CompressedImageCodec('png'), False),
])


def synthetic_mnist_rows(num_rows, seed=0):
    """Label-dependent synthetic digits: a bright patch whose position is the
    label; trivially learnable, MNIST-shaped."""
    rng = np.random.default_rng(seed)
    for i in range(num_rows):
        digit = int(rng.integers(0, 10))
        image = rng.integers(0, 50, (28, 28), dtype=np.uint8)
        r, c = divmod(digit, 5)
        image[4 + r * 12: 12 + r * 12, 2 + c * 5: 7 + c * 5] += 180
        yield {'idx': np.int64(i), 'digit': np.int64(digit), 'image': image}


def real_mnist_rows(data_dir, train=True):
    from torchvision import datasets  # optional; needs a local copy
    ds = datasets.MNIST(data_dir, train=train, download=False)
    for i in range(len(ds)):
        img, digit = ds[i]
        yield {'idx': np.int64(i), 'digit': np.int64(digit),
               'image': np.asarray(img, dtype=np.uint8)}


def generate_mnist_dataset(output_url, num_rows=10000, mnist_data_dir=None, train=True):
    rows = (real_mnist_rows(mnist_data_dir, train) if mnist_data_dir
            else synthetic_mnist_rows(num_rows))
    with DatasetWriter(output_url, MnistSchema, rows_per_rowgroup=1000) as writer:
        writer.write_many(rows)


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/mnist_petastorm')
    parser.add_argument('-n', '--num-rows', type=int, default=10000)
    parser.add_argument('--mnist-data-dir', default=None)
    args = parser.parse_args()
    generate_mnist_dataset(args.output_url, args.num_rows, args.mnist_data_dir)
    print('Wrote %s' % args.output_url)
