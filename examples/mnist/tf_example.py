"""Train a small keras MLP on the MNIST Parquet dataset (CPU).

Parity: reference ``examples/mnist/tf_example.py`` — the TF adapter
end-to-end flow (make_reader -> make_petastorm_dataset -> model.fit).
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse


def train(dataset_url, epochs=1, batch_size=128):
    import tensorflow as tf

    from petastorm_tpu import make_reader
    from petastorm_tpu.tf_utils import make_petastorm_dataset

    model = tf.keras.Sequential([
        tf.keras.layers.Flatten(input_shape=(28, 28)),
        tf.keras.layers.Dense(128, activation='relu'),
        tf.keras.layers.Dense(10),
    ])
    model.compile(optimizer='adam',
                  loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
                  metrics=['accuracy'])

    history = None
    for _ in range(epochs):
        with make_reader(dataset_url, num_epochs=1, workers_count=4) as reader:
            dataset = make_petastorm_dataset(reader) \
                .map(lambda row: (tf.cast(row.image, tf.float32) / 255.0, row.digit)) \
                .batch(batch_size)
            history = model.fit(dataset, epochs=1, verbose=2)
    return float(history.history['accuracy'][-1])


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/mnist_petastorm')
    parser.add_argument('--epochs', type=int, default=1)
    parser.add_argument('--batch-size', type=int, default=128)
    args = parser.parse_args()
    print('final accuracy: %.3f' % train(args.dataset_url, args.epochs, args.batch_size))
