"""NGram temporal reader over AV-sensor-like Parquet (acceptance config #5).

Generates a multi-field timestamped dataset, reads sliding windows with
delta-threshold gap filtering, and feeds window tensors to a jitted step.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np

import jax
import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.jax import DataLoader
from petastorm_tpu.ngram import NGram
from petastorm_tpu.unischema import Unischema, UnischemaField

SensorSchema = Unischema('SensorSchema', [
    UnischemaField('timestamp', np.int64, (), None, False),
    UnischemaField('lidar', np.float32, (32,), NdarrayCodec(), False),
    UnischemaField('velocity', np.float32, (3,), NdarrayCodec(), False),
])


def generate(url, rows=600, seed=0):
    rng = np.random.default_rng(seed)
    t = 0
    def row_gen():
        nonlocal t
        for i in range(rows):
            t += int(rng.integers(1, 3)) if i % 50 else 100  # dropouts every 50
            yield {'timestamp': np.int64(t),
                   'lidar': rng.standard_normal(32).astype(np.float32),
                   'velocity': rng.standard_normal(3).astype(np.float32)}
    with DatasetWriter(url, SensorSchema, rows_per_rowgroup=100) as w:
        w.write_many(row_gen())


def main(url):
    generate(url)
    ngram = NGram(fields={-2: ['lidar'], -1: ['lidar'], 0: ['lidar', 'velocity']},
                  delta_threshold=10, timestamp_field='timestamp')

    @jax.jit
    def predict_speed(history, velocity):
        return jnp.mean(history, axis=(1, 2)) + jnp.linalg.norm(velocity, axis=1)

    def collate(batch):
        history = np.stack([batch[-2]['lidar'], batch[-1]['lidar']], axis=1)
        return {'history': history, 'velocity': batch[0]['velocity']}

    with make_reader(url, schema_fields=ngram, shuffle_row_groups=False) as reader:
        loader = DataLoader(reader, batch_size=32, transform_fn=collate)
        for i, batch in enumerate(loader):
            out = predict_speed(batch['history'], batch['velocity'])
            if i == 0:
                print('window batch: history', batch['history'].shape,
                      'velocity', batch['velocity'].shape, '->', out.shape)
    print('done')


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/ngram_sensor')
    args = parser.parse_args()
    main(args.dataset_url)
