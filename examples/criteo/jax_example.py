"""Train DLRM on Criteo-shaped Parquet through the columnar loader (config #4).

Uses make_batch_reader (vanilla Parquet, no codecs) -> DataLoader with a
transform assembling (dense, categorical, label) arrays on the host.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse
import time

import numpy as np
import optax

import jax
import jax.numpy as jnp

from petastorm_tpu import make_batch_reader
from petastorm_tpu.benchmark import StallMonitor
from petastorm_tpu.jax import DataLoader
from petastorm_tpu.models.dlrm import DLRM

from generate_criteo_parquet import NUM_CATEGORICAL, NUM_DENSE, VOCAB_SIZES


def pack_columns(batch):
    dense = np.stack([batch['dense_%d' % i] for i in range(NUM_DENSE)], axis=1)
    cats = np.stack([batch['cat_%d' % i] for i in range(NUM_CATEGORICAL)], axis=1)
    return {'dense': np.log1p(dense).astype(np.float32), 'cats': cats,
            'label': batch['label'].astype(np.float32)}


def train(dataset_url, epochs=1, batch_size=2048, lr=1e-3, scan_steps=0):
    model = DLRM(vocab_sizes=VOCAB_SIZES)
    params = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, NUM_DENSE)), jnp.zeros((1, NUM_CATEGORICAL), jnp.int32))
    tx = optax.adagrad(lr)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch['dense'], batch['cats'])
            return optax.sigmoid_binary_cross_entropy(logits, batch['label']).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state2, loss

    monitor = StallMonitor()
    for epoch in range(epochs):
        losses = []
        t0 = time.monotonic()
        with make_batch_reader(dataset_url, num_epochs=1, workers_count=4) as reader:
            loader = DataLoader(reader, batch_size=batch_size, transform_fn=pack_columns)
            if scan_steps >= 1:
                # Fused consumption (scan_batches): the DLRM step is tiny
                # (embedding gathers + small MLPs), so per-step dispatch
                # latency — not compute — is where a fast device stalls;
                # k steps per stacked device_put + lax.scan dispatch
                # amortizes it k-fold (the bench's stall_pct_dlrm_scan leg).
                def scan_step(carry, batch):
                    p, o = carry
                    p, o, loss = train_step(p, o, batch)
                    return (p, o), loss
                for (params, opt_state), outs in loader.scan_batches(
                        scan_step, (params, opt_state),
                        steps_per_call=scan_steps, donate_carry=False):
                    losses.extend(np.asarray(outs).ravel().tolist())
            else:
                for batch in monitor.wrap(loader):
                    params, opt_state, loss = train_step(params, opt_state, batch)
                    losses.append(float(loss))
        stall = ('(fused scan: per-step stall n/a)' if scan_steps >= 1
                 else monitor.report())
        print('epoch %d: loss=%.4f (%.1fs) stall=%s'
              % (epoch, np.mean(losses[-10:]), time.monotonic() - t0, stall))
    return np.mean(losses[-10:])


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/criteo_parquet')
    parser.add_argument('--epochs', type=int, default=2)
    parser.add_argument('--batch-size', type=int, default=2048)
    parser.add_argument('--scan-steps', type=int, default=0,
                        help='consume via scan_batches: K steps per stacked '
                             'device_put + lax.scan dispatch — use when '
                             'dispatch latency, not compute, is the stall '
                             '(tiny DLRM steps on fast/tunneled devices)')
    args = parser.parse_args()
    train(args.dataset_url, args.epochs, args.batch_size,
          scan_steps=args.scan_steps)
