"""Write a Criteo-shaped plain-Parquet dataset (acceptance config #4).

The real Criteo-1TB flow materializes via SparkDatasetConverter; this
generator produces the same column layout (13 dense floats, 26 categorical
ids, binary label) with pyarrow so the DLRM example runs hermetically.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths

NUM_DENSE = 13
NUM_CATEGORICAL = 26
VOCAB_SIZES = [1000 + 37 * i for i in range(NUM_CATEGORICAL)]


def generate_criteo_parquet(output_url, rows_count=20000, rows_per_group=4096, seed=0):
    rng = np.random.default_rng(seed)
    fs, path = get_filesystem_and_path_or_paths(output_url)
    fs.makedirs(path, exist_ok=True)
    columns = {'label': pa.array(rng.integers(0, 2, rows_count).astype(np.int32))}
    for i in range(NUM_DENSE):
        columns['dense_%d' % i] = pa.array(
            rng.lognormal(0, 1, rows_count).astype(np.float32))
    for i in range(NUM_CATEGORICAL):
        columns['cat_%d' % i] = pa.array(
            rng.integers(0, VOCAB_SIZES[i], rows_count).astype(np.int32))
    with fs.open(path + '/data.parquet', 'wb') as f:
        pq.write_table(pa.table(columns), f, row_group_size=rows_per_group)


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/criteo_parquet')
    parser.add_argument('-n', '--rows-count', type=int, default=20000)
    args = parser.parse_args()
    generate_criteo_parquet(args.output_url, args.rows_count)
    print('Wrote %s' % args.output_url)
