"""Read the hello-world dataset as a tf.data.Dataset.

Parity: reference ``examples/hello_world/petastorm_dataset/tensorflow_hello_world.py``
(eager tf.data iteration via ``make_petastorm_dataset``).
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.tf_utils import make_petastorm_dataset


def tensorflow_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url) as reader:
        dataset = make_petastorm_dataset(reader)
        for sample in dataset.take(4):
            print(int(sample.id), sample.image1.shape)


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    tensorflow_hello_world(args.dataset_url)
