"""Read the hello-world dataset through the torch DataLoader adapter.

Parity: reference ``examples/hello_world/petastorm_dataset/pytorch_hello_world.py``.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.pytorch import DataLoader


def pytorch_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    # batch_size=1: array_4d has a wildcard leading dim, so rows cannot be
    # stacked (same constraint as the reference example).
    with DataLoader(make_reader(dataset_url), batch_size=1) as loader:
        for batch in loader:
            print('id batch:', batch.id, 'image1:', tuple(batch.image1.shape))
            break


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)
