"""Hello-world petastorm-format dataset (acceptance config #2).

Parity: reference ``examples/hello_world/petastorm_dataset/
generate_petastorm_dataset.py`` — same HelloWorldSchema shape, written with
the pyarrow DatasetWriter instead of Spark.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.unischema import Unischema, UnischemaField

HelloWorldSchema = Unischema('HelloWorldSchema', [
    UnischemaField('id', np.int64, (), None, False),
    UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
    UnischemaField('array_4d', np.uint8, (None, 128, 30, 4), NdarrayCodec(), False),
])


def row_generator(idx, rng):
    return {
        'id': np.int64(idx),
        'image1': rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
        'array_4d': rng.integers(0, 255, (int(rng.integers(1, 5)), 128, 30, 4),
                                 dtype=np.uint8),
    }


def generate_petastorm_dataset(output_url='file:///tmp/hello_world_dataset', rows_count=10):
    rng = np.random.default_rng(0)
    with DatasetWriter(output_url, HelloWorldSchema, rows_per_rowgroup=5) as writer:
        writer.write_many(row_generator(i, rng) for i in range(rows_count))


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    generate_petastorm_dataset(args.output_url)
    print('Wrote %s' % args.output_url)
