"""Read the hello-world dataset straight into device memory.

The TPU-native analog of the reference's tensorflow/pytorch hello worlds.
"""

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.jax import DataLoader


def jax_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    # array_4d has a wildcard dim -> keep fixed-shape fields only for batching.
    with make_reader(dataset_url, schema_fields=['id', 'image1']) as reader:
        for batch in DataLoader(reader, batch_size=4):
            print('id:', batch['id'], 'image1:', batch['image1'].shape,
                  'on', next(iter(batch['image1'].devices())))


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)
