"""Read the hello-world dataset straight into device memory.

The TPU-native analog of the reference's tensorflow/pytorch hello worlds.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

from petastorm_tpu import make_reader
from petastorm_tpu.jax import DataLoader


def jax_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    # array_4d has a wildcard dim -> keep fixed-shape fields only for batching.
    with make_reader(dataset_url, schema_fields=['id', 'image1']) as reader:
        for batch in DataLoader(reader, batch_size=4):
            print('id:', batch['id'], 'image1:', batch['image1'].shape,
                  'on', next(iter(batch['image1'].devices())))


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    jax_hello_world(args.dataset_url)
