"""Read the hello-world dataset with the plain python API.

Parity: reference ``examples/hello_world/petastorm_dataset/python_hello_world.py``.
"""

import argparse

from petastorm_tpu import make_reader


def python_hello_world(dataset_url='file:///tmp/hello_world_dataset'):
    with make_reader(dataset_url) as reader:
        for sample in reader:
            print(sample.id, sample.image1.shape, sample.array_4d.shape)


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/hello_world_dataset')
    args = parser.parse_args()
    python_hello_world(args.dataset_url)
