"""Read a plain Parquet dataset through the columnar torch loader.

Parity: reference ``examples/hello_world/external_dataset/pytorch_hello_world.py``
(BatchedDataLoader over make_batch_reader — the fast columnar torch path).
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

from petastorm_tpu import make_batch_reader
from petastorm_tpu.pytorch import BatchedDataLoader


def pytorch_hello_world(dataset_url='file:///tmp/external_dataset'):
    with BatchedDataLoader(make_batch_reader(dataset_url), batch_size=8) as loader:
        for batch in loader:
            print('torch batch ids:', batch['id'][:5].tolist())
            break


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    pytorch_hello_world(args.dataset_url)
