"""Read a plain Parquet dataset via make_batch_reader.

Parity: reference ``examples/hello_world/external_dataset/python_hello_world.py``.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

from petastorm_tpu import make_batch_reader


def python_hello_world(dataset_url='file:///tmp/external_dataset'):
    with make_batch_reader(dataset_url) as reader:
        for batch in reader:
            print('batch of %d: ids %s...' % (len(batch.id), batch.id[:5]))


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    python_hello_world(args.dataset_url)
