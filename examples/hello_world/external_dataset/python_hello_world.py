"""Read a plain Parquet dataset via make_batch_reader.

Parity: reference ``examples/hello_world/external_dataset/python_hello_world.py``.
"""

import argparse

from petastorm_tpu import make_batch_reader


def python_hello_world(dataset_url='file:///tmp/external_dataset'):
    with make_batch_reader(dataset_url) as reader:
        for batch in reader:
            print('batch of %d: ids %s...' % (len(batch.id), batch.id[:5]))


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    python_hello_world(args.dataset_url)
