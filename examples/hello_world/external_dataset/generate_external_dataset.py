"""Write a plain (non-petastorm) Parquet dataset for make_batch_reader demos.

Parity: reference ``examples/hello_world/external_dataset/generate_external_dataset.py``
(there via Spark; here via pyarrow).
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths


def generate_external_dataset(output_url='file:///tmp/external_dataset', rows_count=100):
    fs, path = get_filesystem_and_path_or_paths(output_url)
    fs.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(0)
    table = pa.table({
        'id': pa.array(np.arange(rows_count, dtype=np.int64)),
        'value1': pa.array(rng.standard_normal(rows_count)),
        'value2': pa.array(rng.standard_normal(rows_count)),
    })
    with fs.open(path + '/data.parquet', 'wb') as f:
        pq.write_table(table, f, row_group_size=25)


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/external_dataset')
    args = parser.parse_args()
    generate_external_dataset(args.output_url)
    print('Wrote %s' % args.output_url)
