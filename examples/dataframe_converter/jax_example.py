"""DataFrame -> training data in two lines (converter example).

The reference's ``examples/spark_dataset_converter`` flow, TPU-native: a
(pandas or Spark) DataFrame is materialized once to cached Parquet and the
converter hands back loaders for JAX, TF, or torch.  With pyspark installed
the same script works on a Spark DataFrame via ``make_spark_converter``.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp

from petastorm_tpu.spark.spark_dataset_converter import make_pandas_converter


def main():
    rng = np.random.default_rng(0)
    df = pd.DataFrame({
        'features': [rng.standard_normal(16) for _ in range(512)],
        'label': rng.integers(0, 2, 512).astype(np.int64),
    })

    converter = make_pandas_converter(df, parent_cache_dir_url='file:///tmp/converter_cache')
    print('materialized %d rows to %s' % (len(converter), converter.cache_dir_url))

    @jax.jit
    def logreg_loss(w, x, y):
        logits = x @ w
        return jnp.mean(jnp.logaddexp(0.0, logits) - y * logits)

    w = jnp.zeros((16,))
    grad = jax.jit(jax.grad(logreg_loss))
    with converter.make_jax_loader(batch_size=64, num_epochs=2,
                                   workers_count=2) as loader:
        for step, batch in enumerate(loader):
            x = batch['features']  # rectangular list column -> (B, 16) array
            w = w - 0.1 * grad(w, x.astype(jnp.float32), batch['label'].astype(jnp.float32))
            if step % 5 == 0:
                loss = float(logreg_loss(w, x.astype(jnp.float32),
                                         batch['label'].astype(jnp.float32)))
                print('step %d loss %.4f' % (step, loss))

    converter.delete()
    print('cache deleted')


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # honor JAX_PLATFORMS; fall back to cpu off-TPU
    main()
