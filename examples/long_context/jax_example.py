"""Long-context LM training: token Parquet -> ring-attention Transformer.

The sequence-parallel showcase: documents land in Parquet as token arrays
(NdarrayCodec), the reader streams them columnar, and the model shards the
sequence axis over the device mesh — ring attention rotates K/V blocks over
ICI so no device ever holds the full sequence.  On a single device the same
script runs with the Pallas flash kernel instead (``--strategy flash``).

Run: python generate_token_parquet.py /tmp/lc_tokens
     python jax_example.py --dataset-url file:///tmp/lc_tokens
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse

import numpy as np
import optax

import jax
import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.jax import DataLoader
from petastorm_tpu.models.transformer import (TransformerLM, make_attn_fn,
                                              param_shardings)
from petastorm_tpu.parallel import make_mesh, global_batch_from_local

from generate_token_parquet import SEQ_LEN, VOCAB


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--dataset-url', default='file:///tmp/lc_tokens')
    parser.add_argument('--strategy', default='auto',
                        choices=['auto', 'flash', 'ring', 'ulysses', 'dense'])
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--steps', type=int, default=30)
    parser.add_argument('--block-k', type=int, default=None,
                        help='chunk ring-attention score tiles (memory cap '
                             'for very long local sequences)')
    args = parser.parse_args()

    n_dev = len(jax.devices())
    strategy = args.strategy
    if strategy == 'auto':
        strategy = 'ring' if n_dev > 1 else 'flash'
    if args.block_k is not None and strategy != 'ring':
        parser.error('--block-k only applies to the ring strategy '
                     '(resolved strategy: %s)' % strategy)

    if strategy in ('ring', 'ulysses'):
        sp = 2 if n_dev % 2 == 0 else 1
        mesh = make_mesh({'data': n_dev // sp, 'seq': sp})
    else:
        mesh = make_mesh({'data': n_dev, 'seq': 1})
    from jax.sharding import NamedSharding, PartitionSpec as P
    batch_sharding = NamedSharding(mesh, P('data', 'seq'))

    # The global batch must divide the 'data' mesh axis; round the requested
    # size up to the nearest multiple.
    data_size = mesh.shape['data']
    batch_size = -(-args.batch_size // data_size) * data_size
    if batch_size != args.batch_size:
        print('batch size %d -> %d (multiple of data axis %d)'
              % (args.batch_size, batch_size, data_size))

    model = TransformerLM(
        vocab_size=VOCAB, d_model=256, num_heads=8, num_layers=4, d_ff=1024,
        max_seq_len=SEQ_LEN, attn_fn=make_attn_fn(mesh, strategy, head_axis=None,
                                             block_k=args.block_k),
        remat=True)
    rng = jax.random.PRNGKey(0)
    init_tokens = jnp.zeros((mesh.shape['data'], SEQ_LEN), jnp.int32)
    params = model.init(rng, init_tokens)['params']
    params = jax.device_put(params, param_shardings(params, mesh))
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply({'params': p}, tokens)
            labels = jnp.roll(tokens, -1, axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, loss

    step = 0
    with make_reader(args.dataset_url, num_epochs=None, columnar_decode=True,
                     workers_count=4) as reader:
        loader = DataLoader(reader, batch_size=batch_size, prefetch=2,
                            drop_last=True)
        for batch in loader:
            tokens = global_batch_from_local(
                np.ascontiguousarray(batch['tokens']), batch_sharding)
            params, opt_state, loss = train_step(params, opt_state, tokens)
            step += 1
            if step % 10 == 0:
                print('step %d  loss %.4f  (%s, %d devices)'
                      % (step, float(loss), strategy, n_dev))
            if step >= args.steps:
                break
    print('done: %d steps of seq_len=%d with %s attention' % (step, SEQ_LEN, strategy))


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    main()
