"""Variable-length documents -> packed fixed-shape LM batches.

The packing showcase: documents of *different* lengths land in Parquet
(wildcard-shape ``tokens`` field), the reader streams them per-row, and
``petastorm_tpu.jax.packing`` lays them end-to-end into static
``(rows, max_len)`` batches with segment ids — so XLA compiles ONE program
and pad-token FLOPs are mostly recovered.  Attention stays correct across
document boundaries via ``packed_attention``'s segment mask, and the loss
never predicts across a boundary (``next_token_targets``).

Run: python packed_example.py            # writes its own dataset
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse
import functools
import time

import numpy as np
import optax

import jax
import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.jax import PackedDataLoader, packing
from petastorm_tpu.models.decoding import generate as lm_generate
from petastorm_tpu.models.transformer import TransformerLM
from petastorm_tpu.unischema import Unischema, UnischemaField

VOCAB = 1024
MAX_LEN = 512
#: one source of truth for the architecture — train() and sample() share it
MODEL_KW = dict(vocab_size=VOCAB, d_model=128, num_heads=4, num_layers=2,
                d_ff=256, max_seq_len=MAX_LEN)

VarTokenSchema = Unischema('VarTokenSchema', [
    UnischemaField('doc_id', np.int64, (), None, False),
    # wildcard first dim: every document has its own length
    UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False),
])


def generate(url, num_docs=512, seed=0):
    rng = np.random.default_rng(seed)
    with DatasetWriter(url, VarTokenSchema, rows_per_rowgroup=64) as writer:
        for i in range(num_docs):
            length = int(rng.integers(32, MAX_LEN + 1))
            tokens = (rng.zipf(1.4, length) % VOCAB).astype(np.int32)
            writer.write({'doc_id': np.int64(i), 'tokens': tokens})
    return url


def train(dataset_url, steps=20, rows_per_batch=4, lr=3e-3):
    model_kw = MODEL_KW

    def make_step():
        tx = optax.adamw(lr)

        @jax.jit
        def step(params, opt_state, tokens, segment_ids, positions):
            attn = functools.partial(packing.packed_attention,
                                     segment_ids=segment_ids)
            model = TransformerLM(attn_fn=attn, **model_kw)
            targets, weights = packing.next_token_targets(tokens, segment_ids)

            def loss_fn(p):
                # positions restart at 0 per packed document, so each one is
                # embedded as if it began the row
                logits = model.apply(p, tokens,
                                     positions=positions).astype(jnp.float32)
                per_tok = optax.softmax_cross_entropy_with_integer_labels(
                    logits, targets)
                return (per_tok * weights).sum() / jnp.maximum(weights.sum(), 1)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return step, tx

    step, tx = make_step()
    init_model = TransformerLM(**model_kw)
    params = init_model.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, MAX_LEN), jnp.int32))
    opt_state = tx.init(params)

    done = 0
    stats = {'seen': 0, 'real': 0}

    def count_tokens(batch):
        # Runs on the HOST batch before transfer — stats come for free,
        # no device->host readback against the prefetch pipeline.
        stats['seen'] += batch['segment_ids'].size
        stats['real'] += int((batch['segment_ids'] > 0).sum())
        return batch

    t0 = time.monotonic()
    with make_reader(dataset_url, schema_fields=['tokens'],
                     num_epochs=None, workers_count=4) as reader:
        # PackedDataLoader = pack_stream + the DataLoader's double-buffered
        # device delivery (same prefetch/sharding machinery as images).
        loader = PackedDataLoader(reader, 'tokens', max_len=MAX_LEN,
                                  rows_per_batch=rows_per_batch, prefetch=2,
                                  transform_fn=count_tokens)
        for batch in loader:
            params, opt_state, loss = step(
                params, opt_state, batch['tokens'], batch['segment_ids'],
                batch['positions'])
            done += 1
            if done >= steps:
                break
    loss = float(loss)
    dt = time.monotonic() - t0
    util = stats['real'] / stats['seen']
    print('steps=%d loss=%.3f packing_utilization=%.0f%% tokens/s=%.0f'
          % (done, loss, 100 * util, stats['real'] / dt))
    assert np.isfinite(loss)
    return params, loss, util


def sample(params, prompt_len=8, max_new=16, seed=0):
    """Continue a corpus-style prompt with the compiled KV-cache decoder
    (models.decoding): one batched prefill, then a lax.scan token loop."""
    from petastorm_tpu.ops import flash_attention

    model = TransformerLM(attn_fn=flash_attention, **MODEL_KW)
    params = params.get('params', params)  # train() carries full variables
    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        (rng.zipf(1.4, (2, prompt_len)) % VOCAB).astype(np.int32))
    out = lm_generate(model, params, prompt, max_new, temperature=0.8,
                      top_p=0.95, rng=jax.random.PRNGKey(seed))
    for r in range(out.shape[0]):
        print('prompt %s -> %s' % (np.asarray(prompt[r]).tolist(),
                                   np.asarray(out[r]).tolist()))


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/lc_var_tokens')
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--skip-generate', action='store_true')
    parser.add_argument('--sample', action='store_true',
                        help='after training, sample continuations with the '
                             'compiled KV-cache decoder')
    args = parser.parse_args()
    if not args.skip_generate:
        generate(args.dataset_url)
    params, _, _ = train(args.dataset_url, steps=args.steps)
    if args.sample:
        sample(params)
