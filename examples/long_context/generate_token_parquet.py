"""Write a synthetic long-context token dataset (documents as token arrays).

Each row is one document: ``tokens`` is a fixed-length int32 sequence
(Zipf-ish draws so the LM has learnable statistics), stored through
NdarrayCodec — the pattern for any pre-tokenized corpus.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import sys

import numpy as np

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.unischema import Unischema, UnischemaField

SEQ_LEN = 1024
VOCAB = 4096
NUM_DOCS = 256

TokenSchema = Unischema('TokenSchema', [
    UnischemaField('doc_id', np.int64, (), None, False),
    UnischemaField('tokens', np.int32, (SEQ_LEN,), NdarrayCodec(), False),
])


def main(path='/tmp/lc_tokens'):
    url = path if '://' in path else 'file://' + path
    rng = np.random.default_rng(0)
    with DatasetWriter(url, TokenSchema, rows_per_rowgroup=32) as writer:
        for i in range(NUM_DOCS):
            tokens = (rng.zipf(1.3, SEQ_LEN) % VOCAB).astype(np.int32)
            writer.write({'doc_id': np.int64(i), 'tokens': tokens})
    print('wrote %d docs of %d tokens to %s' % (NUM_DOCS, SEQ_LEN, url))


if __name__ == '__main__':
    main(*sys.argv[1:])
