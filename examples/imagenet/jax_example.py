"""ImageNet-Parquet -> ResNet-50 through the TPU-native loader (config #3).

The north-star flow (BASELINE.json): JPEG/PNG decode + resize run in the
reader's worker pool (TransformSpec), batches are assembled columnar,
double-buffered onto the device mesh as pjit global arrays, and the
StallMonitor reports the step-time data-stall percentage that the <=2%
target refers to.
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse
import time

import numpy as np
import optax

import jax
import jax.numpy as jnp

from petastorm_tpu import make_reader
from petastorm_tpu.benchmark import StallMonitor
from petastorm_tpu.jax import DataLoader, augment
from petastorm_tpu.models.resnet import ResNet50
from petastorm_tpu.models.vit import ViT
from petastorm_tpu.parallel import data_parallel_sharding, make_mesh
from petastorm_tpu.transform import TransformSpec


def make_transform(image_hw):
    import cv2

    def fix_row(row):
        row = dict(row)
        img = row.pop('image')
        if img.shape[:2] != image_hw:
            img = cv2.resize(img, (image_hw[1], image_hw[0]))
        row['image'] = img
        row['label'] = np.int32(hash(row.pop('noun_id')) % 1000)
        return row

    return TransformSpec(fix_row,
                         edit_fields=[('image', np.uint8, image_hw + (3,), False),
                                      ('label', np.int32, (), False)],
                         removed_fields=['noun_id'])


def train(dataset_url, steps=50, batch_size=64, image_hw=(224, 224), lr=0.1,
          model_name='resnet50', decoded_cache_dir=None, hbm_cache=False,
          scan_steps=0, trace_path=None):
    mesh = make_mesh()
    sharding = data_parallel_sharding(mesh)
    # --trace: record every host-side span (host_batch/transform/device_put
    # from the loader, data_wait/step from the monitor) into a
    # chrome://tracing timeline — the per-event view of the same time the
    # stall report aggregates.
    from petastorm_tpu.benchmark import TraceRecorder
    tracer = TraceRecorder() if trace_path else None
    stateless = model_name == 'vit'
    if stateless:
        # ViT-S/16 on the same pipeline; no BatchNorm state, so batch_stats
        # stays an empty dict threaded through the shared step signature.
        model = ViT(num_classes=1000, patch_size=16, d_model=384,
                    num_heads=6, num_layers=12, d_ff=1536)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1,) + image_hw + (3,), jnp.float32))
        params, batch_stats = variables['params'], {}
    else:
        model = ResNet50(num_classes=1000)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1,) + image_hw + (3,), jnp.float32),
                               train=True)
        params, batch_stats = variables['params'], variables['batch_stats']
    tx = optax.sgd(lr, momentum=0.9)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, batch_stats, opt_state, images, labels, key):
        # Augmentation runs ON DEVICE (petastorm_tpu.jax.augment): the host
        # pool only decodes; flips/crops are bandwidth-trivial for the chip
        # and fuse into the first conv under XLA.
        k_crop, k_flip = jax.random.split(key)
        images = augment.random_crop(k_crop, images, images.shape[1:3],
                                     padding=4)
        images = augment.random_flip_left_right(k_flip, images)
        images = augment.normalize(images, dtype=jnp.float32)

        def loss_fn(p):
            if stateless:
                logits = model.apply({'params': p}, images)
                new_stats = batch_stats
            else:
                logits, mutated = model.apply(
                    {'params': p, 'batch_stats': batch_stats}, images,
                    train=True, mutable=['batch_stats'])
                new_stats = mutated['batch_stats']
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
            return loss, new_stats

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, new_opt = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), new_stats, new_opt, loss

    def scan_step(carry, batch):
        # Shared by both fused-consumption modes (scan_epochs over the HBM
        # cache, scan_batches over a stream): per-step augmentation
        # randomness rides in the carry.
        params, batch_stats, opt_state, key = carry
        key, sub = jax.random.split(key)
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, batch['image'], batch['label'],
            sub)
        return (params, batch_stats, opt_state, key), loss

    if hbm_cache:
        # Decoded shard fits HBM: cache it on device and run whole epochs
        # as ONE lax.scan dispatch each (DeviceInMemDataLoader.scan_epochs)
        # — zero per-step host work, so data stall is structurally ~0.
        # Per-step augmentation randomness rides in the carry.
        from petastorm_tpu.jax import DeviceInMemDataLoader
        with make_reader(dataset_url, schema_fields=['image', 'noun_id'],
                         transform_spec=make_transform(image_hw),
                         columnar_decode=True, num_epochs=1,
                         workers_count=8) as reader:
            loader = DeviceInMemDataLoader(reader, batch_size=batch_size,
                                           num_epochs=None, seed=17)
            carry = (params, batch_stats, opt_state, jax.random.PRNGKey(17))
            done = 0
            loss = None
            t0 = time.monotonic()
            for carry, losses in loader.scan_epochs(scan_step, carry):
                done += int(losses.shape[0])
                loss = losses[-1]
                if done >= steps:
                    break
        jax.block_until_ready(loss)
        dt = time.monotonic() - t0
        print('steps=%d loss=%.3f images/s=%.1f (hbm scan: no per-step host '
              'work)' % (done, float(loss), done * batch_size / dt))
        if tracer is not None:
            # Say it out loud rather than leaving the user waiting for a
            # file that never appears: the fused path has no host-side
            # spans to record.
            print('trace skipped: --hbm-cache folds whole epochs into '
                  'on-device scans (no host-side spans); no trace file '
                  'written to %s' % trace_path)
        return {'stall_pct': 0.0, 'steps': done}

    monitor = StallMonitor(warmup_steps=2, trace_recorder=tracer)
    done = 0
    t0 = time.monotonic()
    # Multi-epoch beyond-HBM datasets: --decoded-cache-dir spills decoded
    # tensors to local disk on epoch 0 and streams later epochs from the
    # mmap'd cache — no parquet/JPEG work after the first pass.  A cache
    # that is already complete needs NO reader at all (no background
    # decode pool).
    import contextlib
    from petastorm_tpu.jax import DiskCachedDataLoader
    cache_done = decoded_cache_dir and DiskCachedDataLoader.cache_complete(
        decoded_cache_dir)
    reader_cm = contextlib.nullcontext(None) if cache_done else make_reader(
        dataset_url, schema_fields=['image', 'noun_id'],
        transform_spec=make_transform(image_hw), columnar_decode=True,
        num_epochs=1 if decoded_cache_dir else None, workers_count=8)
    with reader_cm as reader:
        if decoded_cache_dir:
            loader = DiskCachedDataLoader(reader, batch_size=batch_size,
                                          decoded_cache_dir=decoded_cache_dir,
                                          num_epochs=None, sharding=sharding,
                                          trace_recorder=tracer)
        else:
            loader = DataLoader(reader, batch_size=batch_size,
                                sharding=sharding, trace_recorder=tracer)
        if scan_steps >= 1:
            # Fused streaming consumption: k host batches stack into one
            # device_put + one lax.scan dispatch (DataLoader.scan_batches)
            # — the countermeasure when per-dispatch latency, not decode,
            # is the stall (high-latency links, very fast steps).
            carry = (params, batch_stats, opt_state, jax.random.PRNGKey(17))
            loss = None
            for carry, losses in loader.scan_batches(
                    scan_step, carry, steps_per_call=scan_steps,
                    donate_carry=False):
                done += int(losses.shape[0])
                loss = losses[-1]
                if done >= steps:
                    break
            jax.block_until_ready(loss)
            dt = time.monotonic() - t0
            print('steps=%d loss=%.3f images/s=%.1f (scan_batches k=%d: '
                  'fused dispatch)'
                  % (done, float(loss), done * batch_size / dt, scan_steps))
            # scan_batches populates the same per-stage stats, so the
            # bottleneck advisor still gets a verdict (no StallMonitor —
            # per-batch wrapping doesn't apply to fused consumption).
            from petastorm_tpu.benchmark import diagnose, format_report
            print(format_report(diagnose(loader)))
            if tracer is not None:
                print('trace: %d spans -> %s (open in chrome://tracing)'
                      % (tracer.dump(trace_path), trace_path))
            return {'steps': done, 'stall_pct': None}
        step_key = jax.random.PRNGKey(17)
        for batch in monitor.wrap(loader):
            step_key, key = jax.random.split(step_key)
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, batch['image'], batch['label'],
                key)
            done += 1
            if done >= steps:
                break
    jax.block_until_ready(loss)
    dt = time.monotonic() - t0
    report = monitor.report()
    print('steps=%d loss=%.3f images/s=%.1f stall=%.2f%%'
          % (done, float(loss), done * batch_size / dt, report['stall_pct']))
    # Name the bottleneck regime and what to do about it (benchmark.diagnose)
    from petastorm_tpu.benchmark import diagnose, format_report
    print(format_report(diagnose(loader, monitor)))
    if tracer is not None:
        print('trace: %d spans -> %s (open in chrome://tracing)'
              % (tracer.dump(trace_path), trace_path))
    return report


if __name__ == '__main__':
    from petastorm_tpu.utils import ensure_jax_backend
    ensure_jax_backend()  # runs on any host; TPU when reachable
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', default='file:///tmp/imagenet_petastorm')
    parser.add_argument('--steps', type=int, default=50)
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--model', choices=['resnet50', 'vit'],
                        default='resnet50')
    parser.add_argument('--decoded-cache-dir', default=None,
                        help='decode once, stream later epochs from this '
                             'local decoded-tensor cache (multi-epoch '
                             'datasets bigger than HBM)')
    parser.add_argument('--hbm-cache', action='store_true',
                        help='decode once into device HBM and run each '
                             'epoch as one fused lax.scan dispatch '
                             '(single-device; shard per host on pods)')
    parser.add_argument('--scan-steps', type=int, default=0,
                        help='consume the streaming (or disk-cached) loader '
                             'via scan_batches: K steps per stacked '
                             'device_put + lax.scan dispatch — use when '
                             'dispatch/transport latency, not decode, is '
                             'the stall')
    parser.add_argument('--trace', default=None, metavar='PATH',
                        help='dump a chrome://tracing timeline of every '
                             'host-side span (loader stages + data_wait/'
                             'step) to PATH — per-event view of the stall '
                             'report (not applicable to --hbm-cache, whose '
                             'epochs have no host-side work to trace)')
    args = parser.parse_args()
    train(args.dataset_url, args.steps, args.batch_size,
          model_name=args.model, decoded_cache_dir=args.decoded_cache_dir,
          hbm_cache=args.hbm_cache, scan_steps=args.scan_steps,
          trace_path=args.trace)
