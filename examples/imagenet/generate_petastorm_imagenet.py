"""Write an ImageNet-shaped petastorm dataset (acceptance config #3).

Parity: reference ``examples/imagenet/generate_petastorm_imagenet.py`` —
same ImagenetSchema (id, text, image with ``CompressedImageCodec('png')``).
Reads a local ImageNet directory tree when given one; otherwise synthesizes
ImageNet-shaped data (no network egress in TPU sandboxes).
"""

# -- run from a source checkout without installation -------------------------
import os as _os, sys as _sys
_d = _os.path.dirname(_os.path.abspath(__file__))
while _d != _os.path.dirname(_d) and not _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')):
    _d = _os.path.dirname(_d)
if _os.path.isdir(_os.path.join(_d, 'petastorm_tpu')) and _d not in _sys.path:
    _sys.path.insert(0, _d)

import argparse
import os

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema('ImagenetSchema', [
    UnischemaField('noun_id', np.str_, (), ScalarCodec(np.str_), False),
    UnischemaField('text', np.str_, (), ScalarCodec(np.str_), False),
    UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False),
])


def synthetic_rows(rows_count, hw=(224, 224), seed=0):
    rng = np.random.default_rng(seed)
    base = np.linspace(0, 255, hw[0] * hw[1] * 3, dtype=np.float32).reshape(hw[0], hw[1], 3)
    for i in range(rows_count):
        jitter = rng.integers(0, 64, (8, 8, 3)).repeat(hw[0] // 8, 0).repeat(hw[1] // 8, 1)
        yield {
            'noun_id': 'n%08d' % (i % 1000),
            'text': 'synset %d' % (i % 1000),
            'image': np.clip(base + jitter, 0, 255).astype(np.uint8),
        }


def directory_rows(imagenet_dir):
    import cv2
    for noun_id in sorted(os.listdir(imagenet_dir)):
        class_dir = os.path.join(imagenet_dir, noun_id)
        if not os.path.isdir(class_dir):
            continue
        for name in sorted(os.listdir(class_dir)):
            img = cv2.imread(os.path.join(class_dir, name))
            if img is None:
                continue
            yield {'noun_id': noun_id, 'text': noun_id,
                   'image': cv2.cvtColor(img, cv2.COLOR_BGR2RGB)}


def generate_petastorm_imagenet(output_url, imagenet_dir=None, rows_count=1000,
                                rowgroup_size_mb=64):
    rows = directory_rows(imagenet_dir) if imagenet_dir else synthetic_rows(rows_count)
    with DatasetWriter(output_url, ImagenetSchema,
                       rowgroup_size_mb=rowgroup_size_mb) as writer:
        writer.write_many(rows)


if __name__ == '__main__':
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-o', '--output-url', default='file:///tmp/imagenet_petastorm')
    parser.add_argument('--imagenet-dir', default=None)
    parser.add_argument('-n', '--rows-count', type=int, default=1000)
    args = parser.parse_args()
    generate_petastorm_imagenet(args.output_url, args.imagenet_dir, args.rows_count)
    print('Wrote %s' % args.output_url)
