"""Cell-level codecs: encode numpy values into Parquet-storable cells and back.

Parity surface: reference ``petastorm/codecs.py :: DataframeColumnCodec,
ScalarCodec, NdarrayCodec, CompressedNdarrayCodec, CompressedImageCodec``.

Design differences from the reference (TPU-first build):

* The reference's canonical storage projection is a **Spark SQL type**
  (``spark_dtype()``), because its ETL path is Spark.  Ours is a **pyarrow
  DataType** (``arrow_dtype()``), because the ETL path is a pyarrow
  ``ParquetWriter`` (no Spark on TPU-VM hosts).  ``spark_dtype()`` is still
  provided, lazily, when pyspark is importable, so datasets can round-trip
  through either writer.
* Decode is the CPU hot-spot of the whole framework (it runs inside L2 reader
  workers, see ``petastorm_tpu/py_dict_reader_worker.py``).  All codecs decode
  straight to numpy arrays ready for zero-copy handoff to
  ``jax.device_put`` — C-contiguous, native byte order.
"""

import io
import zlib

import numpy as np
import pyarrow as pa

from petastorm_tpu.errors import DecodeFieldError

__all__ = [
    'DataframeColumnCodec',
    'ScalarCodec',
    'NdarrayCodec',
    'CompressedNdarrayCodec',
    'CompressedImageCodec',
    'resize_image_cell',
]


def resize_image_cell(arr, h, w):
    """THE semantic reference for every resize path (``ResizeImages`` row
    func, columnar fallback, ``decode_resized_into``): cv2.resize
    INTER_LINEAR, with cv2's dropped trailing 1-channel dim restored.  All
    python paths call this one function so they stay bit-identical; the
    native fused path (``pt_decode.cc``) approximates it — within a couple
    of LSB when it resizes a full decode (<=2x reductions, upscales,
    no-ops), but diverging by tens of LSB on high-frequency content when
    the DCT-scaled decode engages (>=4x reductions): scaled decode is
    anti-aliased where INTER_LINEAR downsampling aliases.  That is a
    quality difference (arguably in the native path's favor), not noise —
    documented so nobody expects cross-path bit-equality there."""
    import cv2
    if arr is None or not isinstance(arr, np.ndarray) \
            or arr.shape[:2] == (h, w):
        return arr
    out = cv2.resize(arr, (w, h), interpolation=cv2.INTER_LINEAR)
    if arr.ndim == 3 and arr.shape[2] == 1:
        out = out[:, :, None]  # cv2 drops the 1-channel dim
    return out


class DataframeColumnCodec(object):
    """Abstract codec: value <-> storable cell.

    Parity: ``petastorm/codecs.py :: DataframeColumnCodec`` (abstract
    ``encode/decode/spark_dtype``); we add ``arrow_dtype`` as the primary
    storage projection.
    """

    def encode(self, unischema_field, value):
        raise NotImplementedError()

    def decode(self, unischema_field, value):
        raise NotImplementedError()

    def decode_into(self, unischema_field, value, dst):
        """Decode straight into a preallocated array slice.

        The columnar decode plane preallocates one ``(N, *shape)`` batch array
        per row group and hands each codec a ``dst = batch[i]`` view, so the
        decoded value never exists as a separate allocation that must then be
        stacked (``np.stack`` is a full extra memory pass).  Codecs override
        this when the underlying library can write into caller memory
        (see ``CompressedImageCodec``); the default decodes then copies.
        """
        decoded = np.asarray(self.decode(unischema_field, value))
        if decoded.shape != dst.shape:
            # np.copyto would happily broadcast a (6,) cell over a (5, 6)
            # slice; a cell whose stored shape deviates from the schema must
            # surface as an error instead of silently flood-filling.
            raise DecodeFieldError(
                'Field %r cell has shape %r, schema expects %r'
                % (unischema_field.name, decoded.shape, dst.shape))
        np.copyto(dst, decoded, casting='same_kind')

    def arrow_dtype(self):
        """pyarrow storage type of the encoded cell."""
        raise NotImplementedError()

    def spark_dtype(self):
        """Spark SQL storage type (only available when pyspark is installed)."""
        raise NotImplementedError()

    def __eq__(self, other):
        # Exact type match: NdarrayCodec and CompressedNdarrayCodec produce
        # incompatible bytes and must never compare equal.
        return type(other) is type(self) and self.__dict__ == other.__dict__

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash((self.__class__.__name__, tuple(sorted(self.__dict__.items()))))


# -- scalar ------------------------------------------------------------------

_NUMPY_TO_ARROW = {
    np.dtype('bool'): pa.bool_(),
    np.dtype('int8'): pa.int8(),
    np.dtype('uint8'): pa.uint8(),
    np.dtype('int16'): pa.int16(),
    np.dtype('uint16'): pa.uint16(),
    np.dtype('int32'): pa.int32(),
    np.dtype('uint32'): pa.uint32(),
    np.dtype('int64'): pa.int64(),
    np.dtype('uint64'): pa.uint64(),
    np.dtype('float16'): pa.float16(),
    np.dtype('float32'): pa.float32(),
    np.dtype('float64'): pa.float64(),
}


def _arrow_type_for_numpy(np_dtype):
    np_dtype = np.dtype(np_dtype)
    if np_dtype in _NUMPY_TO_ARROW:
        return _NUMPY_TO_ARROW[np_dtype]
    if np_dtype.kind in ('U', 'S') or np_dtype == np.dtype(object):
        return pa.string()
    if np_dtype.kind == 'M':  # datetime64
        return pa.timestamp('ns')
    raise TypeError('No arrow mapping for numpy dtype %r' % (np_dtype,))


class ScalarCodec(DataframeColumnCodec):
    """Stores a scalar natively in its Parquet column.

    Parity: ``petastorm/codecs.py :: ScalarCodec``.  The reference's
    constructor takes a Spark SQL type instance; ours accepts any of a numpy
    dtype / dtype name, a ``pyarrow.DataType``, or (when pyspark is present) a
    Spark SQL type — all normalized to a pyarrow storage type.
    """

    def __init__(self, storage_type):
        self._arrow_type = self._normalize(storage_type)

    def __setstate__(self, state):
        # Accept pickles written by the reference implementation, whose
        # ScalarCodec state is {'_spark_type': <pyspark sql type>}.  Without
        # pyspark installed the type arrives as an _pyspark_stub instance
        # (etl.dataset_metadata._CompatUnpickler), which _normalize duck-types
        # the same way — real petastorm footers open on bare TPU-VM images.
        if '_arrow_type' not in state and '_spark_type' in state:
            state = {'_arrow_type': self._normalize(state['_spark_type'])}
        self.__dict__.update(state)

    @staticmethod
    def _normalize(storage_type):
        if isinstance(storage_type, pa.DataType):
            return storage_type
        # Spark SQL type instance (duck-typed so pyspark stays optional —
        # covers both real pyspark classes and the unpickle-time stubs from
        # etl.dataset_metadata._pyspark_stub)?
        type_name = type(storage_type).__name__
        _SPARK_TO_ARROW = {
            'BooleanType': pa.bool_(),
            'ByteType': pa.int8(),
            'ShortType': pa.int16(),
            'IntegerType': pa.int32(),
            'LongType': pa.int64(),
            'FloatType': pa.float32(),
            'DoubleType': pa.float64(),
            'StringType': pa.string(),
            'BinaryType': pa.binary(),
            'DateType': pa.date32(),
            'TimestampType': pa.timestamp('ns'),
        }
        if hasattr(storage_type, 'typeName'):
            if type_name in _SPARK_TO_ARROW:
                return _SPARK_TO_ARROW[type_name]
            if type_name == 'DecimalType':
                # Instance state carries precision/scale (spark defaults 10/0).
                return pa.decimal128(getattr(storage_type, 'precision', 10),
                                     getattr(storage_type, 'scale', 0))
        # numpy dtype or anything np.dtype() accepts
        return _arrow_type_for_numpy(storage_type)

    def encode(self, unischema_field, value):
        # Normalize 0-d arrays / numpy scalars to python scalars so pyarrow
        # builds a native column.
        if isinstance(value, np.ndarray):
            if value.ndim != 0:
                raise ValueError('ScalarCodec can only encode scalars; field %r got shape %r'
                                 % (unischema_field.name, value.shape))
            value = value.item()
        if isinstance(value, np.generic):
            value = value.item()
        return value

    def decode(self, unischema_field, value):
        dtype = np.dtype(unischema_field.numpy_dtype)
        if dtype.kind == 'S':
            return value if isinstance(value, bytes) else str(value).encode('utf-8')
        if dtype.kind == 'U':
            return value if isinstance(value, str) else str(value)
        if dtype == np.dtype(object):
            return value
        return dtype.type(value)

    def arrow_dtype(self):
        return self._arrow_type

    def spark_dtype(self):
        from pyspark.sql import types as sql_types  # optional dependency
        _ARROW_TO_SPARK = {
            pa.bool_(): sql_types.BooleanType(),
            pa.int8(): sql_types.ByteType(),
            pa.int16(): sql_types.ShortType(),
            pa.int32(): sql_types.IntegerType(),
            pa.int64(): sql_types.LongType(),
            pa.float32(): sql_types.FloatType(),
            pa.float64(): sql_types.DoubleType(),
            pa.string(): sql_types.StringType(),
        }
        if self._arrow_type not in _ARROW_TO_SPARK:
            raise TypeError('Arrow type %s has no Spark SQL equivalent; use the pyarrow '
                            'write path for this field' % (self._arrow_type,))
        return _ARROW_TO_SPARK[self._arrow_type]

    def __eq__(self, other):
        return isinstance(other, ScalarCodec) and self._arrow_type == other._arrow_type

    def __hash__(self):
        return hash(('ScalarCodec', str(self._arrow_type)))


# -- ndarray -----------------------------------------------------------------

class NdarrayCodec(DataframeColumnCodec):
    """numpy array <-> ``np.save`` bytes in a binary Parquet cell.

    Parity: ``petastorm/codecs.py :: NdarrayCodec``.
    """

    def encode(self, unischema_field, value):
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError('Field %r expects dtype %r, got %r'
                             % (unischema_field.name, expected, value.dtype))
        memfile = io.BytesIO()
        np.save(memfile, value)
        return memfile.getvalue()

    def decode(self, unischema_field, value):
        memfile = io.BytesIO(value)
        # allow_pickle=False: cells are untrusted input at read time.
        arr = np.load(memfile, allow_pickle=False)
        arr = np.ascontiguousarray(arr)
        expected = np.dtype(unischema_field.numpy_dtype)
        if arr.dtype != expected and arr.dtype.kind == 'V' \
                and arr.dtype.itemsize == expected.itemsize:
            # Extension dtypes (ml_dtypes.bfloat16 — THE TPU storage dtype)
            # ride through np.save as raw void bytes; the schema knows the
            # real dtype, so restore it (zero-copy view).
            arr = arr.view(expected)
        return arr

    def decode_batch_into(self, unischema_field, cells, dst):
        """Whole-column native path (.npy header validation + memcpy per
        cell, one GIL-free C call) — the delivery-plane hot spot for
        pre-decoded tensor datasets.  False -> caller's per-cell
        ``np.load`` fallback (extension dtypes, wildcard shapes)."""
        from petastorm_tpu import native
        return native.npy_copy_batch(cells, dst)

    def arrow_dtype(self):
        return pa.binary()

    def spark_dtype(self):
        from pyspark.sql import types as sql_types
        return sql_types.BinaryType()


class CompressedNdarrayCodec(NdarrayCodec):
    """``NdarrayCodec`` + zlib, for sparse/compressible tensors.

    Parity: ``petastorm/codecs.py :: CompressedNdarrayCodec``.
    """

    def encode(self, unischema_field, value):
        return zlib.compress(super(CompressedNdarrayCodec, self).encode(unischema_field, value))

    def decode(self, unischema_field, value):
        return super(CompressedNdarrayCodec, self).decode(unischema_field, zlib.decompress(value))

    def decode_batch_into(self, unischema_field, cells, dst):
        """Whole-column native inflate (C++ zlib + .npy unpack, one GIL-free
        call per row group).  False -> caller uses the per-cell path."""
        from petastorm_tpu import native
        return native.zlib_npy_decompress_batch(cells, dst)


# -- images ------------------------------------------------------------------

class CompressedImageCodec(DataframeColumnCodec):
    """PNG/JPEG-compressed image cells via OpenCV.

    Parity: ``petastorm/codecs.py :: CompressedImageCodec``.  Matches the
    reference's channel convention: 3-channel arrays are RGB in memory and are
    swapped to/from OpenCV's BGR at the codec boundary.  This is the per-cell
    CPU hot spot for image datasets; cv2 releases the GIL during
    imencode/imdecode so the thread pool scales.
    """

    def __init__(self, image_codec='png', quality=80):
        if image_codec not in ('png', 'jpeg', 'jpg'):
            raise ValueError('image_codec must be png or jpeg, got %r' % (image_codec,))
        self._image_codec = '.' + image_codec
        self._quality = int(quality)

    @property
    def image_codec(self):
        return self._image_codec[1:]

    @property
    def quality(self):
        return self._quality

    def encode(self, unischema_field, value):
        import cv2
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError('Field %r expects dtype %r, got %r'
                             % (unischema_field.name, expected, value.dtype))
        allowed = (np.uint8,) if self._image_codec in ('.jpg', '.jpeg') else (np.uint8, np.uint16)
        if value.dtype not in [np.dtype(d) for d in allowed]:
            raise ValueError('%s codec supports dtypes %s; field %r is %r (cv2 would silently '
                             'cast to uint8)' % (self.image_codec, [np.dtype(d).name for d in allowed],
                                                 unischema_field.name, value.dtype))
        if value.ndim == 3 and value.shape[2] == 3:
            value = value[:, :, ::-1]  # RGB -> BGR for cv2
        if self._image_codec == '.jpg' or self._image_codec == '.jpeg':
            params = [int(cv2.IMWRITE_JPEG_QUALITY), self._quality]
            ext = '.jpg'
        else:
            params = []
            ext = '.png'
        ok, encoded = cv2.imencode(ext, value, params)
        if not ok:
            raise ValueError('cv2.imencode failed for field %r' % (unischema_field.name,))
        return encoded.tobytes()

    @staticmethod
    def _imdecode(unischema_field, value):
        """BGR-ordered cv2 decode of one cell (shared by decode/decode_into).

        IMREAD_UNCHANGED unconditionally: ANYCOLOR caps at 3 channels and
        would silently drop the alpha plane of (H, W, 4) fields.
        """
        import cv2
        arr = cv2.imdecode(np.frombuffer(value, dtype=np.uint8), cv2.IMREAD_UNCHANGED)
        if arr is None:
            raise DecodeFieldError('cv2.imdecode failed for field %r' % (unischema_field.name,))
        return arr

    def decode(self, unischema_field, value):
        import cv2
        arr = self._imdecode(unischema_field, value)
        if arr.ndim == 3 and arr.shape[2] == 3:
            # cvtColor is a SIMD copy; much cheaper than materializing the
            # negative-stride view arr[:, :, ::-1] would cost downstream.
            arr = cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)
        shape = unischema_field.shape
        if (shape is not None and arr.ndim + 1 == len(shape) and shape[-1] == 1
                and arr.shape == tuple(shape[:-1])):
            # Grayscale decodes 2-D; a field declared (H, W, 1) must get the
            # declared rank on EVERY path (row, columnar-fallback, decode_into)
            # or batch shapes would depend on which path a row group took.
            arr = arr.reshape(shape)
        return np.ascontiguousarray(arr.astype(unischema_field.numpy_dtype, copy=False))

    def decode_batch_into(self, unischema_field, cells, dst):
        """Whole-column native image decode (C++ libjpeg/libpng straight to
        RGB/gray in the batch array: no BGR intermediate, no per-image
        python).  False -> caller uses the per-cell cv2 path."""
        from petastorm_tpu import native
        if self._image_codec in ('.jpg', '.jpeg'):
            return native.jpeg_decode_batch(cells, dst)
        if self._image_codec == '.png':
            return native.png_decode_batch(cells, dst)
        return False

    def decode_batch_into_resized(self, unischema_field, cells, dst):
        """Fused whole-column decode+resize: JPEGs of ANY source size land
        as exactly ``dst[i]``-shaped images.  Accuracy vs the cv2
        fallback: see :func:`resize_image_cell` (bilinear-only regimes
        agree within a couple of LSB; >=4x reductions use DCT-scaled
        decode, which is ANTI-ALIASED and diverges by tens of LSB on
        high-frequency content — a quality difference, not an error).
        False -> caller resizes per cell with cv2."""
        from petastorm_tpu import native
        if self._image_codec in ('.jpg', '.jpeg'):
            return native.jpeg_decode_resize_batch(cells, dst)
        if self._image_codec == '.png':
            return native.png_decode_resize_batch(cells, dst)
        return False

    def decode_resized_into(self, unischema_field, value, dst):
        """Per-cell fallback for the fused path: full decode +
        :func:`resize_image_cell` into ``dst`` — the semantic reference
        the native fused path approximates."""
        arr = resize_image_cell(self.decode(unischema_field, value),
                                dst.shape[0], dst.shape[1])
        if arr.ndim == 2 and dst.ndim == 3:
            arr = arr[:, :, None]
        elif arr.ndim == 3 and arr.shape[2] == 1 and dst.ndim == 2:
            # resize_image_cell restores a trailing 1-channel dim that a
            # 2-D dst row doesn't carry
            arr = arr[:, :, 0]
        np.copyto(dst, arr, casting='same_kind')

    def decode_into(self, unischema_field, value, dst):
        import cv2
        arr = self._imdecode(unischema_field, value)
        if arr.ndim == 3 and arr.shape[2] == 3:
            if arr.shape == dst.shape and arr.dtype == dst.dtype and dst.flags['C_CONTIGUOUS']:
                # Fused BGR->RGB + batch placement: one pass instead of
                # cvtColor-allocate + stack-copy.
                cv2.cvtColor(arr, cv2.COLOR_BGR2RGB, dst=dst)
                return
            arr = cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)
        if (arr.ndim + 1 == dst.ndim and dst.shape[-1] == 1
                and arr.shape == dst.shape[:-1]):
            arr = arr.reshape(dst.shape)  # grayscale (H, W) -> (H, W, 1) only
        if arr.shape != dst.shape:
            raise DecodeFieldError(
                'Field %r image decoded to shape %r, schema expects %r'
                % (unischema_field.name, arr.shape, dst.shape))
        np.copyto(dst, arr, casting='same_kind')

    def arrow_dtype(self):
        return pa.binary()

    def spark_dtype(self):
        from pyspark.sql import types as sql_types
        return sql_types.BinaryType()
