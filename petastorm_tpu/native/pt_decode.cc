// Native decode plane: batch JPEG -> RGB/grayscale directly into a
// preallocated (N, H, W, C) batch array.
//
// Why this exists (TPU-first rationale): the decode plane is the host-CPU
// hot spot of the whole framework (reference analog:
// petastorm/codecs.py :: CompressedImageCodec.decode, which goes through
// cv2.imdecode to BGR and then pays a full extra image pass converting to
// RGB).  libjpeg emits scanlines in any requested color space, so decoding
// straight to RGB into the caller's batch slice removes both the
// intermediate allocation and the conversion pass.  One C call decodes a
// whole row group's column, so worker threads spend the row group's decode
// window entirely outside the GIL.
//
// Exposed C ABI (consumed via ctypes from petastorm_tpu/native/__init__.py):
//   pt_jpeg_decode_batch(srcs, lens, n, dst, h, w, c) -> 0 on success, or
//     (index+1) of the first image that failed / had unexpected dims.
//   pt_zlib_npy_decompress_batch(srcs, lens, n, dst, cell_bytes,
//                                expected_hdr, expected_hdr_len) -> same
//     contract; each cell is zlib(np.save bytes) of a fixed-shape array
//     (CompressedNdarrayCodec).  The .npy header travels inside the
//     compressed stream, so it is parsed post-inflate; the header dict must
//     START WITH expected_hdr — the caller renders the exact
//     "{'descr': ..., 'fortran_order': False, 'shape': ...," prefix np.save
//     emits for the schema's dtype/shape (np.lib.format key order is fixed),
//     so Fortran-ordered, re-shaped, or foreign-dtype cells are rejected here
//     and handled by the python fallback instead of being raw-memcpy'd.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <new>

#include <jpeglib.h>
#include <png.h>
#include <zlib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

void emit_message(j_common_ptr, int) {}  // silence corrupt-stream warnings

// Decode one JPEG into dst (h*w*c, C-contiguous). Returns true on success
// with exact dimension match.
bool decode_one(const uint8_t* src, size_t len, uint8_t* dst,
                unsigned h, unsigned w, unsigned c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.emit_message = emit_message;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  // Strict channel match with the schema: libjpeg would happily expand
  // grayscale to RGB (or fold color to gray), but the cv2 fallback raises on
  // such cells — the two paths must agree, so reject and let python decide.
  if ((c == 1) != (cinfo.num_components == 1)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = (c == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_width != w || cinfo.output_height != h ||
      static_cast<unsigned>(cinfo.output_components) != c) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const size_t stride = static_cast<size_t>(w) * c;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Separable fixed-point bilinear resize, half-pixel-center convention (the
// same sampling grid cv2.resize INTER_LINEAR uses; rounding differs by a
// couple of LSB — the python cv2 fallback is the semantic reference, this
// is its fast approximation and is documented as such).  Two passes with a
// two-row cache: horizontal interpolation to 15-bit intermediates (7-bit
// weights), then vertical blend — all int32, no float in the hot loop.
struct ResizeScratch {
  int* xtap = nullptr;        // per output x: src index pair
  int* wx = nullptr;          // per output x: 7-bit right-tap weight
  int32_t* rows = nullptr;    // 2 cached h-interpolated rows
  int cached[2] = {-1, -1};   // src row indices currently in the cache
  unsigned dw = 0, ch = 0;
  bool ok = false;

  ResizeScratch(unsigned dw_, unsigned ch_) : dw(dw_), ch(ch_) {
    xtap = new (std::nothrow) int[dw * 2];
    wx = new (std::nothrow) int[dw];
    rows = new (std::nothrow) int32_t[2 * static_cast<size_t>(dw) * ch];
    ok = xtap != nullptr && wx != nullptr && rows != nullptr;
  }
  ~ResizeScratch() {
    delete[] xtap;
    delete[] wx;
    delete[] rows;
  }
};

void hinterp_row(const uint8_t* src_row, int32_t* out, const int* xtap,
                 const int* wx, unsigned dw, unsigned ch) {
  for (unsigned x = 0; x < dw; ++x) {
    const size_t o0 = static_cast<size_t>(xtap[2 * x]) * ch;
    const size_t o1 = static_cast<size_t>(xtap[2 * x + 1]) * ch;
    const int w1 = wx[x], w0 = 128 - w1;
    for (unsigned k = 0; k < ch; ++k) {
      out[x * ch + k] = w0 * src_row[o0 + k] + w1 * src_row[o1 + k];
    }
  }
}

void resize_bilinear(const uint8_t* src, unsigned sh, unsigned sw,
                     uint8_t* dst, unsigned dh, unsigned dw, unsigned ch,
                     ResizeScratch* rs) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, static_cast<size_t>(sh) * sw * ch);
    return;
  }
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (unsigned x = 0; x < dw; ++x) {
    float fx = (x + 0.5f) * sx - 0.5f;
    if (fx < 0) fx = 0;
    int ix = static_cast<int>(fx);
    if (ix > static_cast<int>(sw) - 2) ix = static_cast<int>(sw) - 2;
    if (ix < 0) ix = 0;
    rs->xtap[2 * x] = ix;
    rs->xtap[2 * x + 1] = (sw > 1) ? ix + 1 : ix;
    float frac = fx - ix;
    if (frac < 0) frac = 0;
    if (frac > 1) frac = 1;
    rs->wx[x] = static_cast<int>(frac * 128.0f + 0.5f);
  }
  rs->cached[0] = rs->cached[1] = -1;
  const size_t sstride = static_cast<size_t>(sw) * ch;
  const size_t rstride = static_cast<size_t>(dw) * ch;
  for (unsigned y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int iy = static_cast<int>(fy);
    if (iy > static_cast<int>(sh) - 2) iy = static_cast<int>(sh) - 2;
    if (iy < 0) iy = 0;
    const int iy1 = (sh > 1) ? iy + 1 : iy;
    float frac = fy - iy;
    if (frac < 0) frac = 0;
    if (frac > 1) frac = 1;
    const int wy1 = static_cast<int>(frac * 128.0f + 0.5f);
    const int wy0 = 128 - wy1;
    int32_t* r0;
    int32_t* r1;
    // Two-row cache: consecutive output rows share source rows on
    // upscale, and iy1 of row y is often iy of row y+1 on mild downscale.
    if (rs->cached[0] == iy) {
      r0 = rs->rows;
    } else if (rs->cached[1] == iy) {
      r0 = rs->rows + rstride;
    } else {
      r0 = (rs->cached[0] == iy1) ? rs->rows + rstride : rs->rows;
      hinterp_row(src + sstride * iy, r0, rs->xtap, rs->wx, dw, ch);
      rs->cached[(r0 == rs->rows) ? 0 : 1] = iy;
    }
    if (rs->cached[0] == iy1) {
      r1 = rs->rows;
    } else if (rs->cached[1] == iy1) {
      r1 = rs->rows + rstride;
    } else {
      r1 = (r0 == rs->rows) ? rs->rows + rstride : rs->rows;
      hinterp_row(src + sstride * iy1, r1, rs->xtap, rs->wx, dw, ch);
      rs->cached[(r1 == rs->rows) ? 0 : 1] = iy1;
    }
    uint8_t* out = dst + static_cast<size_t>(y) * rstride;
    for (size_t i = 0; i < rstride; ++i) {
      // 15-bit h-interp * 7-bit v-weight = 22 bits; +rounding >>14.
      out[i] = static_cast<uint8_t>(
          (wy0 * r0[i] + wy1 * r1[i] + (1 << 13)) >> 14);
    }
  }
}

// Grow-on-demand scratch buffer (shared by the fused resize paths).
// Returns false on allocation failure; existing contents are discarded.
bool grow_scratch(uint8_t** scratch, size_t* cap, size_t need) {
  if (need <= *cap) return true;
  delete[] *scratch;
  *scratch = new (std::nothrow) uint8_t[need];
  *cap = (*scratch == nullptr) ? 0 : need;
  return *scratch != nullptr;
}

// Shared PNG header validation: begin_read + the 8-bit/no-alpha/channel
// rejections BOTH png entry points must agree on, and the output format
// request.  On false the image has been freed and the cell must fall
// back to python.
bool png_begin_validated(png_image* image, const uint8_t* src, size_t len,
                         int c) {
  std::memset(image, 0, sizeof(*image));
  image->version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(image, src, len)) {
    png_image_free(image);
    return false;
  }
  const bool src_color = (image->format & PNG_FORMAT_FLAG_COLOR) != 0;
  const bool src_alpha = (image->format & PNG_FORMAT_FLAG_ALPHA) != 0;
  const bool src_16bit = (image->format & PNG_FORMAT_FLAG_LINEAR) != 0;
  if (src_16bit || src_alpha || src_color != (c == 3)) {
    png_image_free(image);
    return false;
  }
  image->format = (c == 1) ? PNG_FORMAT_GRAY : PNG_FORMAT_RGB;
  return true;
}

// Decode one JPEG of ANY source size at the coarsest DCT scale that still
// covers (target_h, target_w), into a growable scratch buffer.  DCT-domain
// scaling makes a 1/2-scale decode cost ~1/4 of a full decode — the fused
// decode+resize win for datasets stored larger than the training
// resolution (e.g. raw ImageNet ~500x375 -> 224x224).
bool decode_one_scaled(const uint8_t* src, size_t len, uint8_t** scratch,
                       size_t* scratch_cap, unsigned* sh, unsigned* sw,
                       unsigned target_h, unsigned target_w, unsigned c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.emit_message = emit_message;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  if ((c == 1) != (cinfo.num_components == 1)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = (c == 1) ? JCS_GRAYSCALE : JCS_RGB;
  // Deep power-of-two scales only (1/8, 1/4): measured on this class of
  // host, the reduced IDCTs are scalar while the full 8x8 path is SIMD, so
  // 1/2-scale decode is SLOWER than full-size decode and intermediate
  // ratios (e.g. 5/8 -> 10x10 IDCT) are worse still; only >=4x linear
  // reductions win.  Anything shallower decodes full-size and leans on
  // the fixed-point resize.
  unsigned num = 8;
  const unsigned pow2_scales[2] = {1u, 2u};
  for (unsigned k : pow2_scales) {
    const unsigned skw = (cinfo.image_width * k + 7) / 8;
    const unsigned skh = (cinfo.image_height * k + 7) / 8;
    if (skw >= target_w && skh >= target_h) {
      num = k;
      break;
    }
  }
  cinfo.scale_num = num;
  cinfo.scale_denom = 8;
  jpeg_start_decompress(&cinfo);
  *sh = cinfo.output_height;
  *sw = cinfo.output_width;
  const size_t need =
      static_cast<size_t>(*sh) * *sw * cinfo.output_components;
  if (!grow_scratch(scratch, scratch_cap, need)) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const size_t stride = static_cast<size_t>(*sw) * cinfo.output_components;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = *scratch + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

}  // namespace

extern "C" {

// Fused decode + resize: each JPEG (ANY source size) lands as an exactly
// (h, w, c) image in the caller's (N, H, W, C) batch.  DCT-scaled decode
// (coarsest 1/8-step scale covering the target) + separable bilinear.
// Same return contract as pt_jpeg_decode_batch.
int pt_jpeg_decode_resize_batch(const uint8_t** srcs, const size_t* lens,
                                int n, uint8_t* dst, int h, int w, int c) {
  const size_t img_bytes = static_cast<size_t>(h) * w * c;
  uint8_t* scratch = nullptr;
  size_t scratch_cap = 0;
  ResizeScratch rs(static_cast<unsigned>(w), static_cast<unsigned>(c));
  if (!rs.ok) return -1;
  int failed = 0;
  for (int i = 0; i < n; ++i) {
    unsigned sh = 0, sw = 0;
    if (!decode_one_scaled(srcs[i], lens[i], &scratch, &scratch_cap, &sh, &sw,
                           static_cast<unsigned>(h), static_cast<unsigned>(w),
                           static_cast<unsigned>(c))) {
      failed = i + 1;
      break;
    }
    resize_bilinear(scratch, sh, sw, dst + img_bytes * i,
                    static_cast<unsigned>(h), static_cast<unsigned>(w),
                    static_cast<unsigned>(c), &rs);
  }
  delete[] scratch;
  return failed;
}

int pt_jpeg_decode_batch(const uint8_t** srcs, const size_t* lens, int n,
                         uint8_t* dst, int h, int w, int c) {
  const size_t img_bytes = static_cast<size_t>(h) * w * c;
  for (int i = 0; i < n; ++i) {
    if (!decode_one(srcs[i], lens[i], dst + img_bytes * i,
                    static_cast<unsigned>(h), static_cast<unsigned>(w),
                    static_cast<unsigned>(c))) {
      return i + 1;
    }
  }
  return 0;
}

// Batch PNG -> grayscale/RGB decode via libpng's simplified API, straight
// into the caller's (N, H, W, C) uint8 batch slice (the PNG sibling of
// pt_jpeg_decode_batch; reference analog petastorm/codecs.py ::
// CompressedImageCodec.decode via cv2.imdecode + BGR->RGB pass).
// Rejections (caller falls back to cv2, keeping the two paths bit-identical):
//   * 16-bit sources (the simplified API would rescale; cv2 preserves raw
//     samples into uint16 — a different dtype entirely);
//   * channel-count mismatch with the schema (gray vs color vs alpha) —
//     libpng would happily convert, but the cv2 path errors, and the two
//     paths must agree.
int pt_png_decode_batch(const uint8_t** srcs, const size_t* lens, int n,
                        uint8_t* dst, int h, int w, int c) {
  const size_t img_bytes = static_cast<size_t>(h) * w * c;
  for (int i = 0; i < n; ++i) {
    png_image image;
    if (!png_begin_validated(&image, srcs[i], lens[i], c)) {
      return i + 1;
    }
    if (image.width != static_cast<png_uint_32>(w) ||
        image.height != static_cast<png_uint_32>(h)) {
      png_image_free(&image);
      return i + 1;
    }
    if (!png_image_finish_read(&image, nullptr, dst + img_bytes * i,
                               static_cast<png_int_32>(w * c), nullptr)) {
      png_image_free(&image);
      return i + 1;
    }
  }
  return 0;
}

// PNG sibling of pt_jpeg_decode_resize_batch: libpng has no scaled
// decode, so this is a full decode into scratch + the shared fixed-point
// bilinear — the point is keeping PNG columns on the fused zero-per-row
// columnar path, not decode savings.  Same rejections as
// pt_png_decode_batch (16-bit, alpha, channel mismatch).
int pt_png_decode_resize_batch(const uint8_t** srcs, const size_t* lens,
                               int n, uint8_t* dst, int h, int w, int c) {
  const size_t img_bytes = static_cast<size_t>(h) * w * c;
  uint8_t* scratch = nullptr;
  size_t scratch_cap = 0;
  ResizeScratch rs(static_cast<unsigned>(w), static_cast<unsigned>(c));
  if (!rs.ok) return -1;
  int failed = 0;
  for (int i = 0; i < n; ++i) {
    png_image image;
    if (!png_begin_validated(&image, srcs[i], lens[i], c)) {
      failed = i + 1;
      break;
    }
    const size_t need =
        static_cast<size_t>(image.height) * image.width * c;
    if (!grow_scratch(&scratch, &scratch_cap, need)) {
      png_image_free(&image);
      failed = -1;
      break;
    }
    const unsigned sh = image.height, sw = image.width;
    if (!png_image_finish_read(&image, nullptr, scratch,
                               static_cast<png_int_32>(sw * c), nullptr)) {
      png_image_free(&image);
      failed = i + 1;
      break;
    }
    resize_bilinear(scratch, sh, sw, dst + img_bytes * i,
                    static_cast<unsigned>(h), static_cast<unsigned>(w),
                    static_cast<unsigned>(c), &rs);
  }
  delete[] scratch;
  return failed;
}

int pt_zlib_npy_decompress_batch(const uint8_t** srcs, const size_t* lens,
                                 int n, uint8_t* dst, size_t cell_bytes,
                                 const char* expected_hdr,
                                 size_t expected_hdr_len) {
  // Scratch holds one inflated .npy: magic(6) + version(2) + header-len
  // field (<=4) + header (<=64KiB, 64-byte aligned in practice) + data.
  const size_t scratch_cap = cell_bytes + 65536 + 16;
  uint8_t* scratch = new (std::nothrow) uint8_t[scratch_cap];
  if (scratch == nullptr) return -1;
  int failed = 0;
  for (int i = 0; i < n; ++i) {
    uLongf out_len = static_cast<uLongf>(scratch_cap);
    int rc = uncompress(scratch, &out_len, srcs[i],
                        static_cast<uLong>(lens[i]));
    if (rc != Z_OK || out_len < 10 ||
        std::memcmp(scratch, "\x93NUMPY", 6) != 0) {
      failed = i + 1;
      break;
    }
    const uint8_t major = scratch[6];
    size_t hdr_off, hlen;
    if (major == 1) {
      hdr_off = 10;
      hlen = scratch[8] | (scratch[9] << 8);
    } else if (major == 2 || major == 3) {
      if (out_len < 12) { failed = i + 1; break; }
      hdr_off = 12;
      hlen = static_cast<size_t>(scratch[8]) |
             (static_cast<size_t>(scratch[9]) << 8) |
             (static_cast<size_t>(scratch[10]) << 16) |
             (static_cast<size_t>(scratch[11]) << 24);
    } else {
      failed = i + 1;
      break;
    }
    const size_t data_off = hdr_off + hlen;
    if (out_len != data_off + cell_bytes ||  // payload size mismatch
        hlen < expected_hdr_len ||           // header can't hold the prefix
        std::memcmp(scratch + hdr_off, expected_hdr, expected_hdr_len) != 0) {
      failed = i + 1;  // fortran_order / shape / dtype differs from schema
      break;
    }
    std::memcpy(dst + cell_bytes * i, scratch + data_off, cell_bytes);
  }
  delete[] scratch;
  return failed;
}

// Raw .npy sibling of pt_zlib_npy_decompress_batch: NdarrayCodec cells
// store np.save bytes UNCOMPRESSED, so the delivery-plane hot path for
// pre-decoded tensor datasets (the north-star streaming feed once JPEG
// is out of the loop) is header-validate + one memcpy per cell.  Doing
// the whole column in one GIL-free call replaces a python np.load
// (BytesIO + format dispatch + allocation) per cell.  Same contract and
// same expected-header prefix rejection as the zlib variant.
int pt_npy_copy_batch(const uint8_t** srcs, const size_t* lens, int n,
                      uint8_t* dst, size_t cell_bytes,
                      const char* expected_hdr, size_t expected_hdr_len) {
  for (int i = 0; i < n; ++i) {
    const uint8_t* p = srcs[i];
    const size_t len = lens[i];
    if (len < 10 || std::memcmp(p, "\x93NUMPY", 6) != 0) return i + 1;
    const uint8_t major = p[6];
    size_t hdr_off, hlen;
    if (major == 1) {
      hdr_off = 10;
      hlen = static_cast<size_t>(p[8]) | (static_cast<size_t>(p[9]) << 8);
    } else if (major == 2 || major == 3) {
      if (len < 12) return i + 1;
      hdr_off = 12;
      hlen = static_cast<size_t>(p[8]) | (static_cast<size_t>(p[9]) << 8) |
             (static_cast<size_t>(p[10]) << 16) |
             (static_cast<size_t>(p[11]) << 24);
    } else {
      return i + 1;
    }
    if (len < hdr_off + hlen) return i + 1;
    const size_t data_off = hdr_off + hlen;
    if (len != data_off + cell_bytes ||     // payload size mismatch
        hlen < expected_hdr_len ||          // header can't hold the prefix
        std::memcmp(p + hdr_off, expected_hdr, expected_hdr_len) != 0) {
      return i + 1;  // fortran_order / shape / dtype differs from schema
    }
    std::memcpy(dst + cell_bytes * i, p + data_off, cell_bytes);
  }
  return 0;
}

}  // extern "C"
