// Native decode plane: batch JPEG -> RGB/grayscale directly into a
// preallocated (N, H, W, C) batch array.
//
// Why this exists (TPU-first rationale): the decode plane is the host-CPU
// hot spot of the whole framework (reference analog:
// petastorm/codecs.py :: CompressedImageCodec.decode, which goes through
// cv2.imdecode to BGR and then pays a full extra image pass converting to
// RGB).  libjpeg emits scanlines in any requested color space, so decoding
// straight to RGB into the caller's batch slice removes both the
// intermediate allocation and the conversion pass.  One C call decodes a
// whole row group's column, so worker threads spend the row group's decode
// window entirely outside the GIL.
//
// Exposed C ABI (consumed via ctypes from petastorm_tpu/native/__init__.py):
//   pt_jpeg_decode_batch(srcs, lens, n, dst, h, w, c) -> 0 on success, or
//     (index+1) of the first image that failed / had unexpected dims.
//   pt_zlib_npy_decompress_batch(srcs, lens, n, dst, cell_bytes,
//                                expected_hdr, expected_hdr_len) -> same
//     contract; each cell is zlib(np.save bytes) of a fixed-shape array
//     (CompressedNdarrayCodec).  The .npy header travels inside the
//     compressed stream, so it is parsed post-inflate; the header dict must
//     START WITH expected_hdr — the caller renders the exact
//     "{'descr': ..., 'fortran_order': False, 'shape': ...," prefix np.save
//     emits for the schema's dtype/shape (np.lib.format key order is fixed),
//     so Fortran-ordered, re-shaped, or foreign-dtype cells are rejected here
//     and handled by the python fallback instead of being raw-memcpy'd.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <new>

#include <jpeglib.h>
#include <png.h>
#include <zlib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

void emit_message(j_common_ptr, int) {}  // silence corrupt-stream warnings

// Decode one JPEG into dst (h*w*c, C-contiguous). Returns true on success
// with exact dimension match.
bool decode_one(const uint8_t* src, size_t len, uint8_t* dst,
                unsigned h, unsigned w, unsigned c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.emit_message = emit_message;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(src),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  // Strict channel match with the schema: libjpeg would happily expand
  // grayscale to RGB (or fold color to gray), but the cv2 fallback raises on
  // such cells — the two paths must agree, so reject and let python decide.
  if ((c == 1) != (cinfo.num_components == 1)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = (c == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_width != w || cinfo.output_height != h ||
      static_cast<unsigned>(cinfo.output_components) != c) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  const size_t stride = static_cast<size_t>(w) * c;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

}  // namespace

extern "C" {

int pt_jpeg_decode_batch(const uint8_t** srcs, const size_t* lens, int n,
                         uint8_t* dst, int h, int w, int c) {
  const size_t img_bytes = static_cast<size_t>(h) * w * c;
  for (int i = 0; i < n; ++i) {
    if (!decode_one(srcs[i], lens[i], dst + img_bytes * i,
                    static_cast<unsigned>(h), static_cast<unsigned>(w),
                    static_cast<unsigned>(c))) {
      return i + 1;
    }
  }
  return 0;
}

// Batch PNG -> grayscale/RGB decode via libpng's simplified API, straight
// into the caller's (N, H, W, C) uint8 batch slice (the PNG sibling of
// pt_jpeg_decode_batch; reference analog petastorm/codecs.py ::
// CompressedImageCodec.decode via cv2.imdecode + BGR->RGB pass).
// Rejections (caller falls back to cv2, keeping the two paths bit-identical):
//   * 16-bit sources (the simplified API would rescale; cv2 preserves raw
//     samples into uint16 — a different dtype entirely);
//   * channel-count mismatch with the schema (gray vs color vs alpha) —
//     libpng would happily convert, but the cv2 path errors, and the two
//     paths must agree.
int pt_png_decode_batch(const uint8_t** srcs, const size_t* lens, int n,
                        uint8_t* dst, int h, int w, int c) {
  const size_t img_bytes = static_cast<size_t>(h) * w * c;
  for (int i = 0; i < n; ++i) {
    png_image image;
    std::memset(&image, 0, sizeof(image));
    image.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&image, srcs[i], lens[i])) {
      png_image_free(&image);
      return i + 1;
    }
    const bool src_color = (image.format & PNG_FORMAT_FLAG_COLOR) != 0;
    const bool src_alpha = (image.format & PNG_FORMAT_FLAG_ALPHA) != 0;
    const bool src_16bit = (image.format & PNG_FORMAT_FLAG_LINEAR) != 0;
    if (image.width != static_cast<png_uint_32>(w) ||
        image.height != static_cast<png_uint_32>(h) || src_16bit ||
        src_alpha || src_color != (c == 3)) {
      png_image_free(&image);
      return i + 1;
    }
    image.format = (c == 1) ? PNG_FORMAT_GRAY : PNG_FORMAT_RGB;
    if (!png_image_finish_read(&image, nullptr, dst + img_bytes * i,
                               static_cast<png_int_32>(w * c), nullptr)) {
      png_image_free(&image);
      return i + 1;
    }
  }
  return 0;
}

int pt_zlib_npy_decompress_batch(const uint8_t** srcs, const size_t* lens,
                                 int n, uint8_t* dst, size_t cell_bytes,
                                 const char* expected_hdr,
                                 size_t expected_hdr_len) {
  // Scratch holds one inflated .npy: magic(6) + version(2) + header-len
  // field (<=4) + header (<=64KiB, 64-byte aligned in practice) + data.
  const size_t scratch_cap = cell_bytes + 65536 + 16;
  uint8_t* scratch = new (std::nothrow) uint8_t[scratch_cap];
  if (scratch == nullptr) return -1;
  int failed = 0;
  for (int i = 0; i < n; ++i) {
    uLongf out_len = static_cast<uLongf>(scratch_cap);
    int rc = uncompress(scratch, &out_len, srcs[i],
                        static_cast<uLong>(lens[i]));
    if (rc != Z_OK || out_len < 10 ||
        std::memcmp(scratch, "\x93NUMPY", 6) != 0) {
      failed = i + 1;
      break;
    }
    const uint8_t major = scratch[6];
    size_t hdr_off, hlen;
    if (major == 1) {
      hdr_off = 10;
      hlen = scratch[8] | (scratch[9] << 8);
    } else if (major == 2 || major == 3) {
      if (out_len < 12) { failed = i + 1; break; }
      hdr_off = 12;
      hlen = static_cast<size_t>(scratch[8]) |
             (static_cast<size_t>(scratch[9]) << 8) |
             (static_cast<size_t>(scratch[10]) << 16) |
             (static_cast<size_t>(scratch[11]) << 24);
    } else {
      failed = i + 1;
      break;
    }
    const size_t data_off = hdr_off + hlen;
    if (out_len != data_off + cell_bytes ||  // payload size mismatch
        hlen < expected_hdr_len ||           // header can't hold the prefix
        std::memcmp(scratch + hdr_off, expected_hdr, expected_hdr_len) != 0) {
      failed = i + 1;  // fortran_order / shape / dtype differs from schema
      break;
    }
    std::memcpy(dst + cell_bytes * i, scratch + data_off, cell_bytes);
  }
  delete[] scratch;
  return failed;
}

}  // extern "C"
