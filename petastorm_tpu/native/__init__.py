"""ctypes bindings for the native decode plane (``pt_decode.cc``).

The shared library is compiled lazily on first import (g++ -O3, linked
against the system libjpeg/zlib) and cached next to the source; a stale or
failed build degrades gracefully — callers check :func:`get_lib` for ``None``
and fall back to the pure-python/cv2 codec paths, so the framework never
hard-requires the native component (same posture as the reference, whose
native speed all comes from optional third-party wheels — SURVEY.md §2.6).

Set ``PETASTORM_TPU_NO_NATIVE=1`` to disable the native path entirely.
"""

import ctypes
import logging
import os
import subprocess
from petastorm_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'pt_decode.cc')
_SO = os.path.join(_HERE, 'libpt_decode.so')

_lock = make_lock('native._lock')
_lib = None
_tried = False
_force_disabled = False


import contextlib


@contextlib.contextmanager
def disabled():
    """Force the pure-python/cv2 fallback paths while the context is active.

    Unlike ``PETASTORM_TPU_NO_NATIVE`` (checked once, at first load), this
    works after the library has already been loaded — benchmarks use it to
    run an honest no-native baseline leg in the same process."""
    global _force_disabled
    prev = _force_disabled
    _force_disabled = True
    try:
        yield
    finally:
        _force_disabled = prev


def _build():
    # Compile to a unique temp path and rename into place: os.rename is
    # atomic, so concurrent processes (ZeroMQ pool workers on a fresh
    # checkout) never dlopen a partially written ELF.
    tmp = '%s.%d.tmp' % (_SO, os.getpid())
    cmd = ['g++', '-O3', '-shared', '-fPIC', '-std=c++17',
           '-o', tmp, _SRC, '-ljpeg', '-lpng', '-lz']
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError('native build failed: %s' % proc.stderr[-2000:])
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):  # compile failure or timeout
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _load():
    lib = ctypes.CDLL(_SO)
    lib.pt_jpeg_decode_batch.restype = ctypes.c_int
    lib.pt_jpeg_decode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.pt_png_decode_batch.restype = ctypes.c_int
    lib.pt_png_decode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.pt_jpeg_decode_resize_batch.restype = ctypes.c_int
    lib.pt_jpeg_decode_resize_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.pt_png_decode_resize_batch.restype = ctypes.c_int
    lib.pt_png_decode_resize_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.pt_zlib_npy_decompress_batch.restype = ctypes.c_int
    lib.pt_zlib_npy_decompress_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    lib.pt_npy_copy_batch.restype = ctypes.c_int
    lib.pt_npy_copy_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    return lib


def get_lib():
    """The loaded native library, or None if unavailable/disabled."""
    global _lib, _tried
    if _force_disabled:
        return None
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        if os.environ.get('PETASTORM_TPU_NO_NATIVE'):
            _tried = True
            return None
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            _lib = _load()
        except Exception as e:  # noqa: BLE001 — any failure means "no native"
            logger.warning('Native decode library unavailable (%s); '
                           'falling back to cv2/python decode', e)
            _lib = None
        _tried = True
        return _lib


def _as_ptr_arrays(cells):
    """list[bytes] -> (char** array, size_t* array) borrowing the bytes."""
    n = len(cells)
    ptrs = (ctypes.c_char_p * n)(*cells)
    lens = (ctypes.c_size_t * n)(*[len(c) for c in cells])
    return ptrs, lens


def _arrow_ptr_arrays(column):
    """pyarrow binary (Chunked)Array -> (char**, size_t*, keepalive), borrowing
    the arrow buffers directly — no per-cell ``bytes`` copies, the marshalling
    win the ``to_pylist`` path can't have.  None when unsupported (nulls,
    non-binary type)."""
    import numpy as np
    import pyarrow as pa

    chunks = column.chunks if isinstance(column, pa.ChunkedArray) else [column]
    ptr_parts, len_parts = [], []
    for chunk in chunks:
        if chunk.null_count:
            return None
        if pa.types.is_binary(chunk.type):
            off_dtype = np.int32
        elif pa.types.is_large_binary(chunk.type):
            off_dtype = np.int64
        else:
            return None
        validity, offsets_buf, data_buf = chunk.buffers()
        # A sliced chunk shares its parent's buffers; chunk.offset shifts the
        # window into the offsets vector.
        offs = np.frombuffer(
            offsets_buf, dtype=off_dtype, count=len(chunk) + 1,
            offset=chunk.offset * np.dtype(off_dtype).itemsize).astype(np.uint64)
        ptr_parts.append(data_buf.address + offs[:-1])
        len_parts.append(np.diff(offs))
    ptrs = np.ascontiguousarray(np.concatenate(ptr_parts))
    lens = np.ascontiguousarray(np.concatenate(len_parts))
    return (ptrs.ctypes.data_as(ctypes.POINTER(ctypes.c_char_p)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_size_t)),
            (ptrs, lens, chunks))


def _marshal_cells(cells, expected_n=None):
    """Cells (list[bytes] OR pyarrow binary column) -> (char**, size_t*, n,
    keepalive); None if this cell container can't go native.

    ``expected_n``: the destination batch's row count — a cell count that
    differs must NOT reach the C loop (it would memcpy past the end of
    dst); mismatches return None so callers take the python fallback."""
    if expected_n is not None and len(cells) != expected_n:
        return None
    if isinstance(cells, (list, tuple)):
        if any(c is None for c in cells):
            return None
        ptrs, lens = _as_ptr_arrays(cells)
        return ptrs, lens, len(cells), cells
    try:
        import pyarrow as pa
        if isinstance(cells, (pa.Array, pa.ChunkedArray)):
            marshalled = _arrow_ptr_arrays(cells)
            if marshalled is None:
                return None
            ptrs, lens, keep = marshalled
            return ptrs, lens, len(cells), keep
    except ImportError:
        pass
    return None


def jpeg_decode_batch(cells, dst):
    """Decode list[bytes] JPEGs into a (N, H, W, 3)/(N, H, W) uint8 array.

    Returns True when the whole batch was decoded natively; False means the
    caller must use the fallback path (library missing, or some cell failed /
    had unexpected dimensions — dst contents are then undefined).
    """
    lib = get_lib()
    if lib is None or dst.dtype.kind != 'u' or dst.itemsize != 1 \
            or not dst.flags['C_CONTIGUOUS']:
        return False
    if dst.ndim == 4 and dst.shape[3] in (1, 3):
        h, w, c = dst.shape[1], dst.shape[2], dst.shape[3]
    elif dst.ndim == 3:
        h, w, c = dst.shape[1], dst.shape[2], 1
    else:
        return False
    marshalled = _marshal_cells(cells, expected_n=len(dst))
    if marshalled is None:
        return False
    ptrs, lens, n, keep = marshalled
    rc = lib.pt_jpeg_decode_batch(ptrs, lens, n,
                                  dst.ctypes.data_as(ctypes.c_void_p), h, w, c)
    del keep
    return rc == 0


def jpeg_decode_resize_batch(cells, dst):
    """Fused decode+resize: JPEGs of ANY source size -> the (N, H, W, 3) /
    (N, H, W) uint8 batch, decoded at the coarsest DCT scale covering
    (H, W) and bilinear-resampled to exactly (H, W).

    Sampling grid matches cv2.resize INTER_LINEAR (half-pixel centers).
    Accuracy vs the cv2 decode+resize fallback: a couple of LSB when the
    source decodes full-size (<=2x reductions, upscales, same-size); for
    >=4x reductions the DCT-scaled decode (what makes huge sources cheap)
    is anti-aliased where INTER_LINEAR aliases, so textured content
    diverges by tens of LSB — a documented quality difference, not noise.
    Same True/False contract as :func:`jpeg_decode_batch`.
    """
    lib = get_lib()
    if lib is None or dst.dtype.kind != 'u' or dst.itemsize != 1 \
            or not dst.flags['C_CONTIGUOUS']:
        return False
    if dst.ndim == 4 and dst.shape[3] in (1, 3):
        h, w, c = dst.shape[1], dst.shape[2], dst.shape[3]
    elif dst.ndim == 3:
        h, w, c = dst.shape[1], dst.shape[2], 1
    else:
        return False
    marshalled = _marshal_cells(cells, expected_n=len(dst))
    if marshalled is None:
        return False
    ptrs, lens, n, keep = marshalled
    rc = lib.pt_jpeg_decode_resize_batch(
        ptrs, lens, n, dst.ctypes.data_as(ctypes.c_void_p), h, w, c)
    del keep
    return rc == 0


def png_decode_resize_batch(cells, dst):
    """PNG sibling of :func:`jpeg_decode_resize_batch`: full decode (no
    scaled decode exists for PNG) + the same fixed-point bilinear into the
    (N, H, W, 3)/(N, H, W) batch — keeps PNG columns on the fused
    zero-per-row columnar path.  Same contract and same 8-bit/no-alpha
    rejections as :func:`png_decode_batch`."""
    lib = get_lib()
    if lib is None or dst.dtype.kind != 'u' or dst.itemsize != 1 \
            or not dst.flags['C_CONTIGUOUS']:
        return False
    if dst.ndim == 4 and dst.shape[3] in (1, 3):
        h, w, c = dst.shape[1], dst.shape[2], dst.shape[3]
    elif dst.ndim == 3:
        h, w, c = dst.shape[1], dst.shape[2], 1
    else:
        return False
    marshalled = _marshal_cells(cells, expected_n=len(dst))
    if marshalled is None:
        return False
    ptrs, lens, n, keep = marshalled
    rc = lib.pt_png_decode_resize_batch(
        ptrs, lens, n, dst.ctypes.data_as(ctypes.c_void_p), h, w, c)
    del keep
    return rc == 0


def png_decode_batch(cells, dst):
    """Decode list[bytes] 8-bit PNGs into a (N, H, W, 3)/(N, H, W[, 1]) uint8
    array.  Same contract as :func:`jpeg_decode_batch`: True = whole batch
    decoded natively; False = fall back (16-bit sources, channel mismatch,
    and anything else the C side rejects)."""
    lib = get_lib()
    if lib is None or dst.dtype.kind != 'u' or dst.itemsize != 1 \
            or not dst.flags['C_CONTIGUOUS']:
        return False
    if dst.ndim == 4 and dst.shape[3] in (1, 3):
        h, w, c = dst.shape[1], dst.shape[2], dst.shape[3]
    elif dst.ndim == 3:
        h, w, c = dst.shape[1], dst.shape[2], 1
    else:
        return False
    marshalled = _marshal_cells(cells, expected_n=len(dst))
    if marshalled is None:
        return False
    ptrs, lens, n, keep = marshalled
    rc = lib.pt_png_decode_batch(ptrs, lens, n,
                                 dst.ctypes.data_as(ctypes.c_void_p), h, w, c)
    del keep
    return rc == 0


def _npy_batch_call(fn_name, cells, dst):
    """Shared driver for the .npy column fast paths: render the exact
    header prefix np.save emits for dst's dtype/shape (np.lib.format's
    key order is fixed, so prefix match is exact), marshal the cells,
    and run one GIL-free C call over the whole column.  Fortran-ordered /
    reshaped / foreign-dtype cells are rejected natively and handled by
    the caller's ``np.load`` fallback.  True on full success."""
    lib = get_lib()
    if lib is None or not dst.flags['C_CONTIGUOUS'] or dst.dtype.hasobject:
        return False
    cell_bytes = dst[0].nbytes if len(dst) else 0
    if cell_bytes == 0:
        return False
    expected = "{'descr': %r, 'fortran_order': False, 'shape': %r," \
        % (dst.dtype.str, tuple(dst.shape[1:]))
    expected = expected.encode('latin1')
    marshalled = _marshal_cells(cells, expected_n=len(dst))
    if marshalled is None:
        return False
    ptrs, lens, n, keep = marshalled
    rc = getattr(lib, fn_name)(
        ptrs, lens, n, dst.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(cell_bytes), expected, ctypes.c_size_t(len(expected)))
    del keep
    return rc == 0


def zlib_npy_decompress_batch(cells, dst):
    """Inflate+unpack list[bytes] zlib(.npy) cells into a (N, ...) array
    (CompressedNdarrayCodec column); see :func:`_npy_batch_call`."""
    return _npy_batch_call('pt_zlib_npy_decompress_batch', cells, dst)


def npy_copy_batch(cells, dst):
    """Validate+copy list[bytes] raw .npy cells into a (N, ...) array
    (NdarrayCodec column — the pre-decoded-tensor delivery plane): one
    header check + memcpy per cell, whole column per GIL-free call,
    replacing a python ``np.load`` per cell; see :func:`_npy_batch_call`."""
    return _npy_batch_call('pt_npy_copy_batch', cells, dst)
