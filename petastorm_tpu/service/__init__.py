"""Disaggregated data-loading service: decode on fleet hosts, train on TPUs.

Round-5 evidence (``BENCH_r05.json``) put the framework in the
delivery-bound regime: one host's decode/collate plane cannot feed the
chips (~95% stall).  This subsystem scales the decode plane horizontally
and independently of the training hosts — the architecture of tf.data's
data service (arxiv 2101.12127) realized over this repo's own reader/pool
machinery:

* :class:`~petastorm_tpu.service.dispatcher.Dispatcher` — control plane:
  partitions the row-group list into splits, leases them to workers,
  reassigns on lease expiry (worker death).
* :class:`~petastorm_tpu.service.worker.Worker` — decode plane: wraps the
  existing readers over each leased split and streams serialized batches
  (Arrow IPC / pickle, the ProcessPool wire formats) under credit-based
  backpressure.
* :class:`~petastorm_tpu.service.client.ServiceDataLoader` — delivery
  plane: a drop-in ``petastorm_tpu.jax.DataLoader`` peer with the same
  sharding default (``jax.process_index()``) and resume-token contract,
  committing whole splits exactly once.
* ``petastorm_tpu.service.cluster`` — the cluster cache tier (ISSUE
  10): cache-affinity lease routing, remote HIT serving, and peer fill
  over the epoch-cache plane's content-fingerprint digests (on by
  default with ``cache_plane=True``; kill switch
  ``PETASTORM_TPU_NO_CLUSTER_CACHE=1``).
* ``petastorm_tpu.service.ledger`` — the durable dispatcher ledger
  (ISSUE 15): crash-safe snapshot/restore of split states, attempt
  counters, and the cache directory (``ServiceConfig(ledger_path=)``),
  with held-claim reconciliation so a dispatcher restart resumes the
  epoch instead of re-decoding the world.  Workers drain gracefully on
  SIGTERM / the ``drain`` RPC, and ``petastorm-tpu-chaos``
  (``test_util/chaos.py``) is the scenario matrix proving digest +
  exactly-once + zero residue under injected faults.
* ``petastorm_tpu.service.tenancy`` — the multi-tenant serving tier
  (ISSUE 16): several consumers with distinct datasets/configs share
  one worker fleet.  Co-tenant jobs register at runtime
  (:func:`~petastorm_tpu.service.client.register_tenant_job`, consumed
  with ``ServiceDataLoader(tenant=...)``), lease grants are
  weighted-deficit-round-robin fair across tenants (composing with the
  cache-affinity split pick), admission is bounded
  (``max_tenant_jobs``, structured ``retry_after_s`` refusals), and
  per-tenant shm/cache byte quotas degrade — never stall — the
  over-budget tenant.
* ``petastorm_tpu.service.autoscaler`` — the closed-loop fleet
  autoscaler (ISSUE 16): an in-dispatcher tick controller
  (``ServiceConfig(autoscale=True)``) that scales out on sustained
  lease starvation through a pluggable ``WorkerLauncher`` and scales in
  through the graceful drain path (least cache-coverage victim), damped
  by cooldown/step/min-max bounds; kill switch
  ``PETASTORM_TPU_NO_AUTOSCALE=1``.

Console entry point: ``petastorm-tpu-data-service`` (see
``petastorm_tpu/service/cli.py``).
"""

from petastorm_tpu.service.client import (ServiceDataLoader,  # noqa: F401
                                          ServiceReader,
                                          register_tenant_job)
from petastorm_tpu.service.config import ServiceConfig  # noqa: F401
from petastorm_tpu.service.dispatcher import Dispatcher  # noqa: F401
from petastorm_tpu.service.worker import Worker  # noqa: F401
