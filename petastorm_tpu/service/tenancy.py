"""Multi-tenant serving tier: shared fleets, fair shares, quotas (ISSUE 16).

The data service ran one fleet per job; the tf.data-service design this
subsystem reproduces (arxiv 2101.12127) serves N concurrent jobs over
ONE worker fleet.  This module holds the tenant model the dispatcher
wires in:

* :class:`TenantJob` — one registered job: a tenant id, a fair-share
  weight, the job's :class:`~petastorm_tpu.service.config.ServiceConfig`
  -derived ``job_info`` dict, and its slice of the GLOBAL split-id
  space.  Split ids stay globally unique (tenant N's splits start at
  ``split_base``), so every existing split-addressed RPC — ``complete``,
  ``release``, ``mark_consumed``, heartbeat ``held`` claims — works
  unchanged across tenants.
* :class:`TenantRegistry` — the ordered job table with admission
  control: at most ``max_jobs`` concurrent jobs; past the cap,
  registration is refused with ``retry_after_s`` so clients
  queue-with-backoff instead of erroring out.
* :class:`TenantScheduler` — weighted deficit round-robin (WDRR) over
  tenants' pending splits.  Per lease grant, every tenant with eligible
  pending work accrues credit proportional to its weight share; the
  highest-deficit tenant wins and pays 1.0.  With one tenant the
  schedule degenerates to "always that tenant" — bit-identical to the
  single-tenant dispatcher.  The scheduler only picks *which tenant*;
  PR 10's cache-affinity scan still picks *which split* within it.
* :class:`QuotaLedger` — per-tenant byte budgets for the shm arena and
  the cache plane.  Enforcement is at publish/admission with the
  existing degrade-to-direct-path semantics: an over-quota tenant's
  chunks take the byte path (shm) or skip the plane (cache) — never a
  stall, never an error.

Nothing here owns a thread or a socket; the dispatcher calls in under
its own lock, workers consult the quota ledger on their event loop.
"""

import json
import logging
import warnings

from petastorm_tpu.telemetry import decisions as _decisions
from petastorm_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)

__all__ = ['DEFAULT_TENANT', 'TenantJob', 'TenantRegistry',
           'TenantScheduler', 'QuotaLedger', 'config_to_jsonable',
           'config_from_jsonable']

#: The tenant every pre-ISSUE-16 client, worker, and ledger implicitly
#: belongs to.  A bare (tenant-less) subscribe/job RPC maps here, which
#: is what keeps the single-tenant wire protocol bit-compatible.
DEFAULT_TENANT = 'default'

#: Registration refusals past the admission cap carry this retry hint;
#: ``register_tenant_job`` (client.py) sleeps a jittered multiple of it.
ADMISSION_RETRY_S = 1.0

#: Deficit counters are clamped to ±this many grants of credit so a
#: tenant that sat starved-by-choice (no pending work) for an hour
#: cannot monopolize the fleet for the next hour (bounded burst — the
#: classic DRR quantum-clamp).
_DEFICIT_CLAMP = 8.0


def config_to_jsonable(config_kwargs):
    """A JSON-safe copy of a ServiceConfig kwargs dict for the ledger.

    ``reader_kwargs`` may carry non-JSON values (callables, numpy
    scalars); those entries are dropped WITH a warning rather than
    poisoning the whole snapshot — a restored job re-resolves its
    reader the same way a fresh registration would.
    """
    out = {}
    for key, value in dict(config_kwargs).items():
        try:
            json.dumps(value)
        except (TypeError, ValueError):
            if key == 'reader_kwargs' and isinstance(value, dict):
                kept = {}
                for rk, rv in value.items():
                    try:
                        json.dumps(rv)
                        kept[rk] = rv
                    except (TypeError, ValueError):
                        warnings.warn(
                            'tenant config reader_kwargs[%r] is not '
                            'JSON-serializable; dropped from the ledger '
                            'snapshot (restored jobs re-resolve it)' % rk)
                out[key] = kept
            else:
                warnings.warn(
                    'tenant config field %r is not JSON-serializable; '
                    'dropped from the ledger snapshot' % key)
        else:
            out[key] = value
    return out


def config_from_jsonable(data):
    """Rebuild the ServiceConfig kwargs dict a ledger snapshot stored."""
    return dict(data or {})


class TenantJob(object):
    """One registered job: identity, weight, config, split slice.

    ``pending`` is the tenant's OWN deque of
    :class:`~petastorm_tpu.service.dispatcher.Split` objects — the
    dispatcher's former single ``_pending`` deque, sharded per tenant so
    the scheduler can pick a tenant before the affinity scan picks a
    split.  ``grants`` counts lease grants (the per-tenant rollup and
    the tenant-starved regime read its windowed delta).
    """

    __slots__ = ('tenant', 'weight', 'config', 'job_info', 'split_base',
                 'num_splits', 'num_pieces', 'pending', 'grants',
                 'rows_delivered', 'registered_t')

    def __init__(self, tenant, weight, config, job_info, split_base,
                 num_splits, num_pieces=0, registered_t=0.0):
        self.tenant = tenant
        self.weight = float(weight)
        self.config = config
        self.job_info = job_info
        self.split_base = int(split_base)
        self.num_splits = int(num_splits)
        self.num_pieces = int(num_pieces)
        self.pending = None       # deque[Split]; the dispatcher owns it
        self.grants = 0
        self.rows_delivered = 0
        self.registered_t = registered_t

    def describe(self):
        return {'tenant': self.tenant, 'weight': self.weight,
                'split_base': self.split_base,
                'num_splits': self.num_splits,
                'grants': self.grants}


class TenantRegistry(object):
    """Ordered tenant-job table with bounded admission.

    Insertion order is preserved (``dict`` semantics) so the WDRR
    tie-break — and therefore the whole schedule — is deterministic.
    """

    def __init__(self, max_jobs=8):
        self.max_jobs = int(max_jobs)
        self._jobs = {}

    def __len__(self):
        return len(self._jobs)

    def __contains__(self, tenant):
        return tenant in self._jobs

    def get(self, tenant):
        return self._jobs.get(tenant)

    def jobs(self):
        """Registered jobs, registration order."""
        return list(self._jobs.values())

    def tenants(self):
        return list(self._jobs)

    def admit(self, job):
        """Admit ``job`` or return a refusal dict (never raises).

        A refusal carries ``retry_after_s`` so the client can
        queue-with-backoff; the cap counts CONCURRENT jobs, so a
        completed/retired job frees a slot.
        """
        if job.tenant in self._jobs:
            return {'error': 'tenant %r is already registered '
                             '(one job per tenant id)' % job.tenant}
        if len(self._jobs) >= self.max_jobs:
            return {'error': 'admission refused: %d concurrent tenant '
                             'job(s) is the cap (max_tenant_jobs=%d)'
                             % (len(self._jobs), self.max_jobs),
                    'retry_after_s': ADMISSION_RETRY_S}
        self._jobs[job.tenant] = job
        return None

    def evict(self, tenant):
        return self._jobs.pop(tenant, None)


class TenantScheduler(object):
    """Weighted deficit round-robin over tenants.

    ``pick(eligible)`` is called once per lease grant with the tenants
    that currently have grantable pending work.  Every eligible tenant
    accrues ``weight / sum(weights)`` of credit; the highest-deficit
    one wins and is debited the full grant (1.0).  Over a long run each
    tenant's grant share converges to its weight share of whatever set
    was jointly eligible — the fluid fair-share schedule, quantized to
    whole splits.  Deficits are clamped so an absence does not bank an
    unbounded burst.
    """

    def __init__(self):
        self._deficit = {}
        # Decision journal (ISSUE 20): set by the dispatcher to its
        # ledger-persisted journal; None = the process journal.
        self.decisions = None

    def pick(self, eligible):
        """Choose one tenant id from ``eligible`` (ordered sequence).

        Deterministic: ties break toward the earliest-registered
        eligible tenant.  Returns None on an empty set.
        """
        eligible = [t for t in eligible]
        if not eligible:
            return None
        if len(eligible) == 1:
            # Single-tenant fast path: no deficit bookkeeping at all, so
            # the pre-tenancy dispatcher schedule is reproduced exactly
            # (and nothing is journaled — with one eligible tenant there
            # is no alternative, hence no decision to explain).
            return eligible[0].tenant
        jobs = eligible
        # Pre-accrual snapshot: the WDRR inputs the replay cross-check
        # re-runs to reproduce the winner.
        table = [{'tenant': j.tenant, 'weight': j.weight,
                  'deficit': self._deficit.get(j.tenant, 0.0)}
                 for j in jobs]
        total = sum(j.weight for j in jobs) or float(len(jobs))
        best, best_deficit = None, None
        for job in jobs:
            share = (job.weight / total) if total else (1.0 / len(jobs))
            deficit = self._deficit.get(job.tenant, 0.0) + share
            deficit = max(-_DEFICIT_CLAMP, min(_DEFICIT_CLAMP, deficit))
            self._deficit[job.tenant] = deficit
            if best is None or deficit > best_deficit:
                best, best_deficit = job, deficit
        self._deficit[best.tenant] = best_deficit - 1.0
        _decisions.record_decision(
            'tenant_sched', 'pick', 'wdrr_deficit',
            {'eligible': table, 'deficit_clamp': _DEFICIT_CLAMP},
            tenant=best.tenant, journal=self.decisions)
        return best.tenant

    def refund(self, tenant):
        """Undo one grant's debit: the picked tenant yielded no grant
        (all its pending splits were affinity-deferred), so the lease
        went elsewhere and the tenant keeps its credit."""
        if tenant in self._deficit:
            self._deficit[tenant] = min(
                _DEFICIT_CLAMP, self._deficit[tenant] + 1.0)
            _decisions.record_decision(
                'tenant_sched', 'refund', 'wdrr_refund',
                {'deficit': self._deficit[tenant]},
                tenant=tenant, journal=self.decisions)

    def forget(self, tenant):
        self._deficit.pop(tenant, None)

    def deficits(self):
        return dict(self._deficit)


class QuotaLedger(object):  # ptlint: disable=pickle-unsafe-attrs — lives on one process's dispatcher/worker event loop; snapshot() (a plain dict) is what crosses boundaries
    """Per-tenant outstanding-byte accounting for one resource plane.

    Thread-safe (the worker event loop charges at publish while the
    client-facing section refunds at ack).  ``None`` budget = unlimited
    for that tenant; a charge that would cross the budget is REFUSED
    (caller degrades to the direct path) — outstanding bytes never
    exceed the budget, and refusal is the only enforcement, so no path
    through here can stall.
    """

    def __init__(self, default_budget=None, label=None):
        self._lock = make_lock('service.tenancy.QuotaLedger._lock')
        self._default = default_budget
        self._budgets = {}
        self._used = {}
        self.refusals = 0
        #: Which resource plane this ledger guards ('shm'/'cache') — the
        #: decision journal names it so a refusal says what degraded.
        self.label = label

    def set_budget(self, tenant, budget_bytes):
        with self._lock:
            self._budgets[tenant] = budget_bytes

    def budget(self, tenant):
        with self._lock:
            return self._budgets.get(tenant, self._default)

    def used(self, tenant):
        with self._lock:
            return self._used.get(tenant, 0)

    def charge(self, tenant, nbytes):
        """True and charge if within budget; False (refused) otherwise."""
        nbytes = int(nbytes)
        with self._lock:
            budget = self._budgets.get(tenant, self._default)
            used = self._used.get(tenant, 0)
            if budget is not None and used + nbytes > budget:
                self.refusals += 1
                refused = True
            else:
                self._used[tenant] = used + nbytes
                refused = False
        if refused:
            # A quota refusal is a first-class suppressed non-action:
            # the tenant degraded to the direct path and THIS record is
            # the only place that says why.  Journaled outside the lock.
            _decisions.record_decision(
                'tenant_sched', 'quota_refused', 'quota_budget',
                {'nbytes': nbytes, 'used': used, 'budget': budget,
                 'plane': self.label},
                suppressed=True, tenant=tenant)
            return False
        return True

    def refund(self, tenant, nbytes):
        with self._lock:
            used = self._used.get(tenant, 0) - int(nbytes)
            self._used[tenant] = max(0, used)

    def snapshot(self):
        with self._lock:
            return {'used': dict(self._used),
                    'budgets': dict(self._budgets),
                    'refusals': self.refusals}
