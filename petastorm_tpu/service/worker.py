"""Decode worker: leases splits, decodes them, streams batches to clients.

A worker is a thin shell around the existing reader machinery: each
leased split becomes a short-lived ``make_reader(columnar_decode=True)``
/ ``make_batch_reader`` over exactly that split's row groups
(``piece_indices=``), so the L2–L4 decode plane (pools, codecs, retries,
predicates, transform specs) runs unchanged — just on a different machine
than the accelerators.

Threads:

* the **event loop** owns every ZeroMQ socket: a ROUTER data socket that
  clients subscribe to, and a REQ control socket to the dispatcher
  (register / lease / heartbeat / complete).  Heartbeats renew all held
  leases; losing them (process death) is the failure signal the
  dispatcher acts on.
* the **decode thread** turns split descriptions into serialized chunks
  (Arrow IPC via ``reader_impl/arrow_table_serializer.py`` when the
  chunk is a flat table, pickle otherwise — the same dual framing the
  ProcessPool wire uses) through a bounded queue, which is what pauses
  decode when clients stop granting credits.  Consumers that proved
  same-host residence (a ``/dev/shm`` probe named in their subscribe —
  see ``workers_pool/shm_plane.py``) instead get **shm descriptors**:
  the chunk's columns are placed in a shared-memory segment and only
  ``(segment, offset, shape, dtype)`` metadata rides the socket, with
  transparent per-chunk fallback to the byte path (full arena, tiny
  chunk, cross-host consumer).

Delivery is credit-based: each subscriber grants a chunk budget and
replenishes it as it pulls chunks off its socket; ``end``-of-split
markers ride for free.  A split counts as done only after the owning
client ACKS the complete split — only then does the worker report
``complete`` to the dispatcher.  A worker killed at ANY point before the
ack therefore leaves the split leased, the lease expires, and the split
is reassigned: at-least-once streaming, which the client's whole-split
dedupe turns into exactly-once delivery.
"""

import logging
import os
import pickle
import queue
import threading
import time
import traceback

import numpy as np

from petastorm_tpu.errors import ServiceError, ServiceRpcTimeoutError
from petastorm_tpu.service import tenancy
from petastorm_tpu.telemetry import MetricsRegistry, provenance
from petastorm_tpu.test_util import chaos
from petastorm_tpu.utils import backoff

logger = logging.getLogger(__name__)

#: Per-split span-list bound shipped on the ``end`` header: enough for
#: every chunk of a sane split (serialize + shm publish + cache fills),
#: small enough that a pathological split can't bloat the control frames.
_MAX_SPANS_PER_SPLIT = 2048

_DEFAULT_RPC_TIMEOUT_S = 20.0

#: Zero baseline for per-split cache-outcome classification: a per-split
#: plane instance's lifetime totals ARE the split's delta.
_ZERO_CACHE = {'cache_hits': 0, 'cache_ram_hits': 0, 'cache_misses': 0,
               'cache_degraded': 0}


class _Rpc(object):  # ptlint: disable=pickle-unsafe-attrs — one per owning thread; sockets are rebuilt, never shipped
    """REQ-socket RPC client with timeout + socket recycling.

    A REQ socket wedges in send-state when a reply never comes; on
    timeout the socket is rebuilt so the caller can simply retry."""

    def __init__(self, context, addr, timeout_s=_DEFAULT_RPC_TIMEOUT_S):
        import zmq
        self._zmq = zmq
        self._context = context
        self._addr = addr
        self._timeout_s = timeout_s
        self._socket = None
        self._connect()

    def _connect(self):
        self._socket = self._context.socket(self._zmq.REQ)
        self._socket.setsockopt(self._zmq.LINGER, 0)
        self._socket.connect(self._addr)

    def call(self, request, timeout_s=None, raw=False):
        """``raw=True`` returns error replies instead of raising — for
        callers that read structured refusals (e.g. an admission
        refusal's ``retry_after_s``)."""
        from petastorm_tpu.errors import ServiceError
        timeout_s = self._timeout_s if timeout_s is None else timeout_s
        # Chaos seam (ISSUE 15): a dropped control-plane request
        # surfaces exactly what a lost request surfaces — a timeout on
        # a recycled socket — without waiting the full window (the
        # caller's retry/backoff path is what the fault exercises).
        if chaos.inject('rpc.request', op=request.get('op')) == 'drop':
            self._socket.close(0)
            self._connect()
            raise ServiceRpcTimeoutError(
                'chaos: dropped %r to %s' % (request.get('op'),
                                             self._addr))
        self._socket.send(pickle.dumps(request, protocol=4))
        if not self._socket.poll(int(timeout_s * 1000)):
            self._socket.close(0)
            self._connect()
            raise ServiceRpcTimeoutError(
                'no reply from %s to %r within %.1fs'
                % (self._addr, request.get('op'), timeout_s))
        reply = pickle.loads(self._socket.recv())
        if not raw and isinstance(reply, dict) and reply.get('error'):
            raise ServiceError('%s rejected %r: %s'
                               % (self._addr, request.get('op'),
                                  reply['error']))
        return reply

    def close(self):
        if self._socket is not None:
            self._socket.close(0)
            self._socket = None


def serialize_chunk(chunk):
    """dict-of-arrays -> (tag, payload): Arrow IPC for flat tables (the
    zero-copy-able format every Arrow consumer can read), pickle for
    multi-dim/ragged columns Arrow tables can't hold losslessly.  The
    Arrow payload is the ``pa.Buffer`` itself (buffer protocol — ZMQ
    sends it without the full extra copy ``to_pybytes()`` would force)."""
    import pyarrow as pa

    from petastorm_tpu.reader_impl.arrow_table_serializer import \
        ArrowTableSerializer

    flat = all(isinstance(v, np.ndarray) and v.ndim == 1
               and v.dtype != np.dtype(object) for v in chunk.values())
    if flat:
        try:
            table = pa.table({k: pa.array(v) for k, v in chunk.items()})
            return b'A', ArrowTableSerializer().serialize(table)
        except pa.ArrowInvalid:
            pass
    return b'R', pickle.dumps(chunk, protocol=4)


def deserialize_chunk(tag, payload):
    """Inverse of :func:`serialize_chunk`; always returns dict-of-numpy."""
    from petastorm_tpu.reader_impl.arrow_table_serializer import \
        ArrowTableSerializer

    if tag == b'A':
        table = ArrowTableSerializer().deserialize(payload)
        return {name: table.column(name).to_numpy(zero_copy_only=False)
                for name in table.column_names}
    if tag == b'R':
        return pickle.loads(payload)
    # Explicit dispatch (wire-protocol-conformance): an unknown tag is a
    # framing bug, not a pickle payload — naming it beats unpickling
    # garbage.
    raise ValueError('unknown chunk frame tag %r' % (tag,))


class Worker(object):  # ptlint: disable=pickle-unsafe-attrs — a worker IS a process/thread; jobs reach it via the dispatcher RPC, never by pickling the object
    """One decode worker process/thread.

    Args:
        dispatcher_addr: the dispatcher's REP endpoint.
        data_bind: bind spec for this worker's ROUTER data socket;
            ``tcp://host:*`` picks a free port (the resolved address is
            advertised to the dispatcher, so clients can connect).
        advertise_host: hostname/IP published to the dispatcher in place
            of the bind host.  Required in spirit whenever ``data_bind``
            uses a wildcard host: ``tcp://0.0.0.0:PORT`` is unroutable
            from other machines, so without this the worker substitutes
            ``socket.gethostname()`` and logs what it chose.
        max_inflight_splits / max_buffered_chunks: see ``ServiceConfig``.
        trace_recorder: optional ``benchmark.TraceRecorder`` — each
            decoded split is recorded as a ``service/decode_split`` span.
        cache_plane_dir: override the job's ``cache_plane_dir`` for THIS
            worker.  The plane is a host-local asset: workers on
            different machines naturally resolve the job's path on their
            own filesystems, but co-hosted workers that must NOT share a
            plane (tests, benches simulating a multi-host fleet, tiered
            storage layouts) point each at its own directory here.
    """

    def __init__(self, dispatcher_addr, data_bind='tcp://127.0.0.1:*',
                 advertise_host=None, max_inflight_splits=3,
                 max_buffered_chunks=32, trace_recorder=None,
                 cache_plane_dir=None):
        self._dispatcher_addr = dispatcher_addr
        self._data_bind = data_bind
        self._advertise_host = advertise_host
        self._max_inflight = int(max_inflight_splits)
        self._max_buffered = int(max_buffered_chunks)
        self._trace = trace_recorder
        self._stop = threading.Event()
        #: Graceful drain (ISSUE 15): set by :meth:`drain`, a SIGTERM
        #: (see :meth:`install_signal_handlers`), or a dispatcher
        #: ``drain`` RPC arriving on a heartbeat reply.  The event loop
        #: then stops leasing, hands back splits it never started,
        #: finishes streaming the rest, and deregisters — zero lost
        #: splits, zero residue.
        self._drain = threading.Event()
        #: True once the drain path completed (diagnostics surface).
        self.drained = False
        #: True when the drain deadline passed with splits in flight.
        self.drain_timed_out = False
        self._thread = None
        self._t_start = None
        self._decode_out = None
        self.worker_id = None
        self.data_addr = None
        self._ready = threading.Event()
        #: Source of truth for the worker's counters (ISSUE 5):
        #: ``diagnostics`` is a view, and the full snapshot (including
        #: the stage latency histograms) rides every heartbeat so the
        #: dispatcher's ``stats`` RPC can roll the fleet up by addition.
        self.metrics = MetricsRegistry('service_worker')
        self._m_rows = self.metrics.counter('rows_decoded')
        self._m_splits = self.metrics.counter('splits_decoded')
        self._m_shm_chunks = self.metrics.counter('shm_chunks')
        self._m_decode_hist = self.metrics.histogram('decode_split')
        self._m_serialize_hist = self.metrics.histogram('serialize')
        self._m_shm_pub_hist = self.metrics.histogram('shm_publish')
        #: (this_worker_monotonic - dispatcher_monotonic), measured at
        #: registration (reply midpoint handshake), then RE-measured on
        #: every heartbeat and EWMA-smoothed (ISSUE 7 satellite: a
        #: long-lived worker drifts off its one registration-time
        #: estimate and skews every merged timeline).  Shipped on every
        #: heartbeat; the client chains it with ITS dispatcher offset to
        #: land this worker's spans on its own timeline.
        self.clock_offset = None
        #: EWMA offset minus the registration-time offset, in ms — the
        #: drift signal `stats`/doctor surface (a same-host fleet should
        #: sit at ~0; growth means monotonic clocks diverging or rtt
        #: asymmetry corrupting the midpoint estimate).
        self.clock_drift_ms = 0.0
        self._clock_offset_initial = None
        #: shm result plane (None when the job or host disables it);
        #: written only by the decode thread, stopped after it joins.
        self._arena = None
        #: consumer -> True when its subscribe proved same-host residence
        #: (read by the decode thread, written by the event loop — a plain
        #: dict is safe under the GIL for this flag traffic).
        self._shm_consumers = {}
        #: epoch-cache plane counters accumulated across per-split
        #: readers (job['cache_plane']) into the registry; shipped in
        #: every heartbeat (see ``diagnostics``).
        self._m_cache = {key: self.metrics.counter(key)
                         for key in ('cache_hits', 'cache_misses',
                                     'cache_evictions', 'cache_ram_hits',
                                     'cache_degraded')}
        #: Cluster cache tier (ISSUE 10): remote_hits counts pieces of a
        #: leased split streamed straight from the local plane (no
        #: reader constructed); peer_fills counts entries fetched from a
        #: peer's plane instead of re-decoded; peer_degraded counts
        #: fetches that failed (dead/slow/absent peer -> direct decode).
        self._m_cluster = {key: self.metrics.counter(key)
                           for key in ('cache_remote_hits',
                                       'cache_peer_fills',
                                       'cache_peer_degraded')}
        self._m_serve_hist = self.metrics.histogram('serve_cached_split')
        #: Unified backoff telemetry (ISSUE 15): every control-plane
        #: retry this worker schedules (heartbeat, re-register, peer
        #: fetch) and every episode that exhausted its budget.  Ride the
        #: heartbeats like every counter, summed fleet-wide in `stats`'s
        #: control_plane rollup — a retry storm is a fleet phenomenon.
        self._m_retry = {key: self.metrics.counter(key)
                         for key in ('retry_attempts', 'retry_giveups')}
        #: ClusterWorkerState when the job opts in (None otherwise /
        #: killed); owned by run(), read by the event + decode threads.
        self._cluster = None
        self._cache_plane_dir = cache_plane_dir
        # -- multi-tenant serving (ISSUE 16) ---------------------------------
        #: tenant -> job_info, fetched lazily on the first lease naming
        #: an unknown tenant (the register reply seeds the default).
        self._tenant_jobs = {}
        #: tenant -> resolved reader factory (datasets differ per job).
        self._reader_factories = {}
        #: Per-tenant byte budgets (job_info's tenant_*_quota_bytes).
        #: shm: outstanding descriptor bytes, refunded when the split's
        #: ack retires them; over budget the chunk takes the byte path.
        #: cache: cumulative fill bytes this worker pushed into the
        #: plane; over budget the tenant's readers are built WITHOUT the
        #: plane (direct decode).  Both degrade, neither stalls.
        self._shm_quota = tenancy.QuotaLedger(label='shm')
        self._cache_quota = tenancy.QuotaLedger(label='cache')
        #: (split_id, attempt) -> shm bytes charged; refunded on ack /
        #: replay / decode error so a lost ack cannot leak budget.
        self._shm_split_bytes = {}
        #: tenants whose cache-plane budget is exhausted (sticky for the
        #: worker's lifetime: the plane's files persist on disk).
        self._cache_over_budget = set()
        self._m_quota = {key: self.metrics.counter(key)
                         for key in ('shm_quota_degraded',
                                     'cache_quota_degraded')}

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Run the worker in a daemon thread (in-process deployments:
        tests, the bench's service leg).  The CLI calls :meth:`run`."""
        self._thread = threading.Thread(target=self.run,
                                        name='service-worker', daemon=True)
        self._thread.start()
        # _ready is also set on an early run() failure (so start() never
        # hangs); a set event with no worker_id means registration failed.
        if not self._ready.wait(timeout=30) or self.worker_id is None:
            raise RuntimeError('worker failed to register with %r'
                               % (self._dispatcher_addr,))
        return self

    def stop(self):
        self._stop.set()

    def drain(self):
        """Begin a graceful drain (ISSUE 15): stop taking leases, hand
        back splits never started (``release`` RPC, attempt intact),
        finish streaming + awaiting acks for the rest, flush/retire shm
        slabs, then ``deregister`` and exit the event loop.  Bounded by
        the job's ``drain_timeout_s``; past it the worker deregisters
        as ``timed_out`` and the dispatcher requeues the remainder
        immediately.  Idempotent; safe from any thread and from a
        signal handler (it only sets an Event)."""
        self._drain.set()

    def install_signal_handlers(self):
        """SIGTERM -> :meth:`drain` (the scale-in half of autoscaling:
        an orchestrator's terminationGracePeriod maps onto the drain
        deadline).  Main-thread only by the stdlib's rules; the CLI
        path calls this, in-process deployments call :meth:`drain`."""
        import signal

        def on_sigterm(signum, frame):
            logger.info('SIGTERM: draining worker %s', self.worker_id)
            self.drain()

        signal.signal(signal.SIGTERM, on_sigterm)

    def join(self):
        if self._thread is not None:
            self._thread.join()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()

    # -- main loop -----------------------------------------------------------

    def run(self):
        import zmq

        context = zmq.Context()
        data = context.socket(zmq.ROUTER)
        data.setsockopt(zmq.LINGER, 0)
        data.set_hwm(0)  # credits bound in-flight data, not the HWM
        if self._data_bind.startswith('tcp') and (
                self._data_bind.endswith(':*')
                or self._data_bind.endswith(':0')):
            base = self._data_bind.rsplit(':', 1)[0]
            port = data.bind_to_random_port(base)
            self.data_addr = '%s:%d' % (base, port)
        else:
            data.bind(self._data_bind)
            self.data_addr = self._data_bind
        self.data_addr = self._advertised(self.data_addr)
        rpc = _Rpc(context, self._dispatcher_addr)
        decode_in = queue.Queue()
        decode_out = queue.Queue(maxsize=self._max_buffered)
        self._decode_out = decode_out
        decode_thread = None
        try:
            t_reg0 = time.monotonic()
            reply = rpc.call({'op': 'register_worker',
                              'data_addr': self.data_addr})
            t_reg1 = time.monotonic()
            self.worker_id = reply['worker_id']
            job = reply['job']
            if self._cache_plane_dir is not None:
                # Host-local override applied in ONE place: every
                # downstream consumer (per-split readers, the cluster
                # identity) sees the same resolved path.
                job = dict(job, cache_plane_dir=self._cache_plane_dir)
            # The register reply's job IS the default tenant's; further
            # tenants' jobs are fetched lazily on their first lease.
            self._adopt_tenant_job(job)
            # Clock handshake (ISSUE 5): dispatcher monotonic against
            # the local send/recv midpoint — wrong by at most rtt/2,
            # which orders spans fine on any LAN.  Heartbeats repeat it
            # (ISSUE 7: drift EWMA).
            self._update_clock(reply.get('t_mono'), t_reg0, t_reg1)
            from petastorm_tpu.service import cluster
            if cluster.enabled(job):
                # Identity build is a footer scan — background it so a
                # big dataset cannot delay registration/first lease.
                self._cluster = cluster.ClusterWorkerState(job)
            from petastorm_tpu.telemetry import flight
            # Always-on flight recorder for this process: the minutes
            # before a worker death persist when a flight dir is set.
            flight.enable(label='service_worker')
            from petastorm_tpu.workers_pool import shm_plane
            if job.get('shm', True) and shm_plane.available():
                self._arena = shm_plane.ShmArena(
                    capacity_bytes=job.get(
                        'shm_capacity_bytes',
                        shm_plane.DEFAULT_CAPACITY_BYTES),
                    metrics=self.metrics)
            self._t_start = time.monotonic()
            #: shared zmq context for the decode thread's peer fetcher
            #: (contexts are thread-safe; the fetcher's sockets live and
            #: die on the decode thread alone).
            self._zmq_context = context
            self._ready.set()
            decode_thread = threading.Thread(
                target=self._decode_loop, args=(job, decode_in, decode_out),
                name='service-worker-decode', daemon=True)
            decode_thread.start()
            self._event_loop(zmq, data, rpc, job, decode_in, decode_out)
        finally:
            self._ready.set()  # unblock start() on early failure
            decode_in.put(None)
            if decode_thread is not None:
                # Unstick a decode blocked on the bounded output queue.
                while decode_thread.is_alive():
                    try:
                        decode_out.get_nowait()
                    except queue.Empty:
                        decode_thread.join(timeout=0.05)
            if self._arena is not None:
                # After the decode thread: unlink every segment no client
                # mapped, so a clean shutdown leaves zero /dev/shm residue
                # (descriptors dropped above go with their segments).
                self._arena.stop()
            rpc.close()
            data.close(0)
            context.term()

    #: EWMA weight of each new midpoint estimate: heavy enough to track
    #: genuine drift within ~10 beats, light enough that one rtt-skewed
    #: beat cannot yank every span's alignment.
    _CLOCK_EWMA_ALPHA = 0.2

    def _update_clock(self, t_mono, t0, t1):
        """Fold one (reply ``t_mono``, local send/recv window) clock
        handshake into the EWMA offset + drift estimate."""
        if t_mono is None:
            return
        estimate = (t0 + t1) / 2.0 - float(t_mono)
        if self.clock_offset is None:
            self._clock_offset_initial = estimate
            self.clock_offset = round(estimate, 6)
            return
        alpha = self._CLOCK_EWMA_ALPHA
        ewma = (1.0 - alpha) * self.clock_offset + alpha * estimate
        self.clock_offset = round(ewma, 6)
        self.clock_drift_ms = round(
            1e3 * (ewma - self._clock_offset_initial), 3)

    def _count_retry(self, episode):
        """Count one heartbeat-class retry; an EXHAUSTED episode counts
        one ``retry_giveups`` (the dead-dispatcher signal the
        control-plane-degraded regime reads) and rolls into a fresh
        episode — the worker never stops trying, only the telemetry
        marks the budget boundary."""
        episode = episode or backoff.HEARTBEAT_POLICY.episode()
        self._m_retry['retry_attempts'].inc()
        if episode.give_up():
            self._m_retry['retry_giveups'].inc()
            episode = backoff.HEARTBEAT_POLICY.episode()
        return episode

    def _advertised(self, addr):
        """The address published to the dispatcher: clients on OTHER
        machines connect to it, so a wildcard bind host must be replaced
        with something routable."""
        scheme, rest = addr.split('://', 1)
        host, port = rest.rsplit(':', 1)
        if self._advertise_host is not None:
            host = self._advertise_host
        elif host in ('0.0.0.0', '*', '::'):
            import socket
            host = socket.gethostname()
            logger.warning(
                'data_bind host %r is unroutable from other machines; '
                'advertising %r instead (pass advertise_host/'
                '--advertise-host to override)', '0.0.0.0', host)
        return '%s://%s:%s' % (scheme, host, port)

    # -- multi-tenant job table (ISSUE 16) -----------------------------------

    def _adopt_tenant_job(self, job):
        """Enter one tenant's job_info into the worker's table and arm
        its quota budgets.  Returns the tenant id."""
        tenant = str(job.get('tenant') or tenancy.DEFAULT_TENANT)
        self._tenant_jobs[tenant] = job
        self._shm_quota.set_budget(tenant,
                                   job.get('tenant_shm_quota_bytes'))
        self._cache_quota.set_budget(tenant,
                                     job.get('tenant_cache_quota_bytes'))
        return tenant

    def _job_for(self, split):
        """The owning tenant's job_info for a leased split (the decode
        thread reads dataset_url / reader_kwargs from it).  Known by the
        time the split is queued — ``_event_loop`` fetches unknown
        tenants' jobs before queueing; the default job is the fallback
        for pre-tenancy dispatchers that ship splits without the key."""
        tenant = str(split.get('tenant') or tenancy.DEFAULT_TENANT)
        return self._tenant_jobs.get(
            tenant, self._tenant_jobs[tenancy.DEFAULT_TENANT])

    def _fetch_tenant_job(self, rpc, tenant):
        """Fetch + adopt an unknown tenant's job_info from the
        dispatcher; False when the RPC fails (the caller releases the
        split instead of decoding it against the wrong config)."""
        if tenant in self._tenant_jobs:
            return True
        try:
            reply = rpc.call({'op': 'job', 'tenant': tenant})
        except ServiceError as e:
            logger.warning('job fetch for tenant %r failed: %s', tenant, e)
            return False
        job = reply['job']
        if self._cache_plane_dir is not None:
            job = dict(job, cache_plane_dir=self._cache_plane_dir)
        self._adopt_tenant_job(job)
        logger.info('adopted tenant %r job (%s)', tenant,
                    job.get('dataset_url'))
        return True

    @staticmethod
    def _split_tenant(split):
        return str(split.get('tenant') or tenancy.DEFAULT_TENANT)

    def _refund_shm_quota(self, split):
        """Return a split's outstanding shm-descriptor bytes to its
        tenant's budget (ack arrived / stream abandoned)."""
        key = (int(split['split_id']), int(split['attempt']))
        nbytes = self._shm_split_bytes.pop(key, 0)
        if nbytes:
            self._shm_quota.refund(self._split_tenant(split), nbytes)

    def _event_loop(self, zmq, data, rpc, job, decode_in, decode_out):
        heartbeat_every = max(0.2, job['lease_ttl_s'] / 3.0)
        next_heartbeat = 0.0
        #: Active backoff episode across consecutive heartbeat /
        #: re-register failures (None while healthy) — the unified
        #: jittered-exponential policy (ISSUE 15) in place of the old
        #: fixed-interval retry that had the whole fleet hammering a
        #: restarted dispatcher in lockstep.
        hb_retry = None
        draining = False
        drain_deadline = None
        next_lease_probe = 0.0
        subscribers = {}      # (tenant, consumer) -> identity
        credits = {}          # identity -> remaining chunk budget
        sendq = {}            # (tenant, consumer) -> deque of
        #                       (header, payload|None)
        inflight = {}         # split_id -> split description
        awaiting_ack = {}     # (split_id, attempt) -> split description
        ack_deadline = {}     # (split_id, attempt) -> monotonic deadline
        ack_timeout = 3.0 * job['lease_ttl_s']
        decoding = set()      # split ids queued/being decoded

        def replay(key):
            """Re-decode a streamed-but-never-acked split: its frames went
            to an identity that is gone (client restart) or the ack was
            lost; without this it would sit in inflight forever, its lease
            renewing on every heartbeat."""
            split = awaiting_ack.pop(key, None)
            ack_deadline.pop(key, None)
            if split is not None and split['split_id'] not in decoding:
                # The abandoned stream's shm descriptors will never be
                # acked: return their bytes before the re-decode
                # re-charges the tenant's budget.
                self._refund_shm_quota(split)
                decoding.add(split['split_id'])
                decode_in.put(split)
        poller = zmq.Poller()
        poller.register(data, zmq.POLLIN)
        from collections import deque

        while not self._stop.is_set():
            now = time.monotonic()
            # 1. client control messages (subscribe / credit / ack)
            if dict(poller.poll(20)):
                while True:
                    try:
                        identity, raw = data.recv_multipart(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    msg = pickle.loads(raw)
                    kind = msg.get('type')
                    if kind == 'subscribe':
                        consumer = int(msg['consumer'])
                        # Tenant-qualified subscription (ISSUE 16): a
                        # subscribe without the field is a pre-tenancy
                        # client on the default tenant's job.
                        ckey = (str(msg.get('tenant')
                                    or tenancy.DEFAULT_TENANT), consumer)
                        previous = subscribers.get(ckey)
                        if previous is not None and previous != identity:
                            # The consumer reconnected under a new ZMQ
                            # identity: anything streamed to the old one
                            # (including 'end' markers) is gone — replay
                            # its un-acked splits to the new identity.
                            credits.pop(previous, None)
                            for key in [k for k, s in awaiting_ack.items()
                                        if (self._split_tenant(s),
                                            s['consumer']) == ckey]:
                                replay(key)
                        subscribers[ckey] = identity
                        credits[identity] = int(msg.get('credits', 8))
                        # Same-host handshake: the client names a probe
                        # file it created in ITS /dev/shm; seeing the file
                        # proves shared shm (hostname checks get
                        # containers wrong in both directions).
                        from petastorm_tpu.workers_pool import shm_plane
                        self._shm_consumers[ckey] = bool(
                            self._arena is not None
                            and shm_plane.probe_exists(
                                msg.get('shm_probe')))
                    elif kind == 'credit':
                        if identity in credits:
                            credits[identity] += int(msg.get('n', 1))
                    elif kind == 'fetch':
                        # Cluster cache tier (ISSUE 10): a peer worker
                        # asks for one encoded plane entry by digest.
                        # Request/reply on the spot — fetches are not
                        # credit-gated chunks, and the entry read is a
                        # bounded mmap copy, not a decode.
                        from petastorm_tpu.service import cluster
                        state = self._cluster
                        plane = (state.identity.plane
                                 if state is not None and state.ready()
                                 else None)
                        data.send_multipart(cluster.fetch_reply(
                            identity, msg, plane, arena=self._arena))
                    elif kind == 'ack':
                        key = (int(msg['split']), int(msg['attempt']))
                        split = awaiting_ack.pop(key, None)
                        ack_deadline.pop(key, None)
                        if split is not None:
                            inflight.pop(split['split_id'], None)
                            # The ack retires the split's shm
                            # descriptors: their bytes return to the
                            # tenant's outstanding-shm budget.
                            self._refund_shm_quota(split)
                            try:
                                rpc.call({'op': 'complete',
                                          'worker_id': self.worker_id,
                                          'split_id': split['split_id'],
                                          'attempt': split['attempt']})
                            except ServiceError as e:
                                logger.warning('complete(%d) RPC failed: %s',
                                               split['split_id'], e)
                    elif kind == 'resend':
                        # The client lost chunks of this stream and
                        # discarded its partial buffer: decode + stream the
                        # split again.  It stays in inflight, so the lease
                        # keeps renewing.
                        replay((int(msg['split']), int(msg['attempt'])))
            # 1b. drain trigger (ISSUE 15): hand back every split still
            # sitting in the decode queue (never started — `release`
            # requeues it at the dispatcher, attempt intact), stop
            # leasing, and let the rest finish streaming.  The split
            # currently decoding, anything buffered, and every
            # streamed-but-unacked split complete through the normal
            # chunk/end/ack/complete path — zero lost splits.
            if not draining and self._drain.is_set():
                draining = True
                drain_deadline = now + float(job.get('drain_timeout_s',
                                                     30.0))
                handed = 0
                while True:
                    try:
                        item = decode_in.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        # run()'s stop sentinel: shutdown outranks the
                        # drain — re-queue it for the decode thread and
                        # stop handing back (popping it again here
                        # would spin this loop forever).
                        decode_in.put(None)
                        break
                    inflight.pop(item['split_id'], None)
                    decoding.discard(item['split_id'])
                    handed += 1
                    try:
                        rpc.call({'op': 'release',
                                  'worker_id': self.worker_id,
                                  'split_id': item['split_id'],
                                  'attempt': item['attempt']})
                    except ServiceError:
                        # The lease expires instead (attempt+1) — the
                        # slow path, but still zero lost splits.
                        pass
                logger.info('draining: handed back %d unstarted '
                            'split(s), %d still in flight', handed,
                            len(inflight))
            # 2. move decoded chunks into per-consumer send queues — but
            # only while fewer than max_buffered_chunks wait for credits:
            # leaving the rest in the bounded decode_out queue is what
            # pauses _decode_loop when consumers are slow or absent.
            while sum(len(q) for q in sendq.values()) < self._max_buffered:
                try:
                    item = decode_out.get_nowait()
                except queue.Empty:
                    break
                kind, split = item[0], item[1]
                ckey = (self._split_tenant(split), split['consumer'])
                if kind == 'chunk':
                    _, _, seq, tag, payload = item
                    header = {'type': 'chunk', 'split': split['split_id'],
                              'attempt': split['attempt'], 'seq': seq,
                              'tag': tag}
                    sendq.setdefault(ckey, deque()).append(
                        (header, payload))
                elif kind == 'end':
                    _, _, nchunks, nrows, chunk_spans = item[:5]
                    decoding.discard(split['split_id'])
                    header = {'type': 'end', 'split': split['split_id'],
                              'attempt': split['attempt'],
                              'chunks': nchunks, 'rows': nrows,
                              # Correlated spans of this split's decode
                              # (ISSUE 5): the client aligns them onto its
                              # clock via the chained dispatcher offsets
                              # and merges them into its TraceRecorder.
                              'spans': chunk_spans}
                    if len(item) > 5 and item[5] is not None:
                        # Per-split provenance record (ISSUE 13): rides
                        # the end header like the spans; the client
                        # aligns its stage windows onto its own clock.
                        header['provenance'] = item[5]
                    sendq.setdefault(ckey, deque()).append((header, None))
                    key = (split['split_id'], split['attempt'])
                    awaiting_ack[key] = split
                    ack_deadline[key] = time.monotonic() + ack_timeout
                else:  # decode error: log, drop — the lease will expire
                    decoding.discard(split['split_id'])
                    inflight.pop(split['split_id'], None)
                    self._refund_shm_quota(split)
                    logger.error('decode of split %d failed:\n%s',
                                 split['split_id'], item[2])
            # 3. flush send queues under credit control
            for ckey, q in sendq.items():
                identity = subscribers.get(ckey)
                if identity is None:
                    continue
                while q:
                    header, payload = q[0]
                    if header['type'] == 'chunk':
                        if credits.get(identity, 0) < 1:
                            break
                        # Chaos seam (ISSUE 15): drop/duplicate/delay a
                        # data-plane chunk.  Byte-path frames only — a
                        # duplicated shm descriptor would double-release
                        # its slab generation.  A dropped chunk keeps
                        # its credit with the client (the fault models
                        # identity loss, and exactly-once must stay
                        # LIVE under injection: the client's chunk-count
                        # mismatch at `end` requests the resend).
                        action = (chaos.inject('worker.chunk',
                                               split=header['split'],
                                               seq=header['seq'])
                                  if header['tag'] != b'S' else None)
                        if action != 'drop':
                            credits[identity] -= 1
                            data.send_multipart(
                                [identity,
                                 pickle.dumps(header, protocol=4),
                                 payload])
                            if action == 'dup':
                                data.send_multipart(
                                    [identity,
                                     pickle.dumps(header, protocol=4),
                                     payload])
                    else:
                        data.send_multipart(
                            [identity, pickle.dumps(header, protocol=4)])
                    q.popleft()
            # 3b. acks that never came (lost to a vanished identity with no
            # re-subscribe): replay to the current subscriber rather than
            # holding the split — and its lease — forever.
            if ack_deadline:
                for key in [k for k, d in ack_deadline.items() if now > d]:
                    split = awaiting_ack.get(key)
                    if split is None or subscribers.get(
                            (self._split_tenant(split),
                             split['consumer'])) is None:
                        # no subscriber to replay to: push the deadline out
                        # instead of spinning on decode
                        ack_deadline[key] = now + ack_timeout
                        continue
                    logger.warning('split %d attempt %d un-acked for %.0fs; '
                                   'replaying', key[0], key[1], ack_timeout)
                    replay(key)
            # 4. heartbeat (renews the leases this worker still claims).
            # Cadence is jittered (a same-TTL fleet must not beat in
            # phase) and failures retry on the shared
            # jittered-exponential policy (ISSUE 15) instead of the old
            # fixed-interval lockstep: a restarted dispatcher sees the
            # fleet's retries spread out, not as one synchronized storm.
            if now >= next_heartbeat:
                try:
                    t_hb0 = time.monotonic()
                    request = {'op': 'heartbeat',
                               'worker_id': self.worker_id,
                               'stats': self.heartbeat_stats(),
                               'held': list(inflight)}
                    if draining:
                        request['draining'] = True
                    # Cluster cache advertisement rides the heartbeat
                    # (ISSUE 10): the compact held-digest set when it
                    # changed, and the once-per-job piece-digest map
                    # until the dispatcher confirms it has one.
                    sent_pieces = False
                    if self._cluster is not None:
                        fields = self._cluster.heartbeat_fields()
                        sent_pieces = 'piece_digests' in fields
                        request.update(fields)
                    reply = rpc.call(request)
                    if self._cluster is not None:
                        if sent_pieces and reply.get('ok'):
                            self._cluster.advertised_pieces = True
                        if reply.get('need_piece_digests'):
                            self._cluster.advertised_pieces = False
                    if reply.get('drain'):
                        # Dispatcher-initiated drain (the `drain` RPC)
                        # arrives here, on the channel we already poll.
                        self._drain.set()
                    # Opportunistic clock re-handshake (ISSUE 7): the
                    # beat's send/recv midpoint EWMAs into clock_offset
                    # so a long-lived worker tracks drift instead of
                    # freezing its registration-time estimate.
                    self._update_clock(reply.get('t_mono'), t_hb0,
                                       time.monotonic())
                    hb_retry = None
                    next_heartbeat = now + backoff.jittered(
                        heartbeat_every, 0.1)
                except ServiceRpcTimeoutError:
                    logger.warning('heartbeat to %s timed out',
                                   self._dispatcher_addr)
                    hb_retry = self._count_retry(hb_retry)
                    # Never slower than the healthy cadence: a worker
                    # "backing off" past the TTL would lose its leases
                    # to expiry while politely waiting.
                    next_heartbeat = now + min(heartbeat_every,
                                               hb_retry.next_delay())
                except ServiceError:
                    # The dispatcher lost our registration (restart):
                    # re-register under a fresh id rather than dying.
                    try:
                        reply = rpc.call({'op': 'register_worker',
                                          'data_addr': self.data_addr})
                        logger.warning('re-registered with %s as %s (was %s)',
                                       self._dispatcher_addr,
                                       reply['worker_id'], self.worker_id)
                        self.worker_id = reply['worker_id']
                        if self._cluster is not None:
                            # A restarted dispatcher lost the directory:
                            # re-advertise everything on the next beat.
                            self._cluster.reset_advertisement()
                        hb_retry = None
                        # Beat immediately under the fresh id: the
                        # `held` claims on that beat are what lets a
                        # ledger-restored dispatcher ADOPT our leases
                        # before their grace TTL expires them.
                        next_heartbeat = now
                    except ServiceError:  # incl. timeout
                        hb_retry = self._count_retry(hb_retry)
                        next_heartbeat = now + min(heartbeat_every,
                                                   hb_retry.next_delay())
            # 4b. drain completion (ISSUE 15): once nothing is in
            # flight (every split acked+completed or handed back) and
            # nothing is buffered, deregister and leave; past the
            # deadline deregister as timed_out — the dispatcher
            # requeues the remainder immediately.
            if draining:
                idle = not inflight and decode_out.empty() \
                    and not any(sendq.values())
                if idle or now > drain_deadline:
                    self.drain_timed_out = not idle
                    if not idle:
                        logger.warning(
                            'drain deadline passed with %d split(s) '
                            'still in flight; deregistering timed_out',
                            len(inflight))
                    try:
                        rpc.call({'op': 'deregister',
                                  'worker_id': self.worker_id,
                                  'timed_out': not idle})
                    except ServiceError:
                        pass  # heartbeats stop; leases expire instead
                    self.drained = True
                    break
            # 5. lease more work — only for consumers with a live
            # subscriber here, so an absent training host's splits don't
            # occupy this worker's decode plane and send buffer.  A
            # draining worker takes nothing new, by contract.
            if not draining and subscribers \
                    and len(inflight) < self._max_inflight \
                    and now >= next_lease_probe:
                try:
                    # (tenant, consumer) pairs — the dispatcher's WDRR
                    # scheduler leases only work these subscribers can
                    # actually drain.
                    reply = rpc.call({'op': 'lease',
                                      'worker_id': self.worker_id,
                                      'consumers': sorted(subscribers)})
                except ServiceError:  # timeout or not-yet-re-registered
                    reply = {'wait': True}
                if reply.get('drain'):
                    # Dispatcher-initiated drain also rides lease
                    # refusals — a lease-hungry worker must not wait a
                    # heartbeat interval to learn it.
                    self._drain.set()
                if reply.get('split'):
                    split = reply['split']
                    # Cluster tier: the dispatcher's directory hints at
                    # which peers hold this split's entries (cdigest ->
                    # [data addr]); the decode thread uses them for peer
                    # fill.  Advisory: absent/stale hints just decode.
                    if reply.get('holders'):
                        split['holders'] = reply['holders']
                    # First lease for an unknown tenant: fetch its job
                    # BEFORE queueing (the decode thread must read the
                    # right dataset/config).  A failed fetch hands the
                    # split back rather than decoding it wrong.
                    if self._fetch_tenant_job(rpc,
                                              self._split_tenant(split)):
                        inflight[split['split_id']] = split
                        decoding.add(split['split_id'])
                        decode_in.put(split)
                    else:
                        try:
                            rpc.call({'op': 'release',
                                      'worker_id': self.worker_id,
                                      'split_id': split['split_id'],
                                      'attempt': split['attempt']})
                        except ServiceError:
                            pass  # the lease expires instead
                        next_lease_probe = now + min(
                            1.0, max(0.05, job['lease_ttl_s'] / 10.0))
                else:
                    # nothing assignable right now (all leased or all done)
                    next_lease_probe = now + min(
                        1.0, max(0.05, job['lease_ttl_s'] / 10.0))

    # -- decode --------------------------------------------------------------

    def _resolve_factory(self, job):
        """'auto': petastorm metadata -> codec reader (columnar output),
        plain Parquet -> batch reader.  Resolved once per worker."""
        from petastorm_tpu.errors import MetadataError
        from petastorm_tpu.reader import make_batch_reader, make_reader

        def codec_reader(url, **kwargs):
            return make_reader(url, columnar_decode=True, **kwargs)

        choice = job['reader_factory']
        if choice == 'reader':
            return codec_reader
        if choice == 'batch_reader':
            return make_batch_reader
        try:
            reader = codec_reader(job['dataset_url'], num_epochs=1,
                                  piece_indices=[0], shuffle_row_groups=False,
                                  **job['reader_kwargs'])
            reader.stop()
            reader.join()
            return codec_reader
        except MetadataError:
            return make_batch_reader

    def _serialize_split_chunk(self, split, chunk, cid, spans):
        """(tag, payload) for one chunk: shm descriptors (tag ``b'S'``)
        for consumers that proved same-host residence, degrading per-chunk
        to the byte framing (arena full, chunk under the segment-worthy
        floor, or a cross-host consumer).  Each chunk's serialize/publish
        time feeds the stage histograms and, correlation-id'd by
        ``split/seq``, the span list riding the split's ``end`` header."""
        t0 = time.monotonic()
        tenant = self._split_tenant(split)
        if self._arena is not None \
                and self._shm_consumers.get((tenant, split['consumer'])):
            # Per-tenant shm budget (ISSUE 16), enforced at publish: a
            # chunk that would push the tenant's OUTSTANDING descriptor
            # bytes past its quota takes the byte path instead — degrade,
            # never stall.  Charged bytes return when the split's ack
            # retires its descriptors.
            nbytes = sum(int(getattr(v, 'nbytes', 0))
                         for v in chunk.values())
            if not self._shm_quota.charge(tenant, nbytes):
                self._m_quota['shm_quota_degraded'].inc()
            else:
                from petastorm_tpu.workers_pool import shm_plane
                desc = shm_plane.write_columns(self._arena, chunk)
                if desc is not None:
                    key = (int(split['split_id']), int(split['attempt']))
                    self._shm_split_bytes[key] = \
                        self._shm_split_bytes.get(key, 0) + nbytes
                    t1 = time.monotonic()
                    self._m_shm_chunks.inc()
                    self._m_shm_pub_hist.observe(t1 - t0)
                    spans.append({'name': 'service/shm_publish', 't0': t0,
                                  't1': t1, 'pid': os.getpid(),
                                  'tid': threading.get_ident(),
                                  'cid': cid})
                    return b'S', pickle.dumps(desc, protocol=4)
                self._shm_quota.refund(tenant, nbytes)
        tag, payload = serialize_chunk(chunk)
        t1 = time.monotonic()
        self._m_serialize_hist.observe(t1 - t0)
        spans.append({'name': 'service/serialize', 't0': t0, 't1': t1,
                      'pid': os.getpid(), 'tid': threading.get_ident(),
                      'cid': cid})
        return tag, payload

    def _split_record(self, split, stages, serialize_spans, tags, cache,
                      worker_args=None, sched=None):
        """Per-split provenance record (ISSUE 13), shipped on the split's
        ``end`` header next to the spans.  Stage windows are THIS
        worker's monotonic clock; the client re-aligns them via the
        chained clock offsets before journaling."""
        stages = dict(stages)
        busy_ms = {}
        for stage, names in (('serialize', ('service/serialize',
                                            'service/shm_publish')),
                             ('cache_fill', ('cache/fill',))):
            windows = [s for s in serialize_spans if s.get('name') in names]
            if windows:
                stages[stage] = [min(s['t0'] for s in windows),
                                 max(s['t1'] for s in windows)]
                # Per-chunk spans interleave with decode, so the window
                # is an ENVELOPE spanning most of the split: ship the
                # summed busy time too, which is what explain's dur_ms /
                # %-of-wall columns report (the envelope alone would
                # misattribute the whole split wall to serialization).
                busy_ms[stage] = round(
                    1e3 * sum(s['t1'] - s['t0'] for s in windows), 3)
        transport = None
        if tags:
            if tags <= {b'S'}:
                transport = 'shm'
            elif b'S' in tags:
                transport = 'mixed'
            else:
                transport = 'bytes'
        return provenance.make_record(
            'service', worker_pid=os.getpid(),
            worker_host=provenance.host(),
            pieces=provenance.pieces_for_indices(
                worker_args, split.get('indices') or ()),
            cache=cache, transport=transport, sched=sched, stages=stages,
            stage_busy_ms=busy_ms or None,
            split=int(split['split_id']), attempt=int(split['attempt']),
            # Cost attribution (ISSUE 16): every service record names
            # the tenant whose job paid for this split's decode.
            tenant=self._split_tenant(split))

    def _reader_kwargs(self, job):
        """Per-split reader kwargs; with ``job['cache_plane']`` the reader
        consults the shared epoch-cache plane before hitting Parquet —
        the cache-hit half of the ownership contract (the dispatcher's
        lease is the decode half: each piece is DECODED by exactly one
        worker per epoch, and any worker can SERVE it warm afterwards).
        Explicit cache settings in ``reader_kwargs`` win."""
        kwargs = dict(job['reader_kwargs'])
        # Per-split readers inherit the job's dispatch policy (ISSUE 9);
        # an explicit reader_kwargs['scheduling'] wins, and 'auto' still
        # degrades to fifo on splits too small to reorder.
        kwargs.setdefault('scheduling', job.get('scheduling', 'auto'))
        # ...and the job's ingest-plane mode (ISSUE 14): decode workers
        # are exactly the processes that pay object-store first-byte
        # latency, so the per-split reader mounts the same async
        # byte-range plane a local reader would ('auto' still stays off
        # on local filesystems and under the kill switch).
        kwargs.setdefault('ingest', job.get('ingest', 'auto'))
        tenant = str(job.get('tenant') or tenancy.DEFAULT_TENANT)
        if tenant in self._cache_over_budget \
                and 'cache_type' not in kwargs:
            # Per-tenant cache budget exhausted (ISSUE 16): this
            # tenant's readers run WITHOUT the plane — direct decode,
            # no new fills, never a stall.
            self._m_quota['cache_quota_degraded'].inc()
            return kwargs
        if job.get('cache_plane') and 'cache_type' not in kwargs:
            kwargs['cache_type'] = 'plane'
            kwargs.setdefault('cache_location', job['cache_plane_dir'])
            kwargs.setdefault('cache_size_limit',
                              job.get('cache_plane_disk_bytes'))
            extra = dict(kwargs.get('cache_extra_settings') or {})
            extra.setdefault('ram_bytes', job.get('cache_plane_ram_bytes'))
            kwargs['cache_extra_settings'] = extra
        return kwargs

    def _accumulate_cache_stats(self, reader):
        """Fold one (per-split, hence fresh) plane instance's counters
        and its ``cache_fill`` latency histogram into the worker
        registry, so fill time reaches the fleet ``stages`` rollup like
        every other stage.  Counters are accumulated explicitly (their
        names collide with the heartbeat keys) — merge ONLY the
        histograms from the plane snapshot."""
        cache = getattr(reader, '_cache', None)
        stats = getattr(cache, 'stats', None)
        if not stats:
            return
        for key, counter in self._m_cache.items():
            counter.inc(int(stats.get(key, 0)))
        plane_metrics = getattr(cache, 'metrics', None)
        if plane_metrics is not None:
            self.metrics.merge(
                {'histograms': plane_metrics.snapshot()['histograms']})

    def _accumulate_ingest_stats(self, reader):
        """Fold one per-split reader's ingest-plane activity (ISSUE 14)
        into the worker registry: the ``ingest_fetch``/``ingest_wait``
        histograms reach the fleet ``stages`` rollup, the counters feed
        the ``fetch-bound`` health regime's degrade ratio."""
        plane = getattr(reader, 'ingest_plane', None)
        if plane is None:
            return
        for name, value in plane.stats.items():
            if name in ('ingest_fetches', 'ingest_fetch_bytes',
                        'ingest_gets', 'ingest_degraded', 'ingest_hedges',
                        'ingest_hedge_wins'):
                self.metrics.counter(name).inc(int(value))
        self.metrics.merge(
            {'histograms': {name: hist for name, hist
                            in plane.metrics.snapshot()['histograms'].items()
                            if name.startswith('ingest_')}})

    def _cluster_chunks(self, split, fetcher):
        """Try the cluster cache tier for a leased split: peer-fill any
        local misses the lease's holder hints cover, then look the whole
        split up in the local plane.  Returns ``(chunks, fetcher)`` —
        ``chunks`` is None when the split (still) cannot be served
        cache-only, in which case NOTHING has been emitted and the
        caller falls through to the reader path (which itself benefits
        from whatever peer fill just published).  Never raises: every
        failure here is a degrade back to decode."""
        from petastorm_tpu.service import cluster
        state = self._cluster
        if state is None or not state.ready():
            return None, fetcher
        identity = state.identity
        try:
            indices = split['indices']
            missing = identity.missing_digests(indices)
            holders = split.get('holders') or {}
            filled = []
            for digest in missing:
                addrs = holders.get(cluster.cdigest(digest)) or ()
                if not addrs:
                    continue  # nobody holds it: plain cold decode, no
                    # counter — degrade counts FAILED fetches only
                if fetcher is None:
                    fetcher = cluster.PeerFetcher(self._zmq_context)
                blob = None
                # Every advertised holder is tried back to back (a
                # delay earned by holder A buys nothing against holder
                # B, and this runs on the decode thread); the unified
                # retry telemetry (ISSUE 15) counts the extra attempts
                # and an all-holders-failed walk as one giveup.
                for i, addr in enumerate(addrs):
                    if i:
                        self._m_retry['retry_attempts'].inc()
                    blob = fetcher.fetch(addr, digest)
                    if blob is not None:
                        break
                if blob is None:
                    self._m_retry['retry_giveups'].inc()
                if blob is not None \
                        and identity.plane.publish_blob(digest, blob):
                    self._m_cluster['cache_peer_fills'].inc()
                    filled.append(digest)
                else:
                    self._m_cluster['cache_peer_degraded'].inc()
            if filled:
                state.note_published(filled)
            chunks = identity.serve_chunks(indices)
            if chunks is not None:
                self._m_cluster['cache_remote_hits'].inc(
                    len(identity.split_digests(indices)))
            return chunks, fetcher
        except Exception:  # noqa: BLE001 — cluster tier degrades, never blocks
            logger.warning('cluster cache: serving split %s degraded to '
                           'direct decode', split.get('split_id'),
                           exc_info=True)
            return None, fetcher

    def _decode_loop(self, job, decode_in, decode_out):
        ship_spans = bool(job.get('telemetry_spans', True))
        try:
            self._decode_loop_inner(job, decode_in, decode_out, ship_spans)
        finally:
            # Peer-fetch sockets die with their owning thread, BEFORE
            # run()'s context.term() (which would otherwise block on
            # them forever).
            fetcher, self._fetcher = self._fetcher, None
            if fetcher is not None:
                fetcher.close()

    _fetcher = None

    def _serve_cached_split(self, split, chunks, decode_out, ship_spans,
                            t0, cache_outcome='remote_hit'):
        """Stream an entirely-cached split through the normal chunk
        protocol (same serialization, shm fallback matrix, credits, end
        marker, ack/complete flow — only the decode is gone)."""
        seq = 0
        rows = 0
        spans = []
        tags = set()
        for chunk in chunks:
            cid = '%d/%d' % (split['split_id'], seq)
            tag, payload = self._serialize_split_chunk(split, chunk, cid,
                                                       spans)
            tags.add(tag)
            rows += len(next(iter(chunk.values())))
            decode_out.put(('chunk', split, seq, tag, payload))
            seq += 1
        t1 = time.monotonic()
        self._m_serve_hist.observe(t1 - t0)
        record = None
        if provenance.enabled():
            record = self._split_record(split, {'serve_cached': [t0, t1]},
                                        spans, tags, cache_outcome)
        spans.append({'name': 'service/serve_cached_split', 't0': t0,
                      't1': t1, 'pid': os.getpid(),
                      'tid': threading.get_ident(),
                      'cid': str(split['split_id']),
                      'args': {'rows': rows}})
        if not ship_spans:
            spans = []
        decode_out.put(('end', split, seq, rows,
                        spans[-_MAX_SPANS_PER_SPLIT:], record))
        self._m_rows.inc(rows)
        self._m_splits.inc()
        if self._trace is not None:
            self._trace.event('service/serve_cached_split', t0, t1,
                              split=split['split_id'], rows=rows)

    def _decode_loop_inner(self, job, decode_in, decode_out, ship_spans):
        while True:
            split = decode_in.get()
            if split is None:
                return
            t0 = time.monotonic()
            spans = []
            try:
                # Chaos seam (ISSUE 15): per-split decode latency spikes
                # and injected decode failures (the lease-expiry path).
                chaos.inject('worker.decode', split=split['split_id'])
                prov_on = provenance.enabled()
                peer_fills_before = (
                    int(self._m_cluster['cache_peer_fills'].value)
                    if prov_on else 0)
                tenant = self._split_tenant(split)
                tjob = self._job_for(split)
                # Cluster cache tier (ISSUE 10): a split the local plane
                # fully holds (natively or after peer fill) streams
                # without constructing a reader — no Parquet open, no
                # decode, no per-split pool spin-up.  The tier's
                # identity is built over the REGISTRATION job's dataset,
                # so a co-tenant rides it exactly when its job reads the
                # same dataset (the fleet-compounding case: its splits
                # serve warm from entries the first tenant decoded).
                chunks = None
                if tjob.get('dataset_url') == job.get('dataset_url'):
                    chunks, self._fetcher = self._cluster_chunks(
                        split, self._fetcher)
                if chunks is not None:
                    outcome = 'remote_hit'
                    if prov_on and int(self._m_cluster[
                            'cache_peer_fills'].value) > peer_fills_before:
                        outcome = 'peer_fill'
                    self._serve_cached_split(split, chunks, decode_out,
                                             ship_spans, t0, outcome)
                    continue
                factory = self._reader_factories.get(tenant)
                if factory is None:
                    factory = self._resolve_factory(tjob)
                    self._reader_factories[tenant] = factory
                reader = factory(
                    tjob['dataset_url'], piece_indices=split['indices'],
                    num_epochs=1, shuffle_row_groups=False,
                    **self._reader_kwargs(tjob))
                seq = 0
                rows = 0
                out_bytes = 0
                tags = set()
                with reader:
                    for item in reader:
                        chunk = (item._asdict() if hasattr(item, '_asdict')
                                 else dict(item))
                        cid = '%d/%d' % (split['split_id'], seq)
                        tag, payload = self._serialize_split_chunk(
                            split, chunk, cid, spans)
                        tags.add(tag)
                        rows += len(next(iter(chunk.values())))
                        out_bytes += len(payload)
                        decode_out.put(('chunk', split, seq, tag, payload))
                        seq += 1
                t1 = time.monotonic()
                self._m_decode_hist.observe(t1 - t0)
                # Per-tenant cache-plane budget (ISSUE 16): the split's
                # serialized bytes approximate what its reader filled
                # into the plane; the charge that crosses the budget
                # turns the tenant's FUTURE readers plane-less (the
                # files already on disk stay — they are the plane's to
                # evict).
                if tjob.get('cache_plane') \
                        and tenant not in self._cache_over_budget \
                        and self._cache_quota.budget(tenant) is not None \
                        and not self._cache_quota.charge(tenant,
                                                         out_bytes):
                    self._cache_over_budget.add(tenant)
                    logger.warning(
                        'tenant %r cache-plane budget exhausted; its '
                        'readers degrade to direct decode', tenant)
                spans.append({'name': 'service/decode_split', 't0': t0,
                              't1': t1, 'pid': os.getpid(),
                              'tid': threading.get_ident(),
                              'cid': str(split['split_id']),
                              'args': {'rows': rows}})
                # Cache-plane fills land in the PLANE's own span buffer,
                # and the plane instance is per-split — draining it here
                # claims exactly this split's fills, even with several
                # in-process workers sharing the process (the global
                # singleton would race them).
                plane_spans = getattr(
                    getattr(reader, '_cache', None), 'spans', None)
                if plane_spans is not None:
                    spans.extend(plane_spans.drain())
                # Ingest-plane fetch/hedge spans (ISSUE 14) ride the same
                # split 'end' header — the per-split plane's buffer is
                # this split's fetch activity, exactly.
                ingest_spans = getattr(
                    getattr(reader, 'ingest_plane', None), 'spans', None)
                if ingest_spans is not None:
                    spans.extend(ingest_spans.drain())
                record = None
                if prov_on:
                    # The plane instance is per-split, so its lifetime
                    # totals ARE this split's cache outcome.
                    cache_stats = getattr(
                        getattr(reader, '_cache', None), 'stats', None)
                    record = self._split_record(
                        split, {'decode': [t0, t1]}, spans, tags,
                        provenance.cache_outcome(_ZERO_CACHE, cache_stats),
                        worker_args=getattr(reader, '_worker_args', None),
                        sched={'policy': getattr(reader, 'scheduling',
                                                 None)})
                if not ship_spans:
                    spans = []
                decode_out.put(('end', split, seq, rows,
                                spans[-_MAX_SPANS_PER_SPLIT:], record))
                self._accumulate_cache_stats(reader)
                self._accumulate_ingest_stats(reader)
                if self._cluster is not None and self._cluster.ready() \
                        and tjob.get('dataset_url') == job.get(
                            'dataset_url'):
                    # The per-split reader's plane just published this
                    # split's entries: advertise them on the next beat
                    # without waiting for the listdir refresh.
                    self._cluster.note_published(
                        self._cluster.identity.split_digests(
                            split['indices']))
                self._m_rows.inc(rows)
                self._m_splits.inc()
                if self._trace is not None:
                    self._trace.event('service/decode_split', t0, t1,
                                      split=split['split_id'], rows=rows)
            except Exception:  # noqa: BLE001 — shipped to the event loop
                decode_out.put(('error', split, traceback.format_exc()))

    # -- metrics -------------------------------------------------------------

    @property
    def diagnostics(self):
        """Per-worker metrics — a view over ``self.metrics`` (ISSUE 5),
        also shipped to the dispatcher on every heartbeat (``stats`` RPC
        surfaces them fleet-wide)."""
        elapsed = (time.monotonic() - self._t_start) if self._t_start else 0.0
        rows = int(self._m_rows.value)
        return {
            'rows_decoded': rows,
            'splits_decoded': int(self._m_splits.value),
            'rows_per_s': round(rows / elapsed, 1) if elapsed > 0 else 0.0,
            'queue_depth': (self._decode_out.qsize()
                            if self._decode_out is not None else 0),
            # shm result-plane traffic INCLUDING the degrades: a worker
            # silently on the byte path (arena full, /dev/shm gone) must
            # be visible fleet-wide, not only in its own process.  The
            # arena shares this registry, so its refusals land here.
            'shm_chunks': int(self._m_shm_chunks.value),
            'shm_degraded': int(self.metrics.counter('shm_degraded').value),
            # Epoch-cache plane traffic of this worker's split readers
            # (all zero unless the job enables cache_plane).
            # cache_degraded matters most fleet-wide: it is the only
            # signal that a plane is silently OFF (unwritable dir, full
            # tiers) while hits/misses still look plausible.
            'cache_hits': int(self._m_cache['cache_hits'].value),
            'cache_misses': int(self._m_cache['cache_misses'].value),
            'cache_evictions': int(self._m_cache['cache_evictions'].value),
            'cache_ram_hits': int(self._m_cache['cache_ram_hits'].value),
            'cache_degraded': int(self._m_cache['cache_degraded'].value),
            # Cluster cache tier (ISSUE 10): served-from-plane pieces,
            # peer fetches that replaced a decode, and peer fetches that
            # failed back to direct decode.  peer_degraded is the fleet
            # signal that entries exist somewhere but cannot flow.
            'cache_remote_hits':
                int(self._m_cluster['cache_remote_hits'].value),
            'cache_peer_fills':
                int(self._m_cluster['cache_peer_fills'].value),
            'cache_peer_degraded':
                int(self._m_cluster['cache_peer_degraded'].value),
            # Unified backoff telemetry (ISSUE 15): summed fleet-wide in
            # the dispatcher's control_plane rollup — climbing giveups
            # fleet-wide is the retry-storm / dead-control-plane signal.
            'retry_attempts': int(self._m_retry['retry_attempts'].value),
            'retry_giveups': int(self._m_retry['retry_giveups'].value),
            # Per-tenant quota enforcement (ISSUE 16): chunks pushed to
            # the byte path by an shm budget and readers built without
            # the cache plane by a cache budget — degrades, not stalls,
            # so only these counters make them visible fleet-wide.
            'shm_quota_degraded':
                int(self._m_quota['shm_quota_degraded'].value),
            'cache_quota_degraded':
                int(self._m_quota['cache_quota_degraded'].value),
            'draining': bool(self._drain.is_set()),
        }

    def heartbeat_stats(self):
        """The heartbeat payload: ``diagnostics`` plus the telemetry
        piggyback — the full registry snapshot (stage histograms merge
        fleet-wide by addition in the dispatcher), the EWMA clock offset
        for span alignment with its drift-vs-registration estimate,
        this process's decision-journal payload (ISSUE 20 — worker-side
        quota/hedge/autotuner/residency decisions reach the dispatcher
        rollup on the channel that already exists), and the pid for
        timeline labels."""
        from petastorm_tpu.telemetry import decisions as _decisions
        return dict(self.diagnostics,
                    registry=self.metrics.snapshot(),
                    clock_offset=self.clock_offset,
                    clock_drift_ms=self.clock_drift_ms,
                    decisions=_decisions.heartbeat_payload(),
                    pid=os.getpid())
