"""JAX-side client of the data service: ``ServiceDataLoader``.

A drop-in peer of ``petastorm_tpu.jax.DataLoader`` whose "reader" is the
service instead of a local decode pool: the connection subscribes to
every registered decode worker (rotated by consumer index so hosts
spread their first pulls — the ``jax.process_index()``-keyed round-robin
of the sharding contract), pulls serialized chunks under credit-based
backpressure, and commits *whole splits*:

* chunks of a split buffer until the worker's ``end`` marker arrives —
  a worker death mid-split leaves only a discarded partial buffer, never
  half-delivered rows;
* a completed split is ACKed to the worker (which only then reports
  ``complete`` to the dispatcher) and deduped by split id, so a split
  re-streamed after lease reassignment is delivered exactly once;
* ``ordered=True`` releases splits in ascending split-id order; the
  default releases them as workers finish (lowest latency).  Row order
  WITHIN a split follows the worker's per-split reader, so full
  determinism additionally needs a deterministic split reader
  (``reader_kwargs={'workers_count': 1}`` in the job config).

Resume follows the existing loader contract: ``state_dict()`` →
``resume_state=``.  The service part of the token is the set of split
ids this consumer has committed plus the partition-geometry fingerprint;
restoring against a fresh service run retires those splits at the
dispatcher (no re-decode) and the DataLoader machinery restores the
sub-split residue (partial batches, buffered chunks) exactly as the
local loaders do.
"""

import logging
import pickle
import queue
import threading
import time

from petastorm_tpu.errors import ServiceError
from petastorm_tpu.jax.loader import DataLoader
from petastorm_tpu.service import tenancy
from petastorm_tpu.service.worker import _Rpc, deserialize_chunk
from petastorm_tpu.telemetry import merge_into_recorder, provenance
from petastorm_tpu.utils import backoff

logger = logging.getLogger(__name__)


class _ServiceConnection(object):  # ptlint: disable=pickle-unsafe-attrs — one per consumer process; the resume token (state_dict) is the only thing that crosses processes
    """One consumer's connection: dispatcher RPCs + a DEALER per worker."""

    def __init__(self, dispatcher_addr, consumer=None, resume=None,
                 ordered=False, queue_splits=4, credits=None,
                 rpc_timeout_s=20.0, trace_recorder=None, tenant=None):
        import zmq

        self._zmq = zmq
        self._dispatcher_addr = dispatcher_addr
        #: Which tenant's job this connection consumes (ISSUE 16).  None
        #: asks for the dispatcher's own (default) job — the tenant-less
        #: wire shape every pre-tenancy client sends.
        self.tenant = None if tenant is None else str(tenant)
        self._context = zmq.Context()
        self._rpc_timeout_s = rpc_timeout_s
        #: optional ``benchmark.TraceRecorder``: worker spans riding the
        #: ``end`` headers merge into it after clock-offset alignment —
        #: one Perfetto timeline across client + every decode worker.
        self._trace = trace_recorder
        #: (client_clock - dispatcher_clock), refreshed from the 1 Hz
        #: ``workers`` discovery poll's send/recv midpoint.
        self._clock_offset = None
        self._worker_offsets = {}   # data addr -> (worker - dispatcher)
        self._labeled_pids = set()
        try:
            self._init(consumer, resume or {}, ordered, queue_splits,
                       credits)
        except Exception:
            from petastorm_tpu.workers_pool import shm_plane
            shm_plane.remove_probe(getattr(self, '_shm_probe', None))
            self._context.term()
            raise

    def _init(self, consumer, resume, ordered, queue_splits, credits):
        rpc = _Rpc(self._context, self._dispatcher_addr,
                   timeout_s=self._rpc_timeout_s)
        try:
            request = {'op': 'job'}
            if self.tenant is not None:
                request['tenant'] = self.tenant
            self.job = rpc.call(request)['job']
        finally:
            rpc.close()
        # The effective tenant (the job's own id) — subscribes and the
        # resume token carry THIS, so a tenant-less connection to the
        # default job round-trips as 'default' everywhere downstream.
        self.tenant = str(self.job.get('tenant') or tenancy.DEFAULT_TENANT)
        if consumer is None:
            consumer = _default_consumer(self.job['num_consumers'])
        if not 0 <= consumer < self.job['num_consumers']:
            raise ServiceError('consumer must be in [0, %d), got %r'
                               % (self.job['num_consumers'], consumer))
        self.consumer = int(consumer)
        # Geometry FIRST: a mismatched token's split ids index a different
        # partition, and the mark_consumed below would permanently retire
        # live splits of THIS job before the error could raise.
        _check_resume_geometry(resume, self)
        self._credits = int(credits if credits is not None
                            else self.job['credits'])
        self._ordered = bool(ordered)
        # Tenant jobs live in a GLOBAL split-id space starting at
        # split_base; the consumer-modulo shard is over the tenant-LOCAL
        # index so every tenant's consumers spread the same way the
        # single-tenant (base 0) job always did.
        base = int(self.job.get('split_base', 0))
        self._my_splits = [base + i for i in range(self.job['num_splits'])
                           if i % self.job['num_consumers'] == self.consumer]
        # Same-host shm delivery: create the /dev/shm probe whose
        # visibility proves to a worker that descriptors will map here.
        # Workers without sight of it (cross-host) keep the byte path.
        from petastorm_tpu.workers_pool import shm_plane
        self._shm_probe = None
        if self.job.get('shm', True) and shm_plane.available():
            try:
                self._shm_probe = shm_plane.make_probe()
            except OSError as e:
                # e.g. /dev/shm writable but full (ENOSPC): the fallback
                # matrix promises byte-path delivery, not a dead client.
                logger.warning('cannot create shm probe (%s); same-host '
                               'delivery will use the byte path', e)
        self.shm_chunks = 0
        #: Discovery-poll retries scheduled under the shared backoff
        #: policy (ISSUE 15) — nonzero means the dispatcher was
        #: unreachable at some point this connection rode through.
        self.retry_attempts = 0
        self.consumed = set(int(s) for s in resume.get('consumed') or ())
        unknown = self.consumed - set(self._my_splits)
        if unknown:
            raise ServiceError(
                'resume token holds split ids %s that do not belong to '
                'consumer %d of this job' % (sorted(unknown)[:5],
                                             self.consumer))
        if self.consumed:
            rpc = _Rpc(self._context, self._dispatcher_addr,
                       timeout_s=self._rpc_timeout_s)
            try:
                rpc.call({'op': 'mark_consumed',
                          'split_ids': sorted(self.consumed)})
            finally:
                rpc.close()
        #: complete splits ready for the reader: (split_id, [chunk dicts]);
        #: bounded — a full queue stops the receiver from reading sockets,
        #: which stops credit replenishment, which stalls the workers.
        self._ready = queue.Queue(maxsize=max(1, int(queue_splits)))
        self._error = None
        self._ended = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._recv_loop,
                                        name='service-client-recv',
                                        daemon=True)
        self._thread.start()

    # -- consumption (reader thread) -----------------------------------------

    def next_split(self):
        """Next complete, not-yet-delivered split: ``(split_id, chunks)``;
        None at end of stream.  A receive-loop failure raises here — a
        dead receiver must not masquerade as a clean (rows-missing) end
        of stream.  With a trace recorder attached the wait is recorded
        as a ``service/split_wait`` span — the 'no split was ready'
        component of a data stall (lease starvation, slow workers)."""
        t_wait = time.monotonic()
        item = self._next_split()
        if self._trace is not None:
            self._trace.event('service/split_wait', t_wait, time.monotonic())
        return item

    def _next_split(self):
        while True:
            if self._ended.is_set() and self._ready.empty():
                if self._error is not None:
                    raise ServiceError(
                        'service receive loop died: %s: %s'
                        % (type(self._error).__name__, self._error))
                return None
            try:
                return self._ready.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return None

    def drain_ready(self):
        """Pop every split currently buffered client-side (non-blocking) —
        the service leg of the loader's exact-checkpoint drain."""
        drained = []
        while True:
            try:
                drained.append(self._ready.get_nowait())
            except queue.Empty:
                return drained

    def commit(self, split_id):
        self.consumed.add(int(split_id))

    def stop(self):
        self._stop.set()

    def join(self):
        self._thread.join()
        self._context.term()

    # -- receive loop --------------------------------------------------------

    def _recv_loop(self):
        from petastorm_tpu.workers_pool import shm_plane

        zmq = self._zmq
        rpc = _Rpc(self._context, self._dispatcher_addr,
                   timeout_s=self._rpc_timeout_s)
        sockets = {}            # worker data addr -> DEALER
        poller = zmq.Poller()
        buffers = {}            # (split_id, attempt) -> {seq: (tag, payload)}
        received = set(self.consumed)
        remaining = set(self._my_splits) - received
        held = {}               # ordered mode: completed, awaiting turn
        order = [sid for sid in self._my_splits if sid not in received]
        next_refresh = 0.0
        #: Active backoff episode across consecutive discovery-poll
        #: failures (ISSUE 15): a healthy poll runs at a JITTERED ~1 Hz
        #: (a consumer fleet spreads over the second instead of
        #: arriving in phase), and a dead/restarting dispatcher sees
        #: exponentially-paced retries, not a synchronized 1 Hz hammer
        #: from every training host at once.
        discovery_retry = None
        addr_of = {}            # DEALER -> worker data addr (span origin)
        try:
            while remaining and not self._stop.is_set():
                now = time.monotonic()
                if now >= next_refresh:
                    try:
                        t_rpc0 = time.monotonic()
                        reply = rpc.call({'op': 'workers'})
                        t_rpc1 = time.monotonic()
                        workers = reply['workers']
                        if reply.get('t_mono') is not None:
                            # The discovery poll doubles as the clock
                            # handshake: (client - dispatcher) from the
                            # send/recv midpoint (ISSUE 5).  EWMA over
                            # the 1 Hz polls (ISSUE 7): one rtt-skewed
                            # poll must not yank the whole timeline, and
                            # a long run tracks genuine drift instead of
                            # freezing the first estimate.
                            estimate = ((t_rpc0 + t_rpc1) / 2.0
                                        - float(reply['t_mono']))
                            self._clock_offset = (
                                estimate if self._clock_offset is None
                                else 0.8 * self._clock_offset
                                + 0.2 * estimate)
                        for worker in workers:
                            if worker.get('clock_offset') is not None:
                                self._worker_offsets[worker['addr']] = \
                                    float(worker['clock_offset'])
                        discovery_retry = None
                        next_refresh = now + backoff.jittered(1.0, 0.2)
                    except ServiceError:
                        workers, reply = [], {}
                        discovery_retry = discovery_retry or \
                            backoff.DISCOVERY_POLICY.episode()
                        self.retry_attempts += 1
                        next_refresh = now + discovery_retry.next_delay()
                    failed = set(reply.get('failed_splits') or ()) & remaining
                    if failed:
                        # The dispatcher gave up on these (attempt ceiling):
                        # surface a terminal error instead of waiting on
                        # rows that will never stream.
                        raise ServiceError(
                            'split(s) %s of consumer %d failed every decode '
                            'attempt at the dispatcher'
                            % (sorted(failed)[:5], self.consumer))
                    stale = set(reply.get('retired_splits') or ()) \
                        & remaining
                    if stale:
                        # A ledger-restored dispatcher retired these in a
                        # PREVIOUS incarnation: they will never stream
                        # again, and this connection holds no token that
                        # accounts for them (a live client's remaining
                        # set already excludes everything it received) —
                        # raise instead of hanging forever.
                        raise ServiceError(
                            'split(s) %s of consumer %d were delivered '
                            'and retired before this dispatcher '
                            'restarted (restored ledger): resume with '
                            'the matching token, or point the '
                            'dispatcher at a fresh ledger_path for a '
                            'fresh epoch' % (sorted(stale)[:5],
                                             self.consumer))
                    # Rotate by consumer index: host c starts its pulls at
                    # worker c % W instead of every host hammering worker 0.
                    if workers:
                        c = self.consumer % len(workers)
                        workers = workers[c:] + workers[:c]
                    for worker in workers:
                        addr = worker['addr']
                        if addr in sockets:
                            continue
                        sock = self._context.socket(zmq.DEALER)
                        sock.setsockopt(zmq.LINGER, 0)
                        sock.set_hwm(0)
                        sock.connect(addr)
                        sock.send(pickle.dumps(
                            {'type': 'subscribe', 'consumer': self.consumer,
                             'tenant': self.tenant,
                             'credits': self._credits,
                             'shm_probe': self._shm_probe}, protocol=4))
                        sockets[addr] = sock
                        addr_of[sock] = addr
                        poller.register(sock, zmq.POLLIN)
                for sock in dict(poller.poll(100)):
                    while True:
                        try:
                            frames = sock.recv_multipart(zmq.NOBLOCK)
                        except zmq.Again:
                            break
                        header = pickle.loads(frames[0])
                        sid = int(header['split'])
                        attempt = int(header['attempt'])
                        if header['type'] == 'chunk':
                            # replenish immediately: in-flight chunks stay
                            # bounded by the credit window; backpressure
                            # comes from this loop blocking on _ready.put
                            sock.send(pickle.dumps({'type': 'credit', 'n': 1},
                                                   protocol=4))
                            if sid in received:
                                # duplicate stream: drop quietly — but a
                                # dropped shm descriptor must still return
                                # its segment to the writer.
                                if header['tag'] == b'S':
                                    shm_plane.release_descriptor(
                                        pickle.loads(frames[1]))
                                continue
                            if header['tag'] == b'S':
                                # Map NOW: the arrays are zero-copy views
                                # over the shared slab pages, and the
                                # slab returns to the worker the moment
                                # the last view dies (generation stamp
                                # from a weakref.finalize).
                                try:
                                    chunk = shm_plane.read_payload(
                                        pickle.loads(frames[1]))
                                except shm_plane.SegmentVanishedError:
                                    # Writer stopped/died before we
                                    # attached: the chunk is lost, the
                                    # count mismatch at 'end' requests a
                                    # resend.
                                    continue
                                self.shm_chunks += 1
                                buffers.setdefault((sid, attempt), {})[
                                    int(header['seq'])] = ('shm', chunk)
                                continue
                            buffers.setdefault((sid, attempt), {})[
                                int(header['seq'])] = (header['tag'],
                                                       frames[1])
                        elif header['type'] == 'end':
                            if sid in received:
                                # Duplicate stream: re-ack so the worker's
                                # completion bookkeeping settles (the
                                # dispatcher side is idempotent).
                                sock.send(pickle.dumps(
                                    {'type': 'ack', 'split': sid,
                                     'attempt': attempt}, protocol=4))
                                continue
                            parts = buffers.get((sid, attempt), {})
                            if len(parts) != int(header['chunks']):
                                # Chunks lost (routed to a stale identity
                                # across a client reconnect): NOT acked —
                                # an ack here would let the worker report
                                # complete on rows we never got.  Ask for
                                # a re-decode instead.
                                logger.warning(
                                    'split %d attempt %d: %d/%d chunks — '
                                    'discarding partial buffer and '
                                    'requesting resend', sid, attempt,
                                    len(parts), int(header['chunks']))
                                buffers.pop((sid, attempt), None)
                                sock.send(pickle.dumps(
                                    {'type': 'resend', 'split': sid,
                                     'attempt': attempt}, protocol=4))
                                continue
                            # Complete: ack — only now may the worker
                            # report the split complete to the dispatcher.
                            sock.send(pickle.dumps(
                                {'type': 'ack', 'split': sid,
                                 'attempt': attempt}, protocol=4))
                            self._merge_worker_spans(header,
                                                     addr_of.get(sock))
                            record = self._align_provenance(
                                header, addr_of.get(sock))
                            chunks = [parts[i][1] if parts[i][0] == 'shm'
                                      else deserialize_chunk(*parts[i])
                                      for i in sorted(parts)]
                            received.add(sid)
                            remaining.discard(sid)
                            for key in [k for k in buffers if k[0] == sid]:
                                del buffers[key]
                            if self._ordered:
                                held[sid] = (chunks, record)
                                while order and order[0] in held:
                                    nxt = order.pop(0)
                                    nxt_chunks, nxt_record = held.pop(nxt)
                                    self._put((nxt, nxt_chunks, nxt_record))
                            else:
                                self._put((sid, chunks, record))
        except Exception as e:  # noqa: BLE001 — re-raised in next_split
            # Without this, a crashed receiver would look exactly like a
            # clean (rows-missing!) end of stream to the consumer.
            self._error = e
        finally:
            self._ended.set()
            rpc.close()
            # Clean end of stream: the LAST split's ack may still sit in
            # ZMQ's outbound queue — a zero-linger close would discard it
            # and leave the worker replaying an already-delivered split.
            # User abort keeps the instant close.
            linger_ms = 0 if self._stop.is_set() else 1000
            for sock in sockets.values():
                sock.close(linger_ms)
            shm_plane.remove_probe(self._shm_probe)
            # Reclaim segments whose writer was SIGKILLed with descriptors
            # in flight (nothing else will ever unlink them); live
            # workers' segments are untouched.
            if self._shm_probe is not None:
                shm_plane.sweep_orphans()

    def _merge_worker_spans(self, header, addr):
        """Land a split's worker spans on this process's timeline: shift
        by the chained offsets (client-dispatcher from the discovery
        poll, worker-dispatcher from the worker's registration handshake
        — ``(C-D) - (W-D) = C-W``), label the worker's Perfetto track,
        merge.  Missing offsets (worker pre-first-heartbeat) fall back to
        0 — correct between same-host processes, where CLOCK_MONOTONIC is
        shared."""
        spans = header.get('spans')
        if not spans or self._trace is None:
            return
        shift = 0.0
        worker_offset = self._worker_offsets.get(addr)
        if self._clock_offset is not None and worker_offset is not None:
            shift = self._clock_offset - worker_offset
        pid = spans[0].get('pid')
        if pid is not None and pid not in self._labeled_pids:
            self._labeled_pids.add(pid)
            import os
            if pid != os.getpid():
                # In-process (thread) workers share our pid: labeling it
                # would rename the CLIENT's own track.
                self._trace.set_process_label(
                    pid, 'service worker %s' % (addr or '?'))
        merge_into_recorder(self._trace, spans, clock_offset_s=shift)

    def _align_provenance(self, header, addr):
        """The split's provenance record (ISSUE 13) with its stage
        windows shifted onto THIS process's monotonic clock — the same
        chained-offset math :meth:`_merge_worker_spans` applies — plus a
        receive timestamp so the consumer can account buffer-wait.

        Unlike the span path (which only renders timelines), provenance
        COMPUTES cross-clock differences (``latency_ms`` feeds the
        worst-K and the SLO watchdog), so an unalignable record is
        dropped rather than shifted by 0: a cross-host worker whose
        offset has not arrived yet (pre-first-heartbeat) would otherwise
        journal a latency equal to the inter-host boot skew, permanently
        poisoning the rolling worst-K.  Same-host workers (shared
        CLOCK_MONOTONIC) pass the sanity gate unshifted."""
        record = header.get('provenance')
        if record is None or not provenance.enabled():
            return None
        now = time.monotonic()
        worker_offset = self._worker_offsets.get(addr)
        if self._clock_offset is not None and worker_offset is not None:
            record = provenance.shift_stages(
                record, self._clock_offset - worker_offset)
        stages = record.get('stages') or {}
        latest = max((w[1] for w in stages.values()), default=now)
        if abs(now - latest) > 60.0:
            # Unaligned (or mis-aligned) clocks: the stage windows are
            # nowhere near this client's present — journaling them would
            # fabricate an hours-long batch.
            return None
        record['_received_t'] = now
        return record

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._ready.put(item, timeout=0.2)
                return
            except queue.Full:
                continue


def register_tenant_job(dispatcher_addr, tenant, config_kwargs, weight=1.0,
                        rpc_timeout_s=20.0, max_wait_s=120.0):
    """Register ``tenant``'s job on a running dispatcher (ISSUE 16).

    ``config_kwargs`` are :class:`~petastorm_tpu.service.config.
    ServiceConfig` keyword arguments (``dataset_url`` at minimum); the
    dispatcher builds the config, appends the tenant's splits to the
    global id space, and every registered worker starts serving them
    under the fair-share schedule — no new fleet.

    Admission is bounded (``max_tenant_jobs``): a refusal past the cap
    carries ``retry_after_s`` and this helper queues-with-backoff up to
    ``max_wait_s`` before raising a clear :class:`ServiceError`.  Any
    other refusal (duplicate tenant, bad config) raises immediately.

    Returns the registered job's ``job_info`` dict (``split_base``,
    ``num_splits``, ...), which a :class:`ServiceDataLoader` constructed
    with ``tenant=`` then consumes.
    """
    import zmq

    context = zmq.Context()
    try:
        rpc = _Rpc(context, dispatcher_addr, timeout_s=rpc_timeout_s)
        try:
            deadline = time.monotonic() + max_wait_s
            while True:
                # raw=True: an admission refusal is a structured reply
                # (error + retry_after_s), not an exception — we need to
                # read the retry hint before deciding to raise.
                reply = rpc.call(
                    {'op': 'register_job', 'tenant': str(tenant),
                     'weight': float(weight),
                     'config': dict(config_kwargs)}, raw=True)
                if isinstance(reply, dict) and reply.get('job') is not None:
                    return reply['job']
                error = (reply or {}).get('error', 'malformed reply')
                retry_after = (reply or {}).get('retry_after_s')
                if retry_after is None:
                    raise ServiceError(
                        'dispatcher %s refused tenant %r job: %s'
                        % (dispatcher_addr, tenant, error))
                delay = backoff.jittered(float(retry_after), 0.25)
                if time.monotonic() + delay > deadline:
                    raise ServiceError(
                        'dispatcher %s still refusing tenant %r job '
                        'after %.0fs (%s) — raise max_tenant_jobs or '
                        'retire a finished job' % (dispatcher_addr, tenant,
                                                   max_wait_s, error))
                time.sleep(delay)
        finally:
            rpc.close()
    finally:
        context.term()


def _default_consumer(num_consumers):
    """The sharding contract's default: this training host's index."""
    try:
        import jax

        from petastorm_tpu.utils import apply_jax_platforms_env
        apply_jax_platforms_env()
        return jax.process_index() % num_consumers
    except Exception:  # noqa: BLE001 — jax absent/uninitialized: consumer 0
        return 0


class ServiceReader(object):
    """Reader-shaped adapter over a service connection.

    Implements exactly the surface ``petastorm_tpu.jax.DataLoader``
    consumes (iteration, ``batched_output``, ``stop``/``join``,
    ``drain_in_flight``/``resume_dispatch``/``state_dict``), yielding
    columnar chunk dicts.  A split's chunks are committed to the consumed
    set the moment they enter the loader machinery — from then on the
    loader's own snapshot carries any not-yet-yielded residue, which is
    what makes the combined token exact.
    """

    batched_output = True
    ngram = None
    num_epochs = 1

    def __init__(self, connection):
        self._conn = connection
        self._current = []
        #: Per-batch provenance (ISSUE 13): clock-aligned split records
        #: adopted as their chunks enter the loader, drained per host
        #: batch by ``DataLoader`` via :meth:`take_provenance`.
        self._pending_provenance = []
        self.last_row_consumed = False

    @property
    def job(self):
        return self._conn.job

    @property
    def consumer(self):
        return self._conn.consumer

    def __iter__(self):
        return self

    def __next__(self):
        while not self._current:
            item = self._conn.next_split()
            if item is None:
                self.last_row_consumed = True
                raise StopIteration
            split_id, chunks, record = item
            self._conn.commit(split_id)
            self._current = list(chunks)
            self._adopt_provenance(record)
        return self._current.pop(0)

    def _adopt_provenance(self, record):
        if record is None:
            return
        received = record.pop('_received_t', None)
        now = time.monotonic()
        if received is not None and now > received:
            # Time the complete split sat in the client buffer before
            # the consumer took it — part of the causal chain.
            record.setdefault('stages', {})['client_buffer'] = [received,
                                                                now]
        self._pending_provenance.append(record)
        del self._pending_provenance[:-64]

    def take_provenance(self):
        """Provenance records of the splits adopted since the last call
        (the loader-facing surface `Reader.take_provenance` also has)."""
        out = list(self._pending_provenance)
        self._pending_provenance = []
        return out

    # -- exact-checkpoint support -------------------------------------------

    def drain_in_flight(self):
        drained = list(self._current)
        self._current = []
        for split_id, chunks, record in self._conn.drain_ready():
            self._conn.commit(split_id)
            self._adopt_provenance(record)
            drained.extend(chunks)
        return drained

    def resume_dispatch(self):
        pass  # dispatch is remote; nothing was paused

    def state_dict(self):
        return {'service': {
            'version': 1,
            'consumer': self._conn.consumer,
            'tenant': self._conn.tenant,
            'consumed': sorted(self._conn.consumed),
            'num_splits': self._conn.job['num_splits'],
            'num_consumers': self._conn.job['num_consumers'],
            'fingerprint': self._conn.job['fingerprint'],
        }}

    def stop(self):
        self._conn.stop()

    def join(self):
        self._conn.join()


class ServiceDataLoader(DataLoader):
    """``petastorm_tpu.jax.DataLoader`` fed by the data service.

    Same constructor surface as ``DataLoader`` minus the reader (the
    service is the reader), plus:

    Args:
        dispatcher_addr: the dispatcher's control endpoint
            (``tcp://host:port``).
        consumer: which consumer shard this host is; defaults to
            ``jax.process_index() % num_consumers`` — the service analog
            of the readers' JAX auto-sharding.
        tenant: which tenant's job to consume on a shared fleet
            (ISSUE 16); None (the default) consumes the dispatcher's own
            job — exactly the pre-tenancy behavior.  Register other
            tenants' jobs first via :func:`register_tenant_job`.
        ordered: release splits in split-id order (deterministic) instead
            of completion order.
        queue_splits / credits / rpc_timeout_s: client-side flow control;
            ``credits`` defaults to the job's configured window.

    Everything else (``batch_size``, ``transform_fn``, ``drop_last``,
    ``prefetch``, ``device``/``sharding``, ``resume_state``, ``echo``,
    ``trace_recorder``) behaves exactly as on ``DataLoader``; resume
    tokens round-trip through ``state_dict()`` with the service position
    (committed split ids) in place of the ventilator cursor.
    """

    def __init__(self, dispatcher_addr, batch_size, consumer=None,
                 ordered=False, queue_splits=4, credits=None,
                 rpc_timeout_s=20.0, resume_state=None, tenant=None,
                 **kwargs):
        svc = ((resume_state or {}).get('reader') or {}).get('service') or {}
        if svc and consumer is None:
            consumer = svc.get('consumer')
        if svc and tenant is None:
            tenant = svc.get('tenant')
        connection = _ServiceConnection(
            dispatcher_addr, consumer=consumer, resume=svc,
            ordered=ordered, queue_splits=queue_splits, credits=credits,
            rpc_timeout_s=rpc_timeout_s, tenant=tenant,
            # The loader's recorder doubles as the merge target for the
            # workers' spans: ONE timeline from rowgroup decode to H2D.
            trace_recorder=kwargs.get('trace_recorder'))
        super(ServiceDataLoader, self).__init__(
            ServiceReader(connection), batch_size,
            resume_state=resume_state, **kwargs)

    def service_diagnostics(self):
        """Fleet-wide service metrics (dispatcher ``stats`` RPC): split
        queue depths, lease churn, per-worker rows/s."""
        conn = self.reader._conn
        rpc = _Rpc(conn._context, conn._dispatcher_addr,
                   timeout_s=conn._rpc_timeout_s)
        try:
            return rpc.call({'op': 'stats'})
        finally:
            rpc.close()


def _check_resume_geometry(svc, connection):
    """Service analog of ``Reader._check_resume_topology``: a token's
    split ids index one partition geometry; any drift (dataset, split
    size, consumer count) must raise, not silently skip/replay rows."""
    if not svc:
        return
    mismatches = [
        key for key, current in (
            ('fingerprint', connection.job['fingerprint']),
            ('num_splits', connection.job['num_splits']),
            ('num_consumers', connection.job['num_consumers']),
            ('consumer', connection.consumer),
            ('tenant', connection.tenant))
        if svc.get(key) is not None and svc[key] != current]
    if mismatches:
        raise ServiceError(
            'resume token was taken under a different service job '
            '(mismatched: %s) — its split ids do not index this '
            'partition geometry' % ', '.join(mismatches))
