"""``petastorm-tpu-data-service`` — run/inspect the data service.

Three-command quickstart (one dispatcher, N decode hosts, then point
``ServiceDataLoader`` at the dispatcher from the training job)::

    petastorm-tpu-data-service dispatcher \
        --bind tcp://0.0.0.0:7777 --dataset-url file:///data/train \
        --num-consumers 4
    petastorm-tpu-data-service worker --dispatcher tcp://dispatch:7777
    petastorm-tpu-data-service status --dispatcher tcp://dispatch:7777

``status`` is a one-shot JSON dump; for a live terminal view of the same
``stats`` RPC (per-worker throughput, fleet stage p50/p99, cache/shm
hit-and-degrade rates) use ``petastorm-tpu-top`` (ISSUE 5).
"""

import argparse
import json
import logging
import sys
import time


def _build_parser():
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-data-service',
        description='Disaggregated data-loading service '
                    '(petastorm_tpu.service)')
    sub = parser.add_subparsers(dest='command', required=True)

    d = sub.add_parser('dispatcher', help='run the control plane')
    d.add_argument('--bind', default='tcp://127.0.0.1:7777',
                   help='REP endpoint to serve on (tcp://host:port; '
                        'port * picks a free one)')
    d.add_argument('--dataset-url', required=True)
    d.add_argument('--num-consumers', type=int, default=1,
                   help='number of consuming training hosts '
                        '(split i belongs to consumer i %% N)')
    d.add_argument('--rowgroups-per-split', type=int, default=2)
    d.add_argument('--lease-ttl-s', type=float, default=10.0)
    d.add_argument('--credits', type=int, default=8)
    d.add_argument('--reader-factory', default='auto',
                   choices=('auto', 'reader', 'batch_reader'))
    d.add_argument('--workers-count', type=int, default=None,
                   help='decode threads per split reader on each worker')
    d.add_argument('--cache-plane-dir', default=None,
                   help='enable the tiered epoch-cache plane: decode '
                        'workers publish decoded batches under this '
                        '(host-local) directory and serve later '
                        'epochs/runs from it (petastorm_tpu/cache_plane)')
    d.add_argument('--cache-plane-ram-bytes', type=int, default=None,
                   help='hot /dev/shm tier cap (default 128 MiB)')
    d.add_argument('--cache-plane-disk-bytes', type=int, default=None,
                   help='disk tier cap (default 4 GiB)')
    d.add_argument('--no-cluster-cache', action='store_true',
                   help='disable the cluster cache tier (on by default '
                        'whenever the cache plane is enabled): no '
                        'cache-affinity lease routing, no remote HIT '
                        'serving, no peer fill — '
                        'PETASTORM_TPU_NO_CLUSTER_CACHE=1 is the '
                        'equivalent kill switch')
    d.add_argument('--ingest', default='auto',
                   choices=('auto', 'plane', 'off'),
                   help='async byte-range ingest plane mode for every '
                        "per-split reader (see make_reader(ingest=)); "
                        "'auto' enables it on non-local dataset "
                        'filesystems — the object-store case decode '
                        'workers exist for; PETASTORM_TPU_NO_INGEST_'
                        'PLANE=1 is the kill switch')
    d.add_argument('--ledger-path', default=None,
                   help='durable dispatcher ledger file (ISSUE 15): '
                        'split states, attempt counters, and the cache '
                        'directory persist crash-safely, and a '
                        'restarted dispatcher pointed at the same path '
                        '(and port) resumes the job instead of '
                        're-decoding the world')
    d.add_argument('--drain-timeout-s', type=float, default=30.0,
                   help='how long a draining worker may spend finishing '
                        'in-flight splits before deregistering timed_out')
    d.add_argument('--no-telemetry-spans', action='store_true',
                   help='do not ship per-split correlated stage spans on '
                        'the data-plane end headers (metrics registries '
                        'and heartbeat stats stay on; see '
                        'docs/observability.md)')
    d.add_argument('--max-tenant-jobs', type=int, default=8,
                   help='admission cap on CONCURRENT tenant jobs sharing '
                        'this fleet (ISSUE 16); registrations past it '
                        'are refused with a retry_after_s hint')
    d.add_argument('--tenant-shm-quota-bytes', type=int, default=None,
                   help='per-tenant cap on outstanding shm-arena bytes; '
                        'over-quota chunks degrade to the byte path '
                        '(default: unlimited)')
    d.add_argument('--tenant-cache-quota-bytes', type=int, default=None,
                   help='per-tenant cap on cache-plane bytes written per '
                        'worker; past it the tenant reads/decodes '
                        'without the plane (default: unlimited)')
    d.add_argument('--autoscale', action='store_true',
                   help='closed-loop fleet autoscaler (ISSUE 16): spawn '
                        'workers when leases starve, drain the least-'
                        'cache-covered worker when the fleet idles; '
                        'PETASTORM_TPU_NO_AUTOSCALE=1 is the kill switch')
    d.add_argument('--autoscale-min-workers', type=int, default=1)
    d.add_argument('--autoscale-max-workers', type=int, default=8)
    d.add_argument('--autoscale-step', type=int, default=1,
                   help='max workers spawned per scale-out action')
    d.add_argument('--autoscale-cooldown-s', type=float, default=10.0,
                   help='hysteresis: no further action for this long '
                        'after any scale action')
    d.add_argument('--autoscale-starve-s', type=float, default=3.0,
                   help='pending work + zero free lease slots must '
                        'persist this long before a scale-out')
    d.add_argument('--autoscale-idle-s', type=float, default=30.0,
                   help='a fully idle fleet must persist this long '
                        'before a scale-in drain')
    d.add_argument('--metrics-port', type=int, default=None,
                   help='serve Prometheus text exposition on '
                        'http://0.0.0.0:PORT/metrics (stdlib http.server '
                        'daemon thread; port 0 picks a free one): every '
                        'live registry plus the decision-journal gauges '
                        '(ISSUE 20) — see docs/observability.md for a '
                        'scrape config')

    w = sub.add_parser('worker', help='run one decode worker')
    w.add_argument('--dispatcher', required=True,
                   help='dispatcher endpoint (tcp://host:port)')
    w.add_argument('--data-bind', default='tcp://127.0.0.1:*',
                   help='ROUTER endpoint to stream batches from; the '
                        'resolved address is advertised to the dispatcher, '
                        'so bind an address the training hosts can reach')
    w.add_argument('--advertise-host', default=None,
                   help='hostname/IP published to the dispatcher instead '
                        'of the bind host — required when binding '
                        '0.0.0.0 (unroutable from the training hosts); '
                        'defaults to the machine hostname for wildcard '
                        'binds')
    w.add_argument('--max-inflight-splits', type=int, default=3)
    w.add_argument('--max-buffered-chunks', type=int, default=32)
    w.add_argument('--cache-plane-dir', default=None,
                   help="override the job's cache_plane_dir on THIS "
                        'worker (host-local plane layouts; see '
                        'Worker(cache_plane_dir=))')

    s = sub.add_parser('status', help='print dispatcher stats as JSON')
    s.add_argument('--dispatcher', required=True)

    g = sub.add_parser('drain', help='gracefully drain one worker '
                                     '(scale-in): it finishes or hands '
                                     'back in-flight splits, then '
                                     'deregisters')
    g.add_argument('--dispatcher', required=True)
    g.add_argument('--worker', required=True,
                   help="worker id from `status` (e.g. 'w0')")

    p = sub.add_parser('stop', help='ask the dispatcher to shut down')
    p.add_argument('--dispatcher', required=True)

    c = sub.add_parser('clock', help='measure clock offset and RTT to '
                                     'the dispatcher (the handshake '
                                     'cross-process span alignment uses; '
                                     'see docs/observability.md)')
    c.add_argument('--dispatcher', required=True)
    c.add_argument('--samples', type=int, default=5,
                   help='handshakes to run; the lowest-RTT one wins '
                        '(NTP-style best-of-N)')
    return parser


def _rpc_once(addr, request):
    import zmq

    from petastorm_tpu.service.worker import _Rpc
    context = zmq.Context()
    rpc = _Rpc(context, addr)
    try:
        return rpc.call(request)
    finally:
        rpc.close()
        context.term()


def main(argv=None):
    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(name)s %(levelname)s %(message)s')
    args = _build_parser().parse_args(argv)

    if args.command == 'dispatcher':
        from petastorm_tpu.service import Dispatcher, ServiceConfig
        reader_kwargs = {}
        if args.workers_count is not None:
            reader_kwargs['workers_count'] = args.workers_count
        config = ServiceConfig(
            dataset_url=args.dataset_url,
            num_consumers=args.num_consumers,
            rowgroups_per_split=args.rowgroups_per_split,
            lease_ttl_s=args.lease_ttl_s,
            credits=args.credits,
            reader_factory=args.reader_factory,
            reader_kwargs=reader_kwargs,
            cache_plane=args.cache_plane_dir is not None,
            cache_plane_dir=args.cache_plane_dir,
            cache_plane_ram_bytes=args.cache_plane_ram_bytes,
            cache_plane_disk_bytes=args.cache_plane_disk_bytes,
            cluster_cache=(False if args.no_cluster_cache else None),
            ingest=args.ingest,
            telemetry_spans=not args.no_telemetry_spans,
            ledger_path=args.ledger_path,
            drain_timeout_s=args.drain_timeout_s,
            max_tenant_jobs=args.max_tenant_jobs,
            tenant_shm_quota_bytes=args.tenant_shm_quota_bytes,
            tenant_cache_quota_bytes=args.tenant_cache_quota_bytes,
            autoscale=args.autoscale,
            autoscale_min_workers=args.autoscale_min_workers,
            autoscale_max_workers=args.autoscale_max_workers,
            autoscale_step=args.autoscale_step,
            autoscale_cooldown_s=args.autoscale_cooldown_s,
            autoscale_starve_s=args.autoscale_starve_s,
            autoscale_idle_s=args.autoscale_idle_s)
        with Dispatcher(config, bind=args.bind) as dispatcher:
            metrics_server = None
            if args.metrics_port is not None:
                from petastorm_tpu.telemetry.scrape import \
                    start_metrics_server
                # Refresh through the stats handler so derived gauges
                # (fleet health, decision rollups) are current at each
                # scrape — same numbers `top` shows for the same moment.
                metrics_server = start_metrics_server(
                    args.metrics_port,
                    refresh=lambda: dispatcher._op_stats({}))
                print('metrics on http://0.0.0.0:%d/metrics'
                      % metrics_server.server_address[1], flush=True)
            print('dispatcher serving %s (%d splits, %d consumers)'
                  % (dispatcher.addr, dispatcher._job['num_splits'],
                     args.num_consumers), flush=True)
            try:
                while dispatcher._thread.is_alive():
                    time.sleep(0.5)
            except KeyboardInterrupt:
                pass
            finally:
                if metrics_server is not None:
                    metrics_server.shutdown()
        return 0

    if args.command == 'worker':
        from petastorm_tpu.service import Worker
        worker = Worker(args.dispatcher, data_bind=args.data_bind,
                        advertise_host=args.advertise_host,
                        max_inflight_splits=args.max_inflight_splits,
                        max_buffered_chunks=args.max_buffered_chunks,
                        cache_plane_dir=args.cache_plane_dir)
        # SIGTERM -> graceful drain (ISSUE 15): finish or hand back
        # in-flight splits, flush shm, deregister — the scale-in path
        # orchestrators drive (terminationGracePeriod should exceed the
        # job's drain_timeout_s).
        worker.install_signal_handlers()
        try:
            worker.run()  # blocks until stop()/drained SIGTERM
        except KeyboardInterrupt:
            pass
        return 0

    if args.command == 'status':
        print(json.dumps(_rpc_once(args.dispatcher, {'op': 'stats'}),
                         indent=1, sort_keys=True))
        return 0

    if args.command == 'drain':
        from petastorm_tpu.errors import ServiceError
        try:
            # _Rpc surfaces an error-carrying reply (e.g. unknown
            # worker id) as a ServiceError — the operator gets the
            # message and exit 1, not a traceback.
            _rpc_once(args.dispatcher,
                      {'op': 'drain', 'worker_id': args.worker})
        except ServiceError as e:
            print('drain refused: %s' % e, file=sys.stderr)
            return 1
        print('worker %s draining (watch `status` for it to deregister)'
              % args.worker)
        return 0

    if args.command == 'stop':
        _rpc_once(args.dispatcher, {'op': 'stop'})
        print('dispatcher at %s stopped' % args.dispatcher)
        return 0

    if args.command == 'clock':
        import zmq

        from petastorm_tpu.service.worker import _Rpc
        from petastorm_tpu.telemetry.spans import measure_clock_offset
        context = zmq.Context()
        rpc = _Rpc(context, args.dispatcher)
        try:
            samples = [measure_clock_offset(
                lambda: rpc.call({'op': 'clock'})['t_mono'])
                for _ in range(max(1, args.samples))]
        finally:
            rpc.close()
            context.term()
        offset_s, rtt_s = min(samples, key=lambda s: s[1])
        print(json.dumps({'offset_s': offset_s, 'rtt_s': rtt_s,
                          'samples': len(samples)}, sort_keys=True))
        return 0

    return 2  # unreachable: argparse enforces the command set


if __name__ == '__main__':
    sys.exit(main())
