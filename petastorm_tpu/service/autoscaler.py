"""Closed-loop fleet autoscaler (ISSUE 16).

The dispatcher already measures everything an autoscaler needs — the
health engine's windowed regimes say when leases starve (not enough
decode workers) and when the fleet idles (too many), and PR 15's drain
path makes scale-in safe.  This module closes the loop: an in-dispatcher
tick controller (the flight-recorder pattern — ``maybe_tick()`` from the
serve loop, NO new control-plane thread) computes a target worker count
and acts through a pluggable :class:`WorkerLauncher` seam.

Control law (deliberately boring — an exciting autoscaler is a flapping
one):

* **scale out** when pending splits have starved for
  ``autoscale_starve_s`` — no alive worker has a free lease slot (or
  none are alive at all) while work waits;
* **scale in** when the fleet has been fully idle (no pending, no
  leased) for ``autoscale_idle_s`` with more than ``autoscale_min_workers``
  alive — via the graceful drain path, choosing the worker whose
  departure costs the least cache-directory coverage;
* **damping**: a cooldown after ANY action, at most ``autoscale_step``
  workers per action, and the alive count clamped to
  ``[autoscale_min_workers, autoscale_max_workers]``.  The chaos
  scale-storm scenarios assert the action count stays within the bound
  these knobs imply.

Kill switch: ``PETASTORM_TPU_NO_AUTOSCALE=1`` beats any config — the
controller constructs but never acts (the doctor probe reports the
state).
"""

import logging
import os
import subprocess
import sys
import time

from petastorm_tpu.telemetry import decisions as _decisions

logger = logging.getLogger(__name__)

__all__ = ['KILL_SWITCH', 'killed', 'WorkerLauncher',
           'SubprocessWorkerLauncher', 'Autoscaler']

KILL_SWITCH = 'PETASTORM_TPU_NO_AUTOSCALE'


def killed():
    """True when the environment vetoes autoscaling on this host."""
    return os.environ.get(KILL_SWITCH, '') not in ('', '0')


class WorkerLauncher(object):
    """The seam between the control law and real worker processes.

    The dispatcher never spawns processes itself: scale-out calls
    ``spawn(dispatcher_addr)``, scale-in is executed by the dispatcher's
    own drain path and reported here via ``notify_drain(worker_id)`` so
    a launcher can reap the matching child.  Tests substitute a fake
    that records both call streams.
    """

    def spawn(self, dispatcher_addr):
        raise NotImplementedError

    def notify_drain(self, worker_id):
        """A drain was initiated on ``worker_id`` (informational)."""

    def close(self):
        """Release launcher resources (kill children it still owns)."""


class SubprocessWorkerLauncher(WorkerLauncher):
    """Launch real decode workers as child processes of the dispatcher.

    Children run the same entry the operator would
    (``petastorm-tpu-data-service worker --dispatcher ...``) with the
    SIGTERM-drain handler installed, so a dispatcher shutdown or an
    explicit drain terminates them gracefully.
    """

    def __init__(self, worker_args=None):
        self._worker_args = list(worker_args or ())
        self._procs = []

    def spawn(self, dispatcher_addr):
        cmd = [sys.executable, '-m', 'petastorm_tpu.service.cli',
               'worker', '--dispatcher', dispatcher_addr]
        cmd += self._worker_args
        # The child resolves ``-m petastorm_tpu...`` via sys.path, which
        # for ``-m`` starts at the child's cwd — prepend the package
        # root so a dispatcher launched from anywhere spawns importable
        # workers.
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env['PYTHONPATH'] = root + (
            os.pathsep + env['PYTHONPATH'] if env.get('PYTHONPATH') else '')
        proc = subprocess.Popen(cmd, env=env)
        self._procs.append(proc)
        logger.info('autoscaler spawned worker pid %d', proc.pid)
        return proc.pid

    def close(self):
        for proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 10.0
        for proc in self._procs:
            try:
                proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(5.0)
        self._procs = []


class Autoscaler(object):
    """The tick controller.  Owned and called by the dispatcher thread
    (serve-loop ticks), so it needs no lock of its own; every method
    runs under the dispatcher's sequencing.
    """

    #: Seconds between observation ticks (the serve loop polls at
    #: ~100 ms; sub-second control would just chase noise).
    TICK_S = 1.0

    def __init__(self, config, launcher, now=None):
        self.config = config
        self.launcher = launcher
        self.enabled = bool(config.autoscale) and not killed()
        now = time.monotonic() if now is None else now
        self._next_tick = now
        self._cooldown_until = 0.0
        self._starve_since = None
        self._idle_since = None
        # Action counters — the chaos scale-storm bound and the stats
        # rollup read these.
        self.scale_outs = 0
        self.scale_ins = 0
        self.suppressed = 0   # wanted to act; cooldown/bounds said no
        self.last_action = None
        self.last_action_t = None
        # Decision journal (ISSUE 20): the dispatcher points this at its
        # ledger-persisted journal so every action/suppression explains
        # itself; None falls through to the process journal.
        self.decisions = None

    @property
    def actions(self):
        return self.scale_outs + self.scale_ins

    def maybe_tick(self, observation, now=None):
        """One control-law evaluation; returns the action taken.

        ``observation`` is the dispatcher's view under its lock::

            {'pending': int, 'leased': int,
             'alive': [worker_id, ...],        # non-draining, fresh hb
             'free_slots': int,                # alive workers w/o lease
             'coverage': {worker_id: int}}     # cache digests held

        Returns ``None`` (no-op), ``('scale_out', n)`` after spawning
        ``n`` workers, or ``('scale_in', worker_id)`` naming the drain
        victim — the DISPATCHER executes the drain (it owns that path).
        """
        now = time.monotonic() if now is None else now
        if not self.enabled or now < self._next_tick:
            return None
        self._next_tick = now + self.TICK_S
        pending = int(observation.get('pending', 0))
        leased = int(observation.get('leased', 0))
        alive = list(observation.get('alive') or ())
        free_slots = int(observation.get('free_slots', 0))

        starved = pending > 0 and (not alive or free_slots == 0)
        idle = pending == 0 and leased == 0 and alive
        # Explicit None checks: a start stamp of 0.0 (injected clocks in
        # tests/doctor) is falsy but set.
        if starved:
            if self._starve_since is None:
                self._starve_since = now
        else:
            self._starve_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        cfg = self.config
        cooldown_left = max(0.0, self._cooldown_until - now)
        if starved and now - self._starve_since >= cfg.autoscale_starve_s:
            want = min(cfg.autoscale_step,
                       cfg.autoscale_max_workers - len(alive))
            inputs = {'pending': pending, 'leased': leased, 'alive': alive,
                      'free_slots': free_slots,
                      'starve_s': round(now - self._starve_since, 3),
                      'threshold_s': cfg.autoscale_starve_s,
                      'step': cfg.autoscale_step,
                      'max_workers': cfg.autoscale_max_workers,
                      'cooldown_remaining_s': round(cooldown_left, 3)}
            if want <= 0 or now < self._cooldown_until:
                self.suppressed += 1
                _decisions.record_decision(
                    'autoscaler', 'hold', 'autoscale_cooldown_s',
                    dict(inputs, want=want, wanted='scale_out'),
                    suppressed=True, cooldown_until=self._cooldown_until,
                    journal=self.decisions)
                return None
            spawned = 0
            for _ in range(want):
                try:
                    self.launcher.spawn(observation['dispatcher_addr'])
                    spawned += 1
                except Exception:  # noqa: BLE001 — a dead launcher must
                    # not take the serve loop down; starvation persists
                    # and the next tick (post-cooldown) retries.
                    logger.exception('autoscaler spawn failed')
                    break
            if not spawned:
                return None
            self.scale_outs += 1
            self._after_action('scale_out', now)
            self._starve_since = None
            _decisions.record_decision(
                'autoscaler', 'scale_out', 'autoscale_starve_s', inputs,
                cooldown_until=self._cooldown_until, spawned=spawned,
                journal=self.decisions)
            return ('scale_out', spawned)

        if idle and now - self._idle_since >= cfg.autoscale_idle_s \
                and len(alive) > cfg.autoscale_min_workers:
            coverage = dict(observation.get('coverage') or {})
            inputs = {'pending': pending, 'leased': leased, 'alive': alive,
                      'idle_s': round(now - self._idle_since, 3),
                      'threshold_s': cfg.autoscale_idle_s,
                      'min_workers': cfg.autoscale_min_workers,
                      'coverage': coverage,
                      'cooldown_remaining_s': round(cooldown_left, 3)}
            if now < self._cooldown_until:
                self.suppressed += 1
                _decisions.record_decision(
                    'autoscaler', 'hold', 'autoscale_cooldown_s',
                    dict(inputs, want=1, wanted='scale_in'),
                    suppressed=True, cooldown_until=self._cooldown_until,
                    journal=self.decisions)
                return None
            victim = self._drain_victim(alive, coverage)
            self.scale_ins += 1
            self._after_action('scale_in', now)
            self._idle_since = None
            _decisions.record_decision(
                'autoscaler', 'scale_in', 'autoscale_idle_s', inputs,
                cooldown_until=self._cooldown_until, worker_id=victim,
                journal=self.decisions)
            self.launcher.notify_drain(victim)
            return ('scale_in', victim)
        return None

    def _after_action(self, action, now):
        self.last_action = action
        self.last_action_t = now
        self._cooldown_until = now + self.config.autoscale_cooldown_s

    @staticmethod
    def _drain_victim(alive, coverage):
        """The alive worker whose departure costs the least cache
        directory coverage (fewest advertised digests; id-ordered
        tie-break for determinism)."""
        coverage = coverage or {}
        return min(alive, key=lambda wid: (coverage.get(wid, 0), wid))

    def snapshot(self):
        """Counters for the ``stats`` rollup / fleet snapshot."""
        return {'enabled': self.enabled,
                'killed': killed(),
                'scale_outs': self.scale_outs,
                'scale_ins': self.scale_ins,
                'actions': self.actions,
                'suppressed': self.suppressed,
                'last_action': self.last_action}

    def close(self):
        try:
            self.launcher.close()
        except Exception:  # noqa: BLE001 — shutdown must not raise
            logger.exception('autoscaler launcher close failed')
