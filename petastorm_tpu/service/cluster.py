"""Cluster-wide cache tier: the epoch-cache plane as a fleet asset.

The plane (``petastorm_tpu/cache_plane/``) stops at the host boundary:
N hosts training on one dataset each pay full Parquet read + decode.
This module is the service-side glue that makes decoded entries flow
between hosts — three cooperating mechanisms, all strictly best-effort
(degrade-everywhere: the data plane NEVER blocks on cache machinery):

* **cache-affinity lease routing** — workers advertise the digests their
  plane holds (compact prefixes riding heartbeats); the dispatcher keeps
  a cache directory and prefers leasing a split to a worker that already
  holds its entries decoded (``dispatcher._op_lease``).
* **remote HIT serving** — a worker whose leased split fully HITs its
  local plane streams the decoded entries over the existing chunk
  protocol without constructing a reader at all (no Parquet open, no
  decode, no per-split pool spin-up): :meth:`ClusterCacheIdentity
  .serve_chunks`.
* **peer fill** — on a local MISS for a digest the directory says a peer
  holds, the worker fetches the *encoded entry bytes* from that peer
  over a bounded fetch RPC (:class:`PeerFetcher` / :func:`fetch_reply`,
  reusing the data-socket chunk framing and the shm byte-path fallback
  matrix) and republishes them verbatim through the plane's crash-safe
  atomic publish — bit-identical by construction, and local for every
  later epoch.

What makes any of this safe is the plane's content-fingerprint keying:
a digest names (dataset file identity x decode identity x piece), so an
entry is valid on any host or none — there is no staleness protocol to
get wrong, per the reproducibility framing of "Optimizing
High-Throughput Distributed Data Pipelines" (PAPERS.md).

The crux is computing a split's digests WITHOUT constructing a reader:
:class:`ClusterCacheIdentity` resolves the same (schema view, pieces,
transform, predicate, plane context) a per-split reader would, and the
per-piece key formats are imported from the reader workers themselves
(``py_dict_reader_worker.piece_cache_key`` /
``arrow_reader_worker.piece_cache_key`` — single source of truth;
``tests/test_cluster_cache.py`` pins the equivalence against a real
reader's plane).

Kill switch: ``PETASTORM_TPU_NO_CLUSTER_CACHE=1`` (env, beats
everything) or ``ServiceConfig(cluster_cache=False)``; either leaves
the service bit-identical to the pre-cluster behavior.
"""

import logging
import os
import pickle
import threading
from petastorm_tpu.utils.locks import make_lock
import time

logger = logging.getLogger(__name__)

KILL_ENV = 'PETASTORM_TPU_NO_CLUSTER_CACHE'

#: Control-plane digests are truncated to this many hex chars (48 bits):
#: the directory is advisory (affinity, holder hints) and the data plane
#: validates by full digest, so collisions cost one wasted fetch at
#: worst, while heartbeats stay small.
CDIGEST_LEN = 12

#: One peer fetch waits at most this long before degrading to direct
#: decode (a dead/slow/partitioned peer must cost bounded time, and the
#: lease TTL keeps renewing meanwhile only via heartbeats).
FETCH_TIMEOUT_S = 8.0

#: A fetch reply (or a serve of one) larger than this degrades — a
#: bound on both sides of the RPC so one pathological entry cannot wedge
#: a worker's event loop or a fetcher's memory.
FETCH_MAX_BYTES = 256 << 20


def killed():
    return bool(os.environ.get(KILL_ENV))


def enabled(job):
    """Cluster tier active for this job on this process?"""
    return bool(job.get('cluster_cache')) and bool(job.get('cache_plane')) \
        and not killed()


def cdigest(digest):
    """Full entry digest -> compact control-plane digest."""
    return digest[:CDIGEST_LEN]


class ClusterCacheIdentity(object):  # ptlint: disable=pickle-unsafe-attrs — built and used inside one worker process, never shipped
    """Per-(worker, job) decode identity: piece list, plane context, and
    the exact per-piece cache digests a per-split reader would use.

    Built once per worker via :meth:`build` (a footer scan, no decode,
    no pool); ``None`` when the job's reader kwargs fall outside the
    supported surface — the caller then simply runs without the cluster
    tier (the local plane still works exactly as before).
    """

    def __init__(self, plane, pieces, item_digests, converter, kind,
                 drop_partitions):
        #: The worker's own CachePlane over the job's plane dir (same
        #: dirs the per-split readers publish into — shared by path).
        self.plane = plane
        self._pieces = pieces
        #: piece index -> [full digest per row-drop partition].
        self._item_digests = item_digests
        self._converter = converter
        self._kind = kind  # 'columns' (codec reader) | 'batch' (arrow)
        self._drop_partitions = drop_partitions
        #: Decode-identity inputs retained by ``_build`` for the
        #: materialize plane (ISSUE 18): a warmer rebuilds the exact
        #: reader-worker args from these without re-resolving the job.
        self.fs = None
        self.stored_schema = None
        self.schema_view = None
        self.transform_spec = None
        self.predicate = None

    # -- construction --------------------------------------------------------

    @classmethod
    def build(cls, job):
        """Resolve the job's decode identity, or None (unsupported
        kwargs / metadata errors — logged once, never raised: the
        cluster tier is an optimization)."""
        try:
            return cls._build(job)
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            logger.warning('cluster cache: identity unavailable for %r '
                           '(%s: %s); running without the cluster tier',
                           job.get('dataset_url'), type(e).__name__, e)
            return None

    @classmethod
    def _build(cls, job):
        from petastorm_tpu.cache_plane import PlaneCache
        from petastorm_tpu.errors import MetadataError
        from petastorm_tpu.etl.dataset_metadata import (
            get_schema, infer_or_load_unischema, load_row_groups)
        from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
        from petastorm_tpu.reader import _plane_context
        from petastorm_tpu.transform import transform_schema
        from petastorm_tpu.unischema import match_unischema_fields

        kwargs = dict(job.get('reader_kwargs') or {})
        if not _supported_kwargs(kwargs):
            logger.info('cluster cache: reader_kwargs %s outside the '
                        'supported surface; cluster tier off',
                        sorted(kwargs))
            return None
        schema_fields = kwargs.get('schema_fields')
        predicate = kwargs.get('predicate')
        transform_spec = kwargs.get('transform_spec')
        drop_partitions = max(
            1, int(kwargs.get('shuffle_row_drop_partitions') or 1))

        fs, path_or_paths = get_filesystem_and_path_or_paths(
            job['dataset_url'],
            storage_options=kwargs.get('storage_options'),
            filesystem=kwargs.get('filesystem'))
        paths = (path_or_paths if isinstance(path_or_paths, list)
                 else [path_or_paths])

        # The same auto-detection _resolve_factory performs, minus the
        # probe reader: petastorm metadata -> codec reader (columnar
        # output), plain Parquet -> batch reader.
        factory = job.get('reader_factory', 'auto')
        stored_schema = None
        if factory in ('auto', 'reader'):
            try:
                stored_schema = get_schema(fs, paths[0])
                kind = 'columns'
            except MetadataError:
                if factory == 'reader':
                    raise
                kind = 'batch'
        else:
            kind = 'batch'
        if kind == 'batch':
            if schema_fields is not None and not all(
                    isinstance(f, str) for f in schema_fields):
                return None
            stored_schema = infer_or_load_unischema(fs, paths[0])
            if schema_fields is not None:
                matched = match_unischema_fields(stored_schema,
                                                 schema_fields)
                schema_view = (stored_schema.create_schema_view(matched)
                               if matched else stored_schema)
            else:
                schema_view = stored_schema
            if drop_partitions != 1:
                return None  # the batch reader has no row-drop axis
        else:
            if schema_fields is not None and not all(
                    isinstance(f, str) for f in schema_fields):
                return None  # NGram (or exotic) selections: no cluster tier
            schema_view = (stored_schema.create_schema_view(schema_fields)
                           if schema_fields is not None else stored_schema)
            if not _columnar_cacheable(transform_spec):
                # Opaque per-row funcs cache the rows list, not the
                # published columns — servable, but the ':c'/rows split
                # doubles the matrix; keep the supported surface at the
                # fast path the service actually runs.
                return None

        pieces = []
        for p in paths:
            pieces.extend(load_row_groups(fs, p))
        if not pieces:
            return None
        context = _plane_context('plane', fs, pieces, schema_view,
                                 predicate, transform_spec)
        plane_cache = PlaneCache(
            job['cache_plane_dir'],
            size_limit_bytes=job.get('cache_plane_disk_bytes'),
            ram_bytes=job.get('cache_plane_ram_bytes'),
            context=context)
        plane = plane_cache.plane
        if plane.disk is None:
            return None  # plane dir unusable: nothing to share

        item_digests = []
        if kind == 'columns':
            from petastorm_tpu.py_dict_reader_worker import piece_cache_key
            for piece in pieces:
                item_digests.append([
                    plane.digest(piece_cache_key(piece, schema_view,
                                                 transform_spec, part)
                                 + ':c')
                    for part in range(drop_partitions)])
        else:
            from petastorm_tpu.arrow_reader_worker import piece_cache_key
            for piece in pieces:
                item_digests.append(
                    [plane.digest(piece_cache_key(piece, schema_view,
                                                  transform_spec))])

        result_schema = (transform_schema(schema_view, transform_spec)
                         if transform_spec is not None else schema_view)
        if kind == 'columns':
            from petastorm_tpu.reader import _ColumnarDictConverter
            converter = _ColumnarDictConverter(result_schema)
        else:
            from petastorm_tpu.arrow_reader_worker import \
                ArrowResultConverter
            converter = ArrowResultConverter(result_schema)
        identity = cls(plane, pieces, item_digests, converter, kind,
                       drop_partitions)
        identity.fs = fs
        identity.stored_schema = stored_schema
        identity.schema_view = schema_view
        identity.transform_spec = transform_spec
        identity.predicate = predicate
        return identity

    # -- digest surface ------------------------------------------------------

    @property
    def num_pieces(self):
        return len(self._pieces)

    @property
    def pieces(self):
        return self._pieces

    @property
    def kind(self):
        return self._kind

    @property
    def drop_partitions(self):
        return self._drop_partitions

    def piece_digests(self, index):
        """Full digests of one piece's work items (one per row-drop
        partition) — the materialize plane publishes under exactly
        these."""
        return list(self._item_digests[int(index)])

    def piece_cdigests(self):
        """Compact digest per global piece index — the once-per-job
        advertisement a worker ships so the dispatcher can map any
        split's indices to directory entries.  One cdigest per piece:
        multi-partition pieces advertise their first partition's digest
        (affinity is advisory; serve/fetch use the full per-item set)."""
        return [cdigest(parts[0]) for parts in self._item_digests]

    def split_digests(self, indices):
        """Full digests of a split's work items, in delivery order."""
        out = []
        for i in indices:
            out.extend(self._item_digests[int(i)])
        return out

    def missing_digests(self, indices):
        """The subset of a split's digests with no local published
        entry — the peer-fill shopping list."""
        return [d for d in self.split_digests(indices)
                if not self.plane.has_digest(d)]

    # -- remote-HIT serving --------------------------------------------------

    def serve_chunks(self, indices):
        """The split's chunk dicts straight from the local plane, or
        None when ANY item misses (caller falls back to the reader path
        with nothing emitted — all lookups happen before the first chunk
        is returned, so a concurrent eviction can't tear a split).

        Produces exactly what the per-split reader would publish: the
        cached values are post-transform (the plane key carries the
        transform identity) and run through the same result converter
        (namedtuple ``_asdict``), so delivery is bit-identical to the
        decode path.
        """
        from petastorm_tpu.cache_plane.plane import MISS
        values = []
        for i in indices:
            for digest in self._item_digests[int(i)]:
                value = self.plane.lookup_digest(digest)
                if value is MISS:
                    return None
                values.append(value)
        chunks = []
        for value in values:
            if value is None:
                continue  # cached predicate-empty piece: publishes nothing
            if self._kind == 'columns':
                if not len(next(iter(value.values()), ())):
                    continue
                chunks.append(self._converter.convert(value)._asdict())
            else:
                if value.num_rows == 0:
                    continue
                chunks.append(self._converter.convert(value)._asdict())
        return chunks


def _supported_kwargs(kwargs):
    """Reader kwargs the identity computation understands.  Anything
    that renumbers the piece list or changes what a piece caches to —
    and anything we have simply not audited — turns the cluster tier
    off for the job rather than risking a wrong digest."""
    if kwargs.get('rowgroup_selector') is not None \
            or kwargs.get('filters') is not None:
        return False
    cache_type = kwargs.get('cache_type', 'plane')
    if cache_type != 'plane':
        return False  # an explicit non-plane cache wins (documented)
    return True


def _columnar_cacheable(transform_spec):
    from petastorm_tpu.py_dict_reader_worker import columnar_fast_path
    return columnar_fast_path(transform_spec)


# -- peer fetch (data plane) --------------------------------------------------

def fetch_reply(identity_frame, request, plane, arena=None):
    """Build the reply frames for one ``fetch`` request — shared by the
    worker event loop and the doctor's synthetic round-trip probe.

    Returns ``[identity, header_bytes, payload]``.  The payload is the
    raw entry blob (byte path) or a shm descriptor (``tag 'S'``) when
    the requester proved same-host residence via its probe file — the
    same fallback matrix as chunk delivery.  Absent/oversized entries
    reply ``ok=False`` with an empty payload (the fetcher degrades).
    """
    digest = str(request.get('digest', ''))
    blob = plane.entry_blob(digest) if plane is not None and digest else None
    if blob is None or len(blob) > FETCH_MAX_BYTES:
        header = {'type': 'fetched', 'digest': digest, 'ok': False}
        return [identity_frame, pickle.dumps(header, protocol=4), b'']
    tag = b'B'
    payload = blob
    if arena is not None:
        from petastorm_tpu.workers_pool import shm_plane
        import numpy as np
        if shm_plane.probe_exists(request.get('shm_probe')):
            desc = shm_plane.write_columns(
                arena, {'blob': np.frombuffer(blob, np.uint8)})
            if desc is not None:
                tag = b'S'
                payload = pickle.dumps(desc, protocol=4)
    header = {'type': 'fetched', 'digest': digest, 'ok': True, 'tag': tag,
              'nbytes': len(blob)}
    return [identity_frame, pickle.dumps(header, protocol=4), payload]


class PeerFetcher(object):  # ptlint: disable=pickle-unsafe-attrs — owned by one decode thread; sockets never cross threads or processes
    """Bounded fetch client over peers' data sockets (one DEALER per
    peer, cached; owned by a single thread).

    ``fetch`` returns the entry blob bytes or None (timeout, peer dead,
    not found, oversized) — callers count ``cache_peer_degraded`` and
    fall through to direct decode.  A timed-out socket is closed and
    rebuilt on the next fetch to that peer (a DEALER with a stale
    in-flight request would misalign replies).
    """

    def __init__(self, context, timeout_s=None):
        import zmq
        self._zmq = zmq
        self._context = context
        # Resolved per-instance at construction (not at def time) so the
        # module constant stays the one tunable.
        self._timeout_s = float(FETCH_TIMEOUT_S if timeout_s is None
                                else timeout_s)
        self._sockets = {}
        # Same-host proof for the shm path of the fetch reply: workers
        # are shm consumers here, exactly like clients are for chunks.
        from petastorm_tpu.workers_pool import shm_plane
        self._probe = None
        if shm_plane.available():
            try:
                self._probe = shm_plane.make_probe()
            except OSError:
                pass  # byte path only — the matrix's documented fallback

    def _socket(self, addr):
        sock = self._sockets.get(addr)
        if sock is None:
            sock = self._context.socket(self._zmq.DEALER)
            sock.setsockopt(self._zmq.LINGER, 0)
            sock.connect(addr)
            self._sockets[addr] = sock
        return sock

    def _drop(self, addr):
        sock = self._sockets.pop(addr, None)
        if sock is not None:
            sock.close(0)

    def fetch(self, addr, digest):
        """Entry blob bytes from the peer at ``addr``, or None."""
        from petastorm_tpu.workers_pool import shm_plane
        try:
            sock = self._socket(addr)
            sock.send(pickle.dumps(
                {'type': 'fetch', 'digest': digest,
                 'shm_probe': self._probe}, protocol=4))
            deadline = time.monotonic() + self._timeout_s
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not sock.poll(
                        max(1, int(remaining * 1000))):
                    self._drop(addr)
                    # The peer may have died with our reply's shm slab
                    # in flight: reclaim dead owners' segments so a
                    # degraded fetch leaves zero /dev/shm residue.
                    if self._probe is not None:
                        shm_plane.sweep_orphans()
                    return None
                frames = sock.recv_multipart()
                header = pickle.loads(frames[0])
                if header.get('type') != 'fetched' \
                        or header.get('digest') != digest:
                    continue  # stale reply from a recycled exchange
                if not header.get('ok'):
                    return None
                if header.get('tag') == b'S':
                    try:
                        payload = shm_plane.read_payload(
                            pickle.loads(frames[1]))
                    except shm_plane.SegmentVanishedError:
                        return None
                    blob = payload['blob'].tobytes()
                elif header.get('tag') == b'B':
                    blob = bytes(frames[1])
                else:
                    # Explicit dispatch (wire-protocol-conformance): a tag
                    # this side doesn't speak is a degrade, not a guess.
                    return None
                if len(blob) > FETCH_MAX_BYTES:
                    return None
                return blob
        except Exception:  # noqa: BLE001 — a fetch failure is a degrade
            self._drop(addr)
            return None

    def close(self):
        for addr in list(self._sockets):
            self._drop(addr)
        from petastorm_tpu.workers_pool import shm_plane
        shm_plane.remove_probe(self._probe)
        self._probe = None


class ClusterWorkerState(object):  # ptlint: disable=pickle-unsafe-attrs — per-worker-process state, never pickled
    """Everything a service worker keeps for the cluster tier: the lazily
    built identity (background thread — the footer scan must not delay
    registration), the advertised-digest refresh, and the peer fetcher.
    """

    #: Re-listdir the plane's tiers for the heartbeat advertisement at
    #: most this often; locally published digests are folded in live.
    DIGEST_REFRESH_S = 5.0

    def __init__(self, job):
        self.identity = None
        self._job = job
        #: Guarded: the decode thread folds freshly published digests in
        #: (note_published) while the event-loop thread snapshots the
        #: set for heartbeats — an unguarded frozenset() over a set
        #: being update()d raises mid-iteration and would kill the
        #: event loop.
        self._known_lock = make_lock('service.cluster.ClusterWorkerState._known_lock')
        self._known = set()
        self._known_at = 0.0
        self._advertised = None   # last frozenset shipped on a heartbeat
        self.advertised_pieces = False
        self._thread = threading.Thread(target=self._build, daemon=True,
                                        name='cluster-cache-identity')
        self._thread.start()

    def _build(self):
        identity = ClusterCacheIdentity.build(self._job)
        # Publish the fully built object in one reference store (GIL):
        # readers see None or a complete identity, never a partial.
        self.identity = identity

    def ready(self):
        return self.identity is not None

    def heartbeat_fields(self):
        """The cluster fields to ride THIS heartbeat: the compact digest
        set when it changed since last shipped, and the once-per-job
        piece-digest map until the dispatcher has it."""
        fields = {}
        identity = self.identity
        if identity is None:
            return fields
        now = time.monotonic()
        if now - self._known_at >= self.DIGEST_REFRESH_S:
            self._known_at = now
            try:
                listed = {cdigest(d)
                          for d in identity.plane.held_digests()}
                with self._known_lock:
                    self._known = listed
            except Exception:  # noqa: BLE001 — advertisement is advisory
                pass
        with self._known_lock:
            current = frozenset(self._known)
        if current != self._advertised:
            self._advertised = current
            fields['cache_digests'] = sorted(current)
        if not self.advertised_pieces:
            fields['piece_digests'] = identity.piece_cdigests()
        return fields

    def note_published(self, digests):
        """Fold just-published (decoded or peer-filled) digests into the
        advertised set without waiting for the next listdir refresh.
        Called from the decode thread; the lock serializes against the
        event loop's heartbeat snapshot."""
        fresh = [cdigest(d) for d in digests]
        with self._known_lock:
            self._known.update(fresh)

    def reset_advertisement(self):
        """Forget what the dispatcher knows (it restarted): the next
        heartbeat re-ships both the digest set and the piece map."""
        self._advertised = None
        self.advertised_pieces = False
