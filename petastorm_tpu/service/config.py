"""Configuration surface of the disaggregated data-loading service.

One :class:`ServiceConfig` describes a *job*: the dataset, how its
row-group list is cut into splits, how splits map onto consumers, and the
control-plane timing (lease TTL, heartbeat cadence) plus the data-plane
flow control (credit window, worker buffer bound).  The dispatcher owns
the authoritative copy; workers and clients fetch the fields they need
over the ``job`` RPC, so every process in the service agrees on the same
partition geometry without sharing files.
"""

import dataclasses
import hashlib


@dataclasses.dataclass
class ServiceConfig:
    """Job description + tuning knobs for dispatcher/worker/client.

    Args:
        dataset_url: the dataset every decode worker reads (petastorm
            format or plain Parquet — workers auto-detect, see
            ``reader_factory``).
        num_consumers: number of consuming training hosts.  Split ``i``
            belongs to consumer ``i % num_consumers`` — the same modulo
            contract ``reader._shard_indices`` uses, so the service shards
            exactly like the local loaders do.
        rowgroups_per_split: consecutive row groups per split.  A split is
            the unit of lease/reassignment AND of exactly-once delivery
            (clients commit whole splits), so it bounds both re-decode
            work after a worker death and client-side buffering.
        lease_ttl_s: a split lease not renewed (by worker heartbeat)
            within this window is considered orphaned and reassigned.
        max_split_attempts: a split whose lease expires this many times is
            marked ``failed`` instead of requeued — every worker that
            touched it walked away (undecodable data), and clients raise a
            ``ServiceError`` rather than silently waiting forever.
        heartbeat_interval_s: worker heartbeat cadence; defaults to
            ``lease_ttl_s / 3`` when None.
        credits: initial per-client credit window, counted in chunks.
            The client replenishes one credit per chunk it pulls off the
            socket; when its delivery queue fills, it stops reading and
            the worker's sends stall at this bound — credit-based
            backpressure end to end.
        max_buffered_chunks: decode pauses on a worker once this many
            serialized chunks wait for credits (bounds worker memory when
            a consumer is slow or absent).
        max_inflight_splits: leases a worker holds at once (one decoding
            + the rest streaming/awaiting ack).
        reader_factory: ``'auto'`` probes the dataset once per worker
            (petastorm metadata -> codec-decoding ``make_reader`` with
            ``columnar_decode=True``; plain Parquet ->
            ``make_batch_reader``); ``'reader'`` / ``'batch_reader'``
            force the choice.
        reader_kwargs: extra kwargs for the per-split reader (e.g.
            ``workers_count``, ``transform_spec``).  Must be picklable —
            they cross the control plane.
        shm: allow same-host delivery over the shared-memory result plane
            (``workers_pool/shm_plane.py``).  A client proves same-host by
            a ``/dev/shm`` probe file named in its subscribe; chunks to
            that consumer then travel as segment descriptors instead of
            serialized bytes, falling back transparently per-chunk
            (cross-host clients, full arena, small chunks, missing
            ``/dev/shm``, or ``PETASTORM_TPU_NO_SHM=1``).
        shm_capacity_bytes: per-worker cap on shm bytes written but not
            yet mapped by a client; beyond it chunks degrade to the byte
            path instead of blocking decode.
        cache_plane: opt-in to the tiered epoch-cache plane
            (``petastorm_tpu/cache_plane/``): every worker's per-split
            reader runs with ``cache_type='plane'`` over
            ``cache_plane_dir``, so a split decoded once is served from
            the shared cache by ANY worker on the host for every later
            epoch/run against the same dataset bytes.  The dispatcher's
            lease is the per-piece decode-ownership grant (a split —
            and hence each of its row groups — is leased to exactly one
            worker per epoch); the plane's cross-process single-flight
            lock backs that up across overlapping service runs.  A cold
            or full plane degrades per-piece to direct decode + the
            existing byte/shm delivery path — never blocks.
        cache_plane_dir: the shared plane directory (disk tier root; the
            hot ``/dev/shm`` tier is derived from it).  Required when
            ``cache_plane=True``.  Workers on different hosts may point
            at host-local paths — the plane is a same-host cache.
        cache_plane_ram_bytes / cache_plane_disk_bytes: per-tier byte
            caps (None = the plane's defaults: 128 MiB hot, 4 GiB disk).
        cluster_cache: opt the job into the CLUSTER cache tier
            (``service/cluster.py``): workers advertise the digests
            their plane holds, the dispatcher routes leases with cache
            affinity, a worker whose leased split fully HITs its local
            plane streams it without constructing a reader
            (``cache_remote_hits``), and local misses a peer holds are
            fetched from that peer instead of re-decoded
            (``cache_peer_fills``; failures degrade to direct decode,
            ``cache_peer_degraded``).  Defaults to ``cache_plane`` —
            the tier is pure best-effort on top of the plane, so any
            plane-enabled job gets it unless explicitly disabled.
            ``PETASTORM_TPU_NO_CLUSTER_CACHE=1`` is the kill switch
            (beats the config everywhere; either path is bit-identical
            to the pre-cluster behavior).
        scheduling: dispatch-order policy every per-split reader runs
            with (``'auto'`` / ``'fifo'`` / ``'adaptive'`` — see
            ``make_reader(scheduling=)``).  Splits are small by design
            (``rowgroups_per_split``), so ``'auto'`` usually resolves to
            FIFO per split; the field exists so a skew-heavy job can
            force ``'adaptive'`` fleet-wide from one place, and so the
            ``PETASTORM_TPU_NO_ADAPTIVE_SCHED=1`` kill switch has a
            config-level mirror.  An explicit ``scheduling`` in
            ``reader_kwargs`` wins.
        ingest: the async byte-range ingest plane mode every per-split
            reader mounts (``'auto'`` / ``'plane'`` / ``'off'`` — see
            ``make_reader(ingest=)``, ISSUE 14).  Decode workers are
            exactly the processes that pay object-store first-byte
            latency, so ``'auto'`` turns the plane on whenever the
            dataset lives on a non-local filesystem; the field exists so
            a job can force it from one place, and so the
            ``PETASTORM_TPU_NO_INGEST_PLANE=1`` kill switch has a
            config-level mirror.  An explicit ``ingest`` in
            ``reader_kwargs`` wins.
        telemetry_spans: ship each split's correlated stage spans
            (decode / serialize / shm publish / cache fill) on its
            ``end`` header so clients with a ``trace_recorder`` merge
            them into one cross-process timeline (ISSUE 5).  Measured
            overhead is <1% (a handful of small dicts per chunk); the
            flag exists for byte-budgeted control planes, and turning it
            off never affects the metrics registry or heartbeat stats.
        ledger_path: durable dispatcher ledger file (ISSUE 15;
            ``service/ledger.py``).  When set, every split-state
            transition persists crash-safely and a restarted dispatcher
            pointed at the same path restores the lease ledger + cache
            directory instead of re-decoding the world: done splits stay
            done, attempt counters survive, and leases workers still
            hold resume via their ``held`` heartbeat claims.  The file
            outlives clean shutdowns on purpose (it is the next
            incarnation's restore source); a ledger written under a
            different partition geometry is ignored whole.  None (the
            default) keeps the pre-ledger in-memory-only behavior.
        drain_timeout_s: how long a draining worker may spend finishing
            its in-flight splits before it deregisters anyway
            (``timed_out=True`` — the dispatcher requeues whatever it
            still held, attempt+1, and counts ``drain_timeouts``).
        tenant: the tenant id this config's job registers under (ISSUE
            16).  The dispatcher's constructor config is the *default*
            tenant's job; further tenants join a running dispatcher via
            the ``register_job`` RPC (``client.register_tenant_job``)
            with their own ServiceConfig.  Split ids stay globally
            unique across tenants, so every split-addressed RPC is
            tenant-agnostic.
        tenant_weight: fair-share weight for weighted deficit
            round-robin lease scheduling across tenants.  Tenant A at
            weight 3 vs tenant B at weight 1 receives ~3x the lease
            grants while both have pending work; a lone tenant's
            schedule is bit-identical to the pre-tenancy dispatcher.
        max_tenant_jobs: admission cap on CONCURRENT tenant jobs;
            registration past the cap is refused with a retry hint
            (clients queue with jittered backoff) rather than erroring.
        tenant_shm_quota_bytes: per-tenant budget of outstanding shm
            arena bytes on each worker (None = unlimited).  Over
            budget, that tenant's chunks degrade to the byte path
            (``shm_quota_degraded``) — never a stall.
        tenant_cache_quota_bytes: per-tenant budget of bytes published
            into the cache plane per worker (None = unlimited).  Over
            budget, that tenant's later splits decode directly without
            the plane (``cache_quota_degraded``) — the existing
            degrade-to-direct-decode semantics.
        autoscale: opt the dispatcher into the closed-loop autoscaler
            (``service/autoscaler.py``): an in-dispatcher tick
            controller scales the worker fleet out on sustained
            lease-wait starvation and in (graceful drain, least
            cache-coverage victim) on sustained idleness.  Requires a
            ``WorkerLauncher`` (``Dispatcher(launcher=)``); the
            subprocess launcher is the production seam.
            ``PETASTORM_TPU_NO_AUTOSCALE=1`` is the kill switch.
        autoscale_min_workers / autoscale_max_workers: alive-fleet
            clamp; scale-in never drains below the min, scale-out never
            spawns past the max.
        autoscale_step: workers per scale-out action (bounded step —
            half the flap damping).
        autoscale_cooldown_s: seconds after ANY action during which the
            controller only observes (the other half of the damping;
            the chaos scale-storm bound derives from it).
        autoscale_starve_s: how long pending splits must starve (no
            free lease slot on any alive worker) before scaling out.
        autoscale_idle_s: how long the fleet must be fully idle (no
            pending, no leased) before scaling in.
    """

    dataset_url: str
    num_consumers: int = 1
    rowgroups_per_split: int = 2
    lease_ttl_s: float = 10.0
    max_split_attempts: int = 5
    heartbeat_interval_s: float = None
    credits: int = 8
    max_buffered_chunks: int = 32
    max_inflight_splits: int = 3
    reader_factory: str = 'auto'
    reader_kwargs: dict = dataclasses.field(default_factory=dict)
    shm: bool = True
    shm_capacity_bytes: int = 256 << 20
    cache_plane: bool = False
    cache_plane_dir: str = None
    cache_plane_ram_bytes: int = None
    cache_plane_disk_bytes: int = None
    cluster_cache: bool = None
    scheduling: str = 'auto'
    ingest: str = 'auto'
    telemetry_spans: bool = True
    ledger_path: str = None
    drain_timeout_s: float = 30.0
    tenant: str = 'default'
    tenant_weight: float = 1.0
    max_tenant_jobs: int = 8
    tenant_shm_quota_bytes: int = None
    tenant_cache_quota_bytes: int = None
    autoscale: bool = False
    autoscale_min_workers: int = 1
    autoscale_max_workers: int = 8
    autoscale_step: int = 1
    autoscale_cooldown_s: float = 10.0
    autoscale_starve_s: float = 3.0
    autoscale_idle_s: float = 30.0

    def __post_init__(self):
        if self.num_consumers < 1:
            raise ValueError('num_consumers must be >= 1')
        if self.rowgroups_per_split < 1:
            raise ValueError('rowgroups_per_split must be >= 1')
        if self.lease_ttl_s <= 0:
            raise ValueError('lease_ttl_s must be positive')
        if self.max_split_attempts < 1:
            raise ValueError('max_split_attempts must be >= 1')
        if self.credits < 1:
            raise ValueError('credits must be >= 1')
        if self.reader_factory not in ('auto', 'reader', 'batch_reader'):
            raise ValueError("reader_factory must be 'auto', 'reader' or "
                             "'batch_reader', got %r" % (self.reader_factory,))
        if self.shm_capacity_bytes < 1:
            raise ValueError('shm_capacity_bytes must be positive')
        if self.cache_plane and not self.cache_plane_dir:
            raise ValueError('cache_plane=True requires cache_plane_dir')
        if self.cluster_cache is None:
            self.cluster_cache = bool(self.cache_plane)
        if self.cluster_cache and not self.cache_plane:
            raise ValueError('cluster_cache=True requires cache_plane=True '
                             '(the cluster tier shares the plane entries)')
        if self.scheduling not in ('auto', 'fifo', 'adaptive'):
            raise ValueError("scheduling must be 'auto', 'fifo' or "
                             "'adaptive', got %r" % (self.scheduling,))
        if self.ingest not in ('auto', 'plane', 'off'):
            raise ValueError("ingest must be 'auto', 'plane' or 'off', "
                             "got %r" % (self.ingest,))
        if self.drain_timeout_s <= 0:
            raise ValueError('drain_timeout_s must be positive')
        if not self.tenant or not isinstance(self.tenant, str):
            raise ValueError('tenant must be a non-empty string')
        if self.tenant_weight <= 0:
            raise ValueError('tenant_weight must be positive')
        if self.max_tenant_jobs < 1:
            raise ValueError('max_tenant_jobs must be >= 1')
        if self.autoscale:
            if self.autoscale_min_workers < 0:
                raise ValueError('autoscale_min_workers must be >= 0')
            if self.autoscale_max_workers < max(1,
                                                self.autoscale_min_workers):
                raise ValueError('autoscale_max_workers must be >= '
                                 'max(1, autoscale_min_workers)')
            if self.autoscale_step < 1:
                raise ValueError('autoscale_step must be >= 1')
            if self.autoscale_cooldown_s < 0 \
                    or self.autoscale_starve_s < 0 \
                    or self.autoscale_idle_s < 0:
                raise ValueError('autoscale timings must be >= 0')
        if self.heartbeat_interval_s is None:
            self.heartbeat_interval_s = self.lease_ttl_s / 3.0

    def fingerprint(self, num_splits):
        """Identity of the partition geometry a resume token indexes into.

        A client token's ``consumed`` split ids are only meaningful
        against the same (dataset, split size, consumer count, split
        count); the fingerprint rides in both the job info and the token
        so a mismatch raises instead of silently skipping data — the
        service analog of ``Reader._check_resume_topology``.
        """
        key = '%s|%d|%d|%d' % (self.dataset_url, self.num_consumers,
                               self.rowgroups_per_split, num_splits)
        return hashlib.blake2b(key.encode(), digest_size=8).hexdigest()

    def job_info(self, num_splits):
        """The subset workers and clients need, shippable over the wire."""
        return {
            'dataset_url': self.dataset_url,
            'num_consumers': int(self.num_consumers),
            'num_splits': int(num_splits),
            'rowgroups_per_split': int(self.rowgroups_per_split),
            'lease_ttl_s': float(self.lease_ttl_s),
            'credits': int(self.credits),
            'reader_factory': self.reader_factory,
            'reader_kwargs': dict(self.reader_kwargs),
            'shm': bool(self.shm),
            'shm_capacity_bytes': int(self.shm_capacity_bytes),
            'cache_plane': bool(self.cache_plane),
            'cache_plane_dir': self.cache_plane_dir,
            'cache_plane_ram_bytes': self.cache_plane_ram_bytes,
            'cache_plane_disk_bytes': self.cache_plane_disk_bytes,
            'cluster_cache': bool(self.cluster_cache),
            'scheduling': self.scheduling,
            'ingest': self.ingest,
            'telemetry_spans': bool(self.telemetry_spans),
            'drain_timeout_s': float(self.drain_timeout_s),
            'fingerprint': self.fingerprint(num_splits),
            # Multi-tenant serving tier (ISSUE 16).  The dispatcher
            # overlays the assigned 'split_base' when it registers the
            # job; 0 here keeps a bare job_info() self-consistent.
            'tenant': self.tenant,
            'tenant_weight': float(self.tenant_weight),
            'split_base': 0,
            'tenant_shm_quota_bytes': self.tenant_shm_quota_bytes,
            'tenant_cache_quota_bytes': self.tenant_cache_quota_bytes,
        }
