"""Control plane of the disaggregated data service.

The dispatcher never touches row data.  It enumerates the dataset's
row groups once, cuts them into splits (``ServiceConfig
.rowgroups_per_split`` consecutive groups each, split ``i`` owned by
consumer ``i % num_consumers``), and runs a single REP socket serving
short pickled RPCs:

  ``register_worker`` worker announces its data-plane address -> worker_id
  ``heartbeat``       liveness + metrics; renews every lease the worker holds
  ``lease``           hand out one pending split under a TTL lease
  ``complete``        worker finished streaming a split (client acked it)
  ``mark_consumed``   a resuming client retires splits its token already holds
  ``job`` / ``workers`` / ``stats``  discovery + metrics surface
  ``drain``           ask one worker to drain gracefully (via its next
                      heartbeat reply; see ``Worker.drain``)
  ``release``         a draining worker hands back a split it never
                      started (requeued at the FRONT, attempt intact)
  ``deregister``      a drained worker leaves the fleet for good
  ``stop``            remote shutdown (CLI convenience)

Lease expiry is the failure path: a worker that stops heartbeating has
all its leases returned to the pending queue (attempt+1) on the next
serve-loop tick, exactly once — a split is always in exactly one of
pending/leased/done, and a late ``complete`` from the presumed-dead
worker is rejected once the split has moved on.  Exactly-once *delivery*
is finished on the client side (whole-split commit + dedupe by split id);
the dispatcher guarantees exactly-once *assignment* per attempt and
at-least-once decode.

The dispatcher itself stopped being a single point of state loss in
ISSUE 15: with ``ServiceConfig(ledger_path=...)`` every split-state
transition persists to a crash-safe snapshot (``service/ledger.py``),
and a restarted dispatcher reloads it — done splits stay done (no
re-decode of delivered work), attempt counters survive, and in-flight
leases are restored as **orphan leases** that re-registering workers'
``held`` heartbeat claims adopt (the lease resumes) or that requeue
with their attempt count intact after one TTL (the restart was not the
worker's failure).  Workers ride their existing re-register path and
clients their existing resend/re-subscribe path; neither needs to know
the control plane died.

The lease doubles as the **per-piece decode-ownership grant** for the
epoch-cache plane (``ServiceConfig(cache_plane=True)``): every row group
belongs to exactly one split and every split is leased to exactly one
worker per attempt, so exactly one worker decodes (and publishes) each
piece per epoch; every other worker — this run or the next — serves it
as a cache hit.  The plane's cross-process single-flight lock covers the
residual races (lease churn, overlapping epochs/runs), and a cold or
full plane degrades that piece to direct decode — see
``docs/data_service.md`` for the ownership/fallback matrix.
"""

import collections
import dataclasses
import logging
import pickle
import threading
from petastorm_tpu.service import tenancy as _tenancy
from petastorm_tpu.telemetry import decisions as _decisions
from petastorm_tpu.utils.locks import make_lock
import time

logger = logging.getLogger(__name__)

_PENDING, _LEASED, _DONE, _FAILED = 'pending', 'leased', 'done', 'failed'

#: Cache-affinity lease routing (ISSUE 10) — all three knobs bound the
#: preference strictly: affinity may REORDER pending work, never delay
#: it unboundedly.
#: How many pending splits one lease call may look at when choosing.
_AFFINITY_SCAN = 64
#: A worker "holds" a split when it advertises at least this fraction of
#: the split's digests (peer fill covers the remainder).
_AFFINITY_MIN_COVERAGE = 0.5
#: A split held by another live worker is kept back from a cold
#: requester for at most this long (further bounded by lease_ttl/5)
#: before first-come-first-served resumes.  Splits requeued by lease
#: expiry (attempt > 0) are NEVER deferred — reassignment latency is the
#: failure-recovery bound and affinity must not touch it.
_AFFINITY_DEFER_S = 2.0


class Split(object):
    """One leasable unit of decode work: consecutive row-group indices."""

    __slots__ = ('split_id', 'indices', 'consumer', 'attempt', 'state',
                 'worker_id', 'lease_expires', 'affinity_defer_until',
                 'tenant')

    def __init__(self, split_id, indices, consumer, tenant='default'):
        self.split_id = split_id
        self.indices = list(indices)
        self.consumer = consumer
        self.tenant = tenant
        self.attempt = 0
        self.state = _PENDING
        self.worker_id = None
        self.lease_expires = 0.0
        #: Monotonic deadline of this split's affinity preference window
        #: (set on the first deferral, cleared on grant): past it, any
        #: requester gets the split.
        self.affinity_defer_until = None

    def describe(self):
        return {'split_id': self.split_id, 'indices': list(self.indices),
                'consumer': self.consumer, 'attempt': self.attempt,
                'tenant': self.tenant}


def build_splits(num_pieces, rowgroups_per_split, num_consumers,
                 split_base=0, tenant='default'):
    """Cut ``num_pieces`` row groups into Split objects.

    Consecutive grouping keeps each split's reads sequential on disk;
    the consumer assignment is the ``_shard_indices`` modulo contract
    over SPLITS (not row groups), so consumers own disjoint, covering
    subsets by construction.  ``split_base`` offsets the split ids into
    the dispatcher's GLOBAL id space (ISSUE 16: each tenant's slice
    starts where the previous one ended), while the consumer modulo
    runs over the tenant-LOCAL index so sharding is per-job.
    """
    splits = []
    for start in range(0, num_pieces, rowgroups_per_split):
        local = len(splits)
        indices = range(start, min(start + rowgroups_per_split, num_pieces))
        splits.append(Split(split_base + local, indices,
                            local % num_consumers, tenant=tenant))
    return splits


class Dispatcher(object):  # ptlint: disable=pickle-unsafe-attrs — thread-hosted control plane; peers talk to it over ZMQ, never by pickling it
    """Serve the control plane for one job.  Thread-hosted::

        config = ServiceConfig('file:///data/train', num_consumers=2)
        with Dispatcher(config, bind='tcp://127.0.0.1:7777') as d:
            ...  # workers and clients connect to d.addr

    ``bind`` may end in ``:*`` (or ``:0``) to pick a free TCP port; the
    resolved address is ``.addr``.  ``trace_recorder`` (a
    ``benchmark.TraceRecorder``) receives instant markers for every
    lease grant / expiry / completion — the control-plane timeline next
    to the loaders' span streams.
    """

    def __init__(self, config, bind='tcp://127.0.0.1:*', num_pieces=None,
                 trace_recorder=None, launcher=None):
        self._config = config
        self._bind = bind
        self._trace = trace_recorder
        if num_pieces is None:
            num_pieces = _count_row_groups(config.dataset_url,
                                           config.reader_kwargs)
        if num_pieces < 1:
            raise ValueError('dataset %r has no row groups'
                             % (config.dataset_url,))
        self._num_pieces = int(num_pieces)
        self._splits = build_splits(num_pieces, config.rowgroups_per_split,
                                    config.num_consumers,
                                    tenant=config.tenant)
        self._job = config.job_info(len(self._splits))
        # -- multi-tenant serving tier (ISSUE 16) ----------------------------
        # The constructor config IS the default tenant's job; further
        # tenants join over the `register_job` RPC with their own
        # configs, their splits appended to the GLOBAL id space so every
        # split-addressed RPC stays tenant-agnostic.
        self._default_tenant = config.tenant
        self._tenants = _tenancy.TenantRegistry(
            max_jobs=getattr(config, 'max_tenant_jobs', 8))
        self._scheduler = _tenancy.TenantScheduler()
        default_job = _tenancy.TenantJob(
            config.tenant, config.tenant_weight, config, self._job,
            split_base=0, num_splits=len(self._splits),
            num_pieces=self._num_pieces, registered_t=time.monotonic())
        default_job.pending = collections.deque(self._splits)
        self._tenants.admit(default_job)
        self._workers = {}   # worker_id -> {'addr', 'last_heartbeat', 'stats'}
        self._next_worker_id = 0
        self.lease_churn = 0
        # -- cluster cache directory (ISSUE 10) ------------------------------
        # Advisory state only: a wrong/stale entry costs one deferred or
        # misrouted lease, never correctness (workers validate by full
        # digest; the plane validates by content fingerprint).
        from petastorm_tpu.service import cluster as _cluster
        #: worker_id -> set of compact digests its plane holds (replaced
        #: wholesale whenever a heartbeat ships the field).
        self._worker_digests = {}
        #: global piece index -> compact digest, advertised once per job
        #: by the first cluster-enabled worker whose identity resolves.
        self._piece_digests = None
        #: worker_ids whose advertised map was rejected (wrong length =
        #: a different view of the dataset): asked once, declined
        #: permanently — re-asking every beat would warn-spam forever
        #: and re-ship a large invalid list with no path to acceptance.
        self._piece_digests_declined = set()
        self._cluster_on = (bool(self._job.get('cluster_cache'))
                            and not _cluster.killed())
        #: Leases granted to a worker that already held the split
        #: (coverage >= _AFFINITY_MIN_COVERAGE).
        self.affinity_routed = 0
        #: Lease calls answered 'wait' because every scannable split was
        #: inside another worker's preference window.
        self.affinity_deferrals = 0
        # -- crash-survivable control plane (ISSUE 15) -----------------------
        #: Graceful drains completed (deregister RPCs) and drains that
        #: overran their deadline (the worker left with leases live).
        self.drains = 0
        self.drain_timeouts = 0
        #: Restore bookkeeping: lineage restart count (carried in the
        #: ledger file), orphan leases adopted by re-registering
        #: workers' held claims, and orphans requeued attempt-intact.
        self.ledger_restores = 0
        self.ledger_adoptions = 0
        self.ledger_requeues = 0
        self._ledger = None
        self._ledger_dirty = False
        #: data addr -> digest set from the ledger: worker ids are
        #: restart-scoped, so the directory restores by the one identity
        #: that survives — a re-registering worker's data address.
        self._ledger_digests_by_addr = {}
        self._lock = make_lock('service.dispatcher.Dispatcher._lock')
        self._stop = threading.Event()
        self._thread = None
        self._started = threading.Event()
        self.addr = None
        #: Fleet flight recorder (ISSUE 7): same bounded ring every
        #: process keeps, but the frame source is the fleet — the merge
        #: of every worker's heartbeat registry snapshot plus the
        #: control-plane state.  Ticked from the serve loop (no extra
        #: thread in the control plane); consecutive frames subtract
        #: into the windowed deltas the ``stats`` health report reads.
        from petastorm_tpu.telemetry import MetricsRegistry
        from petastorm_tpu.telemetry.flight import (FlightRecorder,
                                                    default_persist_path)
        self.flight = FlightRecorder(source=self._fleet_snapshot,
                                     label='dispatcher_fleet',
                                     persist_path=default_persist_path(
                                         'dispatcher'))
        #: Health gauges land here so any Prometheus scrape of the
        #: dispatcher process carries them (``render_prometheus``).
        self.metrics = MetricsRegistry('dispatcher')
        # -- control-plane decision journal (ISSUE 20) -----------------------
        #: Every autonomous action the dispatcher-side control laws take
        #: (autoscaler, WDRR tenant picks, affinity routing) lands here;
        #: each record marks the ledger dirty so the journal persists on
        #: the next serve-loop tick and a restart keeps the history.
        self._decisions = _decisions.DecisionJournal(label='dispatcher')
        self._decisions.on_record = lambda rec: self._ledger_mark()
        self._scheduler.decisions = self._decisions
        # -- closed-loop autoscaler (ISSUE 16) -------------------------------
        # An in-dispatcher tick controller (flight-recorder pattern, no
        # extra thread); PETASTORM_TPU_NO_AUTOSCALE=1 beats the config.
        self.autoscaler = None
        if getattr(config, 'autoscale', False):
            from petastorm_tpu.service import autoscaler as _autoscaler
            if launcher is None:
                launcher = _autoscaler.SubprocessWorkerLauncher()
            self.autoscaler = _autoscaler.Autoscaler(config, launcher)
            self.autoscaler.decisions = self._decisions
        # -- materialize hand-off (ISSUE 18) ---------------------------------
        # When a controller is attached, scale-in victims are offered for
        # one bounded warming pass before their drain proceeds: idle
        # capacity warms datasets instead of dying.
        self._materializer = None
        self._deferred_drains = {}   # victim worker id -> drain deadline
        self.materialize_handoffs = 0
        if getattr(config, 'ledger_path', None):
            from petastorm_tpu.service.ledger import DispatcherLedger
            # acquire() raises against a live owner BEFORE any state is
            # touched: two control planes on one ledger never coexist.
            self._ledger = DispatcherLedger(config.ledger_path).acquire()
            self._restore_from_ledger(self._ledger.load())
            # First snapshot immediately (cold start) / persist the
            # incremented restore count (restart) — the file must name
            # this incarnation before the first worker registers.
            self._ledger_save(force=True)

    # -- durable ledger (ISSUE 15) -------------------------------------------

    def _restore_from_ledger(self, state):
        """Apply a loaded snapshot, or cold-start on any mismatch.  A
        ledger from a different partition geometry is ignored whole
        (its split ids index a different world) — same gate the client
        resume token passes through."""
        from petastorm_tpu.service import ledger as _ledger_mod
        if state is None:
            return
        if state.get('fingerprint') != self._job['fingerprint']:
            logger.warning(
                'ledger %s was written under a different partition '
                'geometry (fingerprint mismatch); cold start',
                self._ledger.path)
            return
        # v2 tenant table (ISSUE 16): rebuild every non-default tenant's
        # job BEFORE gating on the flat split list — staged, so any
        # rejection cold-starts WHOLE (a v1 file has no table and
        # restores as the single default-tenant job it describes).
        staged, base = [], len(self._splits)
        from petastorm_tpu.service.config import ServiceConfig
        for entry in state.get('tenants') or ():
            try:
                cfg = ServiceConfig(
                    **_tenancy.config_from_jsonable(entry['config']))
                tenant = str(entry['tenant'])
                if int(entry['split_base']) != base:
                    raise ValueError('split_base %r, expected %d'
                                     % (entry['split_base'], base))
                splits = build_splits(int(entry['num_pieces']),
                                      cfg.rowgroups_per_split,
                                      cfg.num_consumers,
                                      split_base=base, tenant=tenant)
                if len(splits) != int(entry['num_splits']):
                    raise ValueError('rebuilt %d splits, recorded %d'
                                     % (len(splits), entry['num_splits']))
            except Exception as e:  # noqa: BLE001 — reject whole
                logger.warning('ledger %s tenant table undecodable '
                               '(%s: %s); cold start', self._ledger.path,
                               type(e).__name__, e)
                return
            job = _tenancy.TenantJob(
                tenant, float(entry.get('weight', 1.0)), cfg,
                dict(cfg.job_info(len(splits)), split_base=base),
                split_base=base, num_splits=len(splits),
                num_pieces=int(entry['num_pieces']),
                registered_t=time.monotonic())
            staged.append((job, splits))
            base += len(splits)
        if len(staged) + 1 > self._tenants.max_jobs:
            logger.warning(
                'ledger %s holds %d tenant jobs over this dispatcher\'s '
                'max_tenant_jobs=%d; cold start', self._ledger.path,
                len(staged) + 1, self._tenants.max_jobs)
            return
        if int(state.get('num_splits', -1)) != base:
            logger.warning(
                'ledger %s was written under a different partition '
                'geometry (num_splits mismatch); cold start',
                self._ledger.path)
            return
        try:
            records = _ledger_mod.decode_splits(state['splits'])
        except (KeyError, TypeError, ValueError) as e:
            logger.warning('ledger %s has undecodable split records '
                           '(%s); cold start', self._ledger.path, e)
            return
        if len(records) != base:
            # Rejected WHOLE: zip() would silently truncate and
            # half-apply a short record list (tail splits re-decoding
            # at attempt 0 contradicts everything the ledger promises).
            logger.warning(
                'ledger %s holds %d split records for a %d-split job; '
                'cold start', self._ledger.path, len(records), base)
            return
        for job, splits in staged:
            self._splits.extend(splits)
            self._tenants.admit(job)
        now = time.monotonic()
        restored = collections.Counter()
        for split, (split_state, attempt) in zip(self._splits, records):
            split.attempt = attempt
            restored[split_state] += 1
            if split_state == _DONE:
                split.state = _DONE
            elif split_state == _FAILED:
                split.state = _FAILED
            elif split_state == _LEASED:
                # Orphan lease: held by nobody until a re-registering
                # worker's `held` heartbeat claim adopts it; expiring
                # unclaimed requeues it attempt-INTACT (_expire_leases).
                split.state = _LEASED
                split.worker_id = None
                split.lease_expires = now + self._config.lease_ttl_s
        for job in self._tenants.jobs():
            job.pending = collections.deque(
                s for s in self._splits[job.split_base:
                                        job.split_base + job.num_splits]
                if s.state == _PENDING)
        self._ledger_digests_by_addr = {
            str(addr): {str(d) for d in digests}
            for addr, digests in (state.get('worker_digests') or {}).items()}
        pieces = state.get('piece_digests')
        if self._cluster_on and pieces \
                and len(pieces) == self._num_pieces:
            self._piece_digests = [str(d) for d in pieces]
        # Decision history (ISSUE 20) survives the restart attempt-
        # intact: the dead incarnation's records restore verbatim, so
        # `petastorm-tpu-why` still explains a pre-kill drain.
        if state.get('decisions'):
            self._decisions.restore(state['decisions'])
        self.ledger_restores = int(state.get('restores', 0)) + 1
        logger.info(
            'ledger %s restored (restart #%d): %d done / %d leased '
            '(orphaned) / %d pending / %d failed splits, %d worker '
            'digest sets, piece map %s', self._ledger.path,
            self.ledger_restores, restored[_DONE], restored[_LEASED],
            restored[_PENDING], restored[_FAILED],
            len(self._ledger_digests_by_addr),
            'restored' if self._piece_digests is not None else 'absent')

    def _ledger_state(self):
        """Snapshot dict for :meth:`ledger.DispatcherLedger.save`
        (caller must NOT hold ``self._lock``)."""
        from petastorm_tpu.service import ledger as _ledger_mod
        # Outside self._lock: the journal has its own (leaf) lock and
        # the dump needs no dispatcher state.
        decisions_dump = self._decisions.dump()
        with self._lock:
            digests = {self._workers[wid]['addr']: sorted(held)
                       for wid, held in self._worker_digests.items()
                       if wid in self._workers}
            # Directory entries of not-yet-re-registered workers survive
            # a SECOND restart too: carry restored-but-unclaimed addrs.
            for addr, held in self._ledger_digests_by_addr.items():
                digests.setdefault(addr, sorted(held))
            # v2 tenant table (ISSUE 16): everything needed to rebuild a
            # non-default tenant's job at restore WITHOUT touching its
            # dataset (num_pieces is recorded, not re-counted).  The
            # default tenant is the constructor config and needs no row.
            from petastorm_tpu.service import tenancy as _tenancy
            tenants = [
                {'tenant': job.tenant, 'weight': job.weight,
                 'split_base': job.split_base,
                 'num_splits': job.num_splits,
                 'num_pieces': job.num_pieces,
                 'config': _tenancy.config_to_jsonable(
                     dataclasses.asdict(job.config))}
                for job in self._tenants.jobs() if job.split_base > 0]
            return {
                'fingerprint': self._job['fingerprint'],
                'dataset_url': self._config.dataset_url,
                'num_splits': len(self._splits),
                'splits': _ledger_mod.encode_splits(self._splits),
                'worker_digests': digests,
                'piece_digests': self._piece_digests,
                'tenants': tenants,
                'decisions': decisions_dump,
                'restores': self.ledger_restores,
                'saved_unix': time.time(),
            }

    def _ledger_save(self, force=False):
        """Persist when dirty (serve-loop tick) or unconditionally
        (``force=True`` — the write-ahead transitions: complete /
        mark_consumed / deregister persist BEFORE their reply)."""
        if self._ledger is None or not (force or self._ledger_dirty):
            return
        self._ledger_dirty = False
        if self._ledger.save(self._ledger_state()) is None:
            # Best-effort save failed (ENOSPC, unwritable dir): keep
            # the dirty flag so the next tick retries instead of
            # silently dropping the pending transitions.
            self._ledger_dirty = True

    def _ledger_mark(self):
        if self._ledger is not None:
            self._ledger_dirty = True

    def _ledger_done(self, split_id):
        """O(1) write-ahead record for one work-retiring transition:
        journal line now (BEFORE the RPC reply), full snapshot on the
        next serve-loop tick (which truncates the journal)."""
        if self._ledger is not None:
            self._ledger.append({'op': 'done', 'split': int(split_id)})
            self._ledger_dirty = True

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._serve,
                                        name='service-dispatcher', daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError('dispatcher failed to bind %r' % (self._bind,))
        return self

    def stop(self):
        self._stop.set()

    def join(self):
        if self._thread is not None:
            self._thread.join()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()

    # -- serve loop ----------------------------------------------------------

    def _serve(self):
        import zmq

        context = zmq.Context()
        socket = context.socket(zmq.REP)
        try:
            if self._bind.startswith('tcp') and (
                    self._bind.endswith(':*') or self._bind.endswith(':0')):
                port = socket.bind_to_random_port(
                    self._bind.rsplit(':', 1)[0])
                self.addr = '%s:%d' % (self._bind.rsplit(':', 1)[0], port)
            else:
                socket.bind(self._bind)
                self.addr = self._bind
        except Exception:
            socket.close(0)
            context.term()
            self._started.set()  # unblock start(); addr stays None
            raise
        self._started.set()
        poller = zmq.Poller()
        poller.register(socket, zmq.POLLIN)
        try:
            from petastorm_tpu.test_util import chaos
            while not self._stop.is_set():
                self._expire_leases()
                # Dirty-flag snapshot per tick: lease grants/expiries
                # reach the ledger within one loop turn (the write-ahead
                # transitions already saved synchronously).
                self._ledger_save()
                # One fleet flight frame per interval, from the loop the
                # control plane already runs (contained inside tick()).
                self.flight.maybe_tick()
                # Closed-loop autoscaler tick (ISSUE 16): same pattern —
                # observe under the lock, act outside it.
                self._autoscale_tick()
                if not dict(poller.poll(100)):
                    continue
                raw = socket.recv()
                try:
                    request = pickle.loads(raw)
                    if not isinstance(request, dict):
                        raise TypeError('expected dict, got %s'
                                        % type(request).__name__)
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    # A malformed peer (non-pickle frame, non-dict
                    # payload) must cost one error reply, never the
                    # serve thread: a dead REP socket wedges every
                    # worker and client in the fleet.
                    socket.send(pickle.dumps(
                        {'error': 'malformed request: %s: %s'
                                  % (type(e).__name__, e)}, protocol=4))
                    continue
                # Chaos seam (ISSUE 15): the REP contract forbids a
                # dropped reply (the socket would wedge), so the
                # control-plane fault model here is DELAY — lost
                # requests/replies are injected at the callers' REQ
                # seam ('rpc.request'), where timeout+retry lives.
                chaos.inject('dispatcher.rpc', op=request.get('op'))
                try:
                    reply = self._dispatch(request)
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    logger.exception('dispatcher RPC %r failed',
                                     request.get('op'))
                    reply = {'error': '%s: %s' % (type(e).__name__, e)}
                socket.send(pickle.dumps(reply, protocol=4))
                if request.get('op') == 'stop':
                    break
        finally:
            # The ring is the postmortem: leave the last window on disk
            # when a flight dir is configured (best-effort by contract).
            self.flight.persist(reason='dispatcher_exit')
            if self.autoscaler is not None:
                # Reap launcher-owned worker children: an exiting control
                # plane must not strand the processes it spawned.
                self.autoscaler.close()
            if self._ledger is not None:
                # Final snapshot + owner release: the FILE stays — it is
                # the next incarnation's restore source.
                self._ledger_save(force=True)
                self._ledger.release()
            socket.close(0)
            context.term()

    def _fleet_snapshot(self):
        """Fleet-merged registry snapshot + control-plane overlay — the
        flight-recorder frame source.  Heartbeat snapshots merge by
        bucket addition (fleet-cumulative, so consecutive frames
        subtract cleanly); split states ride as gauges, lease churn as
        a counter."""
        from petastorm_tpu.telemetry import merge_snapshots
        with self._lock:
            snaps = [w['stats'].get('registry')
                     for w in self._workers.values()]
            states = collections.Counter(s.state for s in self._splits)
            alive = len(self._workers)
        merged = merge_snapshots(snaps)
        merged['namespace'] = 'fleet'
        merged['gauges'].update({
            'splits_pending': states[_PENDING],
            'splits_leased': states[_LEASED],
            'splits_done': states[_DONE],
            'splits_failed': states[_FAILED],
            'workers_registered': alive,
        })
        merged['counters']['lease_churn'] = self.lease_churn
        # Control-plane cluster counters: the worker-side ones
        # (cache_remote_hits / peer_fills / peer_degraded) already ride
        # the merged heartbeat registries above.
        merged['counters']['cache_affinity_routed'] = self.affinity_routed
        # Crash-survivable control plane (ISSUE 15): restore/drain
        # traffic in the flight ring, so windowed deltas can say "the
        # control plane restarted inside this window".
        merged['counters']['ledger_restores'] = self.ledger_restores
        merged['counters']['drains'] = self.drains
        merged['counters']['drain_timeouts'] = self.drain_timeouts
        # Materialize hand-off (ISSUE 18): scale-in victims that ran a
        # warming pass before draining.
        merged['counters']['materialize_handoffs'] = \
            self.materialize_handoffs
        # Multi-tenant serving tier (ISSUE 16): per-tenant grant
        # counters in the ring — their windowed deltas are the
        # tenant-starved evidence (one tenant's grants flat while
        # another's climb) — plus the autoscaler's action counters so
        # the chaos scale-storm bound reads from the same frames.
        with self._lock:
            for job in self._tenants.jobs():
                merged['counters']['tenant_grants:%s' % job.tenant] = \
                    job.grants
        if self.autoscaler is not None:
            merged['counters']['autoscale_outs'] = self.autoscaler.scale_outs
            merged['counters']['autoscale_ins'] = self.autoscaler.scale_ins
        return merged

    # -- closed-loop autoscaler (ISSUE 16) -----------------------------------

    #: A scale-in victim offered to the materializer warms for at most
    #: this long before its drain proceeds regardless (the hand-off must
    #: never turn scale-in into scale-never).
    DRAIN_WARM_DEADLINE_S = 30.0

    def attach_materializer(self, controller):
        """Attach a :class:`materialize.MaterializeController`: scale-in
        victims get one bounded warming pass (piece-granular, through the
        controller's lease protocol) before their drain is executed."""
        self._materializer = controller

    def _drain_worker(self, victim):
        with self._lock:
            worker = self._workers.get(victim)
            if worker is not None:
                worker['draining'] = True

    def _tick_deferred_drains(self, now):
        """Execute drains whose warming pass finished (or timed out)."""
        materializer = self._materializer
        for victim, deadline in list(self._deferred_drains.items()):
            ready = now >= deadline
            if not ready:
                try:
                    ready = materializer is None \
                        or materializer.drain_ready(victim)
                except Exception:  # noqa: BLE001 — hand-off is best-effort
                    ready = True
            if ready:
                del self._deferred_drains[victim]
                self._drain_worker(victim)
                logger.info('autoscaler draining worker %s (warming pass '
                            'done)', victim)

    def _autoscale_tick(self):
        """One control-law evaluation: observation built under the lock,
        the (blocking) spawn/drain action executed outside it by the
        autoscaler/drain machinery the dispatcher already has."""
        if self.autoscaler is None or not self.autoscaler.enabled:
            return
        stale = 3.0 * self._config.lease_ttl_s
        now = time.monotonic()
        self._tick_deferred_drains(now)
        with self._lock:
            states = collections.Counter(s.state for s in self._splits)
            pending, leased = states[_PENDING], states[_LEASED]
            alive = [wid for wid, w in sorted(self._workers.items())
                     if not w.get('draining')
                     and (now - w['last_heartbeat']) < stale]
            held = collections.Counter(
                s.worker_id for s in self._splits
                if s.state == _LEASED and s.worker_id is not None)
            free_slots = sum(
                max(0, self._config.max_inflight_splits - held[wid])
                for wid in alive)
            coverage = {wid: len(self._worker_digests.get(wid, ()))
                        for wid in alive}
        action = self.autoscaler.maybe_tick({
            'pending': pending, 'leased': leased, 'alive': alive,
            'free_slots': free_slots, 'coverage': coverage,
            'dispatcher_addr': self.addr}, now=now)
        if action and action[0] == 'scale_in':
            victim = action[1]
            materializer = self._materializer
            if materializer is not None \
                    and victim not in self._deferred_drains:
                offered = False
                try:
                    offered = materializer.offer_drain_candidate(
                        victim, deadline_s=self.DRAIN_WARM_DEADLINE_S)
                except Exception:  # noqa: BLE001 — hand-off is best-effort
                    logger.warning('materialize drain hand-off for %s '
                                   'failed', victim, exc_info=True)
                if offered:
                    self._deferred_drains[victim] = \
                        now + self.DRAIN_WARM_DEADLINE_S
                    self.materialize_handoffs += 1
                    logger.info('autoscaler victim %s offered to the '
                                'materializer for one warming pass before '
                                'drain', victim)
                    return
            self._drain_worker(victim)
            logger.info('autoscaler draining worker %s (least cache '
                        'coverage)', victim)

    # -- lease bookkeeping ---------------------------------------------------

    def _pending_for(self, split):
        """The owning tenant's pending deque (caller holds the lock).
        Splits always carry the tenant they were built under; a missing
        job (evicted tenant) falls back to the default job's deque so a
        requeue can never drop work on the floor."""
        job = self._tenants.get(split.tenant) \
            or self._tenants.get(self._default_tenant)
        return job.pending

    def _expire_leases(self):
        now = time.monotonic()
        max_attempts = self._config.max_split_attempts
        with self._lock:
            for split in self._splits:
                if split.state == _LEASED and split.lease_expires < now:
                    if split.worker_id is None:
                        # Ledger-restored orphan nobody claimed within
                        # the grace TTL: requeue with the attempt count
                        # INTACT — a dispatcher restart is not the
                        # worker's failure and must not walk the split
                        # toward the max_split_attempts poison ceiling.
                        logger.info(
                            'restored lease on split %d unclaimed; '
                            'requeueing at attempt %d',
                            split.split_id, split.attempt)
                        split.state = _PENDING
                        self._pending_for(split).append(split)
                        self.ledger_requeues += 1
                        self._ledger_mark()
                        continue
                    split.worker_id = None
                    split.attempt += 1
                    self.lease_churn += 1
                    self._ledger_mark()
                    if split.attempt >= max_attempts:
                        # Every worker that touched this split walked away
                        # (undecodable row group, poisoned data): a terminal
                        # state the clients can SEE beats an infinite
                        # pending->leased->expired loop they silently hang
                        # behind.
                        logger.error(
                            'split %d failed %d lease attempts; marking '
                            'failed', split.split_id, split.attempt)
                        split.state = _FAILED
                    else:
                        logger.warning(
                            'lease on split %d (attempt %d) expired; '
                            'requeueing', split.split_id, split.attempt)
                        split.state = _PENDING
                        self._pending_for(split).append(split)
                    if self._trace is not None:
                        self._trace.instant('service/lease_expired',
                                            split=split.split_id)

    def _dispatch(self, request):
        op = request.get('op')
        handler = getattr(self, '_op_' + str(op), None)
        if handler is None:
            return {'error': 'unknown op %r' % (op,)}
        return handler(request)

    # -- RPC handlers --------------------------------------------------------

    def _op_register_worker(self, request):
        with self._lock:
            worker_id = 'w%d' % self._next_worker_id
            self._next_worker_id += 1
            self._workers[worker_id] = {
                'addr': request['data_addr'],
                'last_heartbeat': time.monotonic(),
                'stats': {},
                'draining': False,
            }
            # Ledger-restored cache directory (ISSUE 15): the data addr
            # is the identity that survives a dispatcher restart, so a
            # re-registering worker re-enters the directory immediately
            # instead of waiting for its next on-change advertisement.
            held = self._ledger_digests_by_addr.pop(
                request['data_addr'], None)
            if held:
                self._worker_digests[worker_id] = set(held)
        logger.info('registered worker %s at %s', worker_id,
                    request['data_addr'])
        # t_mono: the registration doubles as the clock-offset handshake
        # (ISSUE 5) — the worker records (its_clock - ours) against the
        # send/recv midpoint and ships the offset on every heartbeat.
        return {'worker_id': worker_id, 'job': self._job,
                't_mono': time.monotonic()}

    def _op_clock(self, request):
        """Bare clock handshake for clients/tools that registered nothing
        (``telemetry.measure_clock_offset`` against this endpoint)."""
        return {'t_mono': time.monotonic()}

    def _op_heartbeat(self, request):
        worker_id = request['worker_id']
        # ``held``: the split ids the worker still claims.  Renewing ONLY
        # those lets a split the worker abandoned (decode error) expire and
        # reassign while the worker itself stays alive; a heartbeat without
        # the field (older workers) renews every lease it holds.
        held = request.get('held')
        if held is not None:
            held = {int(s) for s in held}
        now = time.monotonic()
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is None:
                return {'ok': False, 'error': 'unknown worker %r' % worker_id}
            worker['last_heartbeat'] = now
            if request.get('stats'):
                worker['stats'] = dict(request['stats'])
            if request.get('draining'):
                # Worker-initiated drain (SIGTERM): the fleet view must
                # show it draining, same as a `drain`-RPC'd worker.
                worker['draining'] = True
            # Cluster cache directory (ISSUE 10): the advertised digest
            # set replaces wholesale (workers only ship it on change);
            # the piece-digest map is per-job, first valid one wins.
            if request.get('cache_digests') is not None:
                self._worker_digests[worker_id] = {
                    str(d) for d in request['cache_digests']}
            pieces = request.get('piece_digests')
            if self._cluster_on and pieces and self._piece_digests is None:
                pieces = [str(d) for d in pieces]
                if len(pieces) == self._num_pieces:
                    self._piece_digests = pieces
                elif worker_id not in self._piece_digests_declined:
                    self._piece_digests_declined.add(worker_id)
                    logger.warning(
                        'worker %s advertised %d piece digests for a '
                        '%d-piece job (differing dataset view); '
                        'declining its map permanently', worker_id,
                        len(pieces), self._num_pieces)
            need_pieces = (self._cluster_on
                           and self._piece_digests is None
                           and worker_id not in
                           self._piece_digests_declined)
            for split in self._splits:
                if split.state == _LEASED and split.worker_id == worker_id \
                        and (held is None or split.split_id in held):
                    split.lease_expires = now + self._config.lease_ttl_s
                elif split.state == _LEASED and split.worker_id is None \
                        and held is not None and split.split_id in held:
                    # Reconciliation (ISSUE 15): a ledger-restored
                    # orphan lease the worker still holds RESUMES under
                    # its post-restart worker id — the split streams on,
                    # attempt intact, nothing re-decodes.
                    split.worker_id = worker_id
                    split.lease_expires = now + self._config.lease_ttl_s
                    self.ledger_adoptions += 1
                    self._ledger_mark()
                    logger.info('worker %s re-claimed restored lease on '
                                'split %d (attempt %d)', worker_id,
                                split.split_id, split.attempt)
            draining = bool(worker.get('draining'))
        # t_mono: every heartbeat doubles as a clock re-handshake (ISSUE
        # 7 satellite) — long-lived workers drift off their one
        # registration-time offset, so the worker EWMAs the midpoint
        # estimate from each beat and ships `clock_drift_ms` back.
        return {'ok': True, 't_mono': time.monotonic(),
                'need_piece_digests': need_pieces,
                # Dispatcher-initiated drain (the `drain` RPC) reaches
                # the worker here, on the channel it already polls.
                'drain': draining}

    # -- cache-affinity helpers (ISSUE 10; callers hold self._lock) ----------

    def _split_cdigests(self, split):
        """Compact digests of a split's pieces, or None before any
        worker advertised the piece map."""
        if self._piece_digests is None:
            return None
        return [self._piece_digests[i] for i in split.indices]

    def _coverage(self, split, worker_id):
        """Fraction of the split's digests the worker advertises, or
        None without directory evidence."""
        held = self._worker_digests.get(worker_id)
        digests = self._split_cdigests(split)
        if not held or not digests:
            return None
        return sum(1 for d in digests if d in held) / float(len(digests))

    def _alive_holder(self, split, exclude_worker):
        """Another live worker that holds this split (the deferral
        predicate — and the holder set must be workers that can actually
        be leased to, hence the heartbeat-staleness gate)."""
        digests = self._split_cdigests(split)
        if not digests:
            return None
        stale = 3.0 * self._config.lease_ttl_s
        now = time.monotonic()
        for wid, held in self._worker_digests.items():
            if wid == exclude_worker or wid not in self._workers:
                continue
            if now - self._workers[wid]['last_heartbeat'] >= stale:
                continue
            if sum(1 for d in digests if d in held) \
                    >= _AFFINITY_MIN_COVERAGE * len(digests):
                return wid
        return None

    def _split_holders(self, split, exclude_worker):
        """cdigest -> [data addr, ...] of live peers holding it — the
        lease reply's peer-fill hints."""
        digests = self._split_cdigests(split)
        if not digests:
            return None
        stale = 3.0 * self._config.lease_ttl_s
        now = time.monotonic()
        holders = {}
        for wid, held in self._worker_digests.items():
            worker = self._workers.get(wid)
            if wid == exclude_worker or worker is None:
                continue
            if now - worker['last_heartbeat'] >= stale:
                continue
            for digest in digests:
                if digest in held:
                    holders.setdefault(digest, []).append(worker['addr'])
        return holders or None

    def _choose_pending(self, job, worker_id, consumers):
        """Pop the split (from tenant ``job``'s queue) to lease to
        ``worker_id`` (None = nothing assignable now).  FIFO, except
        that with directory evidence the call prefers (within a bounded
        scan window) a split the requester already holds, and keeps a
        split another live worker holds back from a cold requester for
        a bounded window.  Splits requeued by lease expiry (attempt > 0)
        are never kept back.  The WDRR scheduler picked the tenant;
        this picks the split WITHIN it — the two compose, affinity
        never overrides fair share."""
        pending = job.pending
        affinity = (self._cluster_on and self._piece_digests is not None
                    and bool(self._worker_digests))
        window, skipped = [], []
        limit = _AFFINITY_SCAN if affinity else 1
        while pending and len(window) < limit:
            split = pending.popleft()
            if split.state != _PENDING:
                continue  # completed via mark_consumed while queued
            if consumers is not None and split.consumer not in consumers:
                skipped.append(split)
                continue
            window.append(split)
        chosen = None
        routed = False
        if affinity and window:
            for split in window:
                coverage = self._coverage(split, worker_id)
                if coverage is not None \
                        and coverage >= _AFFINITY_MIN_COVERAGE:
                    chosen, routed = split, True
                    _decisions.record_decision(
                        'affinity', 'routed', 'affinity_min_coverage',
                        {'coverage': coverage,
                         'min_coverage': _AFFINITY_MIN_COVERAGE,
                         'scanned': len(window)},
                        worker_id=worker_id, split_id=split.split_id,
                        tenant=job.tenant, journal=self._decisions)
                    break
        if chosen is None:
            now = time.monotonic()
            defer_s = min(_AFFINITY_DEFER_S,
                          self._config.lease_ttl_s / 5.0)
            for split in window:
                if affinity and split.attempt == 0 \
                        and self._alive_holder(split, worker_id):
                    if split.affinity_defer_until is None:
                        split.affinity_defer_until = now + defer_s
                    if now < split.affinity_defer_until:
                        continue  # inside its holder's preference window
                    _decisions.record_decision(
                        'affinity', 'deferral_exhausted',
                        'affinity_defer_s',
                        {'waited_s': defer_s + now
                         - split.affinity_defer_until,
                         'defer_s': defer_s},
                        worker_id=worker_id, split_id=split.split_id,
                        tenant=job.tenant, journal=self._decisions)
                chosen = split
                break
            if chosen is None and window:
                self.affinity_deferrals += 1
                # The requester got nothing because every scanned split
                # is inside a holder's preference window — a suppressed
                # non-action the journal must explain.
                _decisions.record_decision(
                    'affinity', 'deferred', 'affinity_defer_s',
                    {'waited_s': max(
                        0.0, defer_s + now
                        - min(s.affinity_defer_until for s in window
                              if s.affinity_defer_until is not None)),
                     'defer_s': defer_s, 'scanned': len(window)},
                    suppressed=True, worker_id=worker_id,
                    tenant=job.tenant, journal=self._decisions)
        # Unchosen window members go back to the FRONT in order (the
        # scan must not rotate the FIFO); consumer-mismatched splits
        # rejoin at the back exactly as before.
        for split in reversed([s for s in window if s is not chosen]):
            pending.appendleft(split)
        pending.extend(skipped)
        return chosen, routed

    @staticmethod
    def _parse_lease_consumers(consumers):
        """``consumers`` from the wire → {tenant: {consumer, ...}} or
        None (no filter).  Workers ship the tenant-qualified form
        ``[[tenant, consumer], ...]``; a bare int (pre-ISSUE-16 worker)
        means the default tenant's consumer — the single-tenant wire
        protocol unchanged."""
        if consumers is None:
            return None
        by_tenant = {}
        for entry in consumers:
            if isinstance(entry, (list, tuple)):
                tenant, consumer = entry
            else:
                tenant, consumer = _tenancy.DEFAULT_TENANT, entry
            by_tenant.setdefault(str(tenant), set()).add(int(consumer))
        return by_tenant

    def _op_lease(self, request):
        worker_id = request['worker_id']
        # ``consumers``: the (tenant, consumer) pairs with a live
        # subscriber on the requesting worker.  Leasing only their
        # splits keeps a worker from decoding splits whose training host
        # is absent (they would stall its shared send buffer); a request
        # without the field leases anything.
        by_tenant = self._parse_lease_consumers(request.get('consumers'))
        with self._lock:
            if worker_id not in self._workers:
                return {'error': 'unknown worker %r' % worker_id}
            self._workers[worker_id]['last_heartbeat'] = time.monotonic()
            if self._workers[worker_id].get('draining'):
                # A draining worker gets no new work — the scale-in
                # contract; its in-flight splits finish or hand back.
                return {'wait': True, 'drain': True}
            # Two-level pick: WDRR chooses the tenant, the affinity
            # scan chooses the split within it.  A tenant whose every
            # candidate is affinity-deferred refunds its debit and the
            # grant falls through to the next tenant — deferral must
            # not eat a tenant's fair share.
            chosen, routed = None, False
            tried = set()
            while chosen is None:
                eligible = [
                    j for j in self._tenants.jobs()
                    if j.tenant not in tried and j.pending
                    and (by_tenant is None or j.tenant in by_tenant)]
                tenant = self._scheduler.pick(eligible)
                if tenant is None:
                    break
                job = self._tenants.get(tenant)
                cfilter = (None if by_tenant is None
                           else by_tenant.get(tenant))
                chosen, routed = self._choose_pending(
                    job, worker_id, cfilter)
                if chosen is None:
                    self._scheduler.refund(tenant)
                    tried.add(tenant)
                else:
                    job.grants += 1
            if chosen is not None:
                chosen.state = _LEASED
                chosen.worker_id = worker_id
                chosen.lease_expires = (time.monotonic()
                                        + self._config.lease_ttl_s)
                chosen.affinity_defer_until = None
                self._ledger_mark()
                if routed:
                    self.affinity_routed += 1
                holders = (self._split_holders(chosen, worker_id)
                           if self._cluster_on else None)
                if self._trace is not None:
                    self._trace.instant('service/lease_grant',
                                        split=chosen.split_id,
                                        worker=worker_id,
                                        attempt=chosen.attempt)
                reply = {'split': chosen.describe(),
                         'ttl': self._config.lease_ttl_s}
                if holders:
                    reply['holders'] = holders
                return reply
            # 'done' is scoped to the tenants this worker serves: a
            # worker streaming tenant A must not park because tenant B
            # still has work (and vice versa a global check would hang
            # A's worker on B's tail).
            relevant = [j for j in self._tenants.jobs()
                        if by_tenant is None or j.tenant in by_tenant]
            if relevant and all(
                    s.state in (_DONE, _FAILED)
                    for j in relevant
                    for s in self._splits[j.split_base:
                                          j.split_base + j.num_splits]):
                return {'done': True}
            return {'wait': True}

    def _op_complete(self, request):
        worker_id, split_id = request['worker_id'], request['split_id']
        with self._lock:
            split = self._splits[split_id]
            if split.state == _DONE:
                return {'ok': True}  # idempotent (e.g. duplicate delivery)
            if split.state != _LEASED or split.worker_id != worker_id \
                    or split.attempt != request.get('attempt', split.attempt):
                # The lease moved on (expired + reassigned): this worker's
                # stream either already reached the client (who deduped it)
                # or died with the worker — either way this completion has
                # no standing.
                return {'ok': False}
            split.state = _DONE
            split.worker_id = None
            if self._trace is not None:
                self._trace.instant('service/split_done', split=split_id,
                                    worker=worker_id)
        # Write-ahead for the transition that retires work: the durable
        # record exists BEFORE the worker hears 'ok' (a restart between
        # the two costs one idempotent re-complete, never a re-decode).
        self._ledger_done(split_id)
        return {'ok': True}

    def _op_mark_consumed(self, request):
        """A resuming client already holds these splits' rows (its resume
        token committed them); retire them so no worker re-decodes.  A
        split already streaming stays leased — the client drops the
        duplicate, so marking here is an optimization, not a correctness
        requirement."""
        retired = []
        with self._lock:
            for split_id in request['split_ids']:
                split = self._splits[int(split_id)]
                if split.state == _PENDING:
                    split.state = _DONE
                    retired.append(split.split_id)
        for split_id in retired:
            self._ledger_done(split_id)  # write-ahead: see _op_complete
        return {'ok': True, 'retired': len(retired)}

    # -- graceful drain (ISSUE 15) -------------------------------------------

    def _op_drain(self, request):
        """Mark one worker draining; it learns on its next heartbeat
        reply (or lease refusal) and runs its local drain path — finish
        or hand back in-flight splits, flush shm, deregister."""
        worker_id = request['worker_id']
        with self._lock:
            worker = self._workers.get(worker_id)
            if worker is None:
                return {'ok': False, 'error': 'unknown worker %r'
                                              % worker_id}
            worker['draining'] = True
        logger.info('worker %s marked draining', worker_id)
        return {'ok': True}

    def _op_release(self, request):
        """A draining worker hands back a split it leased but never
        started decoding: requeued at the FRONT of the queue (it was
        next in line), attempt count INTACT (nothing failed)."""
        worker_id, split_id = request['worker_id'], int(request['split_id'])
        with self._lock:
            split = self._splits[split_id]
            if split.state != _LEASED or split.worker_id != worker_id \
                    or split.attempt != request.get('attempt',
                                                    split.attempt):
                return {'ok': False}  # the lease moved on; nothing to do
            split.state = _PENDING
            split.worker_id = None
            self._pending_for(split).appendleft(split)
            self._ledger_mark()
            if self._trace is not None:
                self._trace.instant('service/lease_released',
                                    split=split_id, worker=worker_id)
        return {'ok': True}

    def _op_deregister(self, request):
        """A drained worker leaves the fleet.  ``timed_out=True`` means
        the drain deadline passed with splits still in flight: those
        requeue IMMEDIATELY (attempt+1 — the worker walked away with
        them streaming, exactly the lease-expiry semantics, minus the
        TTL wait)."""
        worker_id = request['worker_id']
        timed_out = bool(request.get('timed_out'))
        max_attempts = self._config.max_split_attempts
        with self._lock:
            worker = self._workers.pop(worker_id, None)
            self._worker_digests.pop(worker_id, None)
            if worker is None:
                return {'ok': False}
            self.drains += 1
            if timed_out:
                self.drain_timeouts += 1
            for split in self._splits:
                if split.state == _LEASED and split.worker_id == worker_id:
                    split.worker_id = None
                    split.attempt += 1
                    self.lease_churn += 1
                    if split.attempt >= max_attempts:
                        split.state = _FAILED
                    else:
                        split.state = _PENDING
                        self._pending_for(split).append(split)
                    self._ledger_mark()
        logger.info('worker %s deregistered (%s drain)', worker_id,
                    'timed-out' if timed_out else 'clean')
        self._ledger_save(force=True)
        return {'ok': True}

    def _op_job(self, request):
        tenant = request.get('tenant')
        if tenant is None:
            return {'job': self._job}
        with self._lock:
            job = self._tenants.get(str(tenant))
            if job is None:
                return {'error': 'unknown tenant %r (registered: %s)'
                                 % (tenant,
                                    ', '.join(self._tenants.tenants()))}
            return {'job': dict(job.job_info)}

    def _op_register_job(self, request):
        """Register a second (third, ...) tenant's job on this fleet
        (ISSUE 16).  The new tenant's splits are appended to the GLOBAL
        split-id space at ``split_base = len(splits)`` so every
        split-addressed RPC works unchanged; admission is bounded
        (``max_tenant_jobs``) and a refusal past the cap carries
        ``retry_after_s`` so clients queue-with-backoff."""
        from petastorm_tpu.service.config import ServiceConfig
        tenant = str(request['tenant'])
        weight = float(request.get('weight', 1.0))
        kwargs = dict(request.get('config') or {})
        kwargs['tenant'] = tenant
        kwargs['tenant_weight'] = weight
        try:
            config = ServiceConfig(**kwargs)
            num_pieces = _count_row_groups(config.dataset_url,
                                           config.reader_kwargs)
        except Exception as e:  # noqa: BLE001 — a bad registration must
            # produce an error REPLY, never take the serve loop down.
            return {'error': 'tenant %r registration rejected: %s'
                             % (tenant, e)}
        with self._lock:
            if tenant in self._tenants:
                return {'error': 'tenant %r is already registered '
                                 '(one job per tenant id)' % tenant}
            base = len(self._splits)
            splits = build_splits(num_pieces, config.rowgroups_per_split,
                                  config.num_consumers, split_base=base,
                                  tenant=tenant)
            job_info = dict(config.job_info(len(splits)),
                            split_base=base)
            job = _tenancy.TenantJob(
                tenant, weight, config, job_info, split_base=base,
                num_splits=len(splits), num_pieces=num_pieces,
                registered_t=time.monotonic())
            refusal = self._tenants.admit(job)
            if refusal is not None:
                return refusal
            self._splits.extend(splits)
            job.pending = collections.deque(splits)
            self._ledger_mark()
        logger.info('registered tenant %r: %d splits at base %d '
                    '(weight %.2f)', tenant, len(splits), base, weight)
        self._ledger_save(force=True)
        return {'job': job_info}

    def _op_workers(self, request):
        stale = 3.0 * self._config.lease_ttl_s
        now = time.monotonic()
        with self._lock:
            workers = [
                {'worker_id': wid, 'addr': w['addr'],
                 'alive': (now - w['last_heartbeat']) < stale,
                 # (worker_clock - dispatcher_clock), from the worker's
                 # registration handshake via its heartbeats: clients
                 # chain it with their own dispatcher offset to align
                 # that worker's spans onto their timeline.
                 'clock_offset': w['stats'].get('clock_offset'),
                 'pid': w['stats'].get('pid')}
                for wid, w in sorted(self._workers.items())]
            # Terminally-failed splits ride on the discovery poll so a
            # waiting client can raise instead of hanging forever.
            failed = sorted(s.split_id for s in self._splits
                            if s.state == _FAILED)
            # Ledger-restored dispatchers additionally surface the DONE
            # set (ISSUE 15): a split the previous incarnation retired
            # will never stream again — a token-less client waiting on
            # one (ledger reused across runs, trainer restarted without
            # its resume token) must raise, not hang forever.  Scoped
            # to restored dispatchers: within one run a client either
            # acked the split itself or holds the token that retired it.
            done = (sorted(s.split_id for s in self._splits
                           if s.state == _DONE)
                    if self.ledger_restores else None)
        # t_mono rides the discovery poll the client already makes every
        # second: its send/recv midpoint IS the client<->dispatcher clock
        # handshake — no extra RPC on the refresh path.
        reply = {'workers': workers, 'failed_splits': failed,
                 't_mono': time.monotonic()}
        if done is not None:
            reply['retired_splits'] = done
        return reply

    def _op_stats(self, request):
        stale = 3.0 * self._config.lease_ttl_s
        with self._lock:
            states = collections.Counter(s.state for s in self._splits)
            now = time.monotonic()
            workers = {wid: dict(w['stats'],
                                 age_s=round(now - w['last_heartbeat'], 3))
                       for wid, w in self._workers.items()}
            # Registered is not alive: the dispatcher never forgets a
            # worker, so health must count heartbeats (same staleness
            # rule as _op_workers) or a fully-crashed fleet could never
            # classify lease-starved.
            alive = sum(1 for w in self._workers.values()
                        if (now - w['last_heartbeat']) < stale)
        # Fleet-wide epoch-cache plane counters (jobs with cache_plane):
        # summed from the per-worker heartbeat stats, so one `status`
        # call says whether this epoch is being decoded or served warm.
        cache = {key: sum(int(w.get(key, 0)) for w in workers.values())
                 for key in ('cache_hits', 'cache_misses',
                             'cache_evictions', 'cache_ram_hits',
                             'cache_degraded', 'cache_quota_degraded')}
        # shm result-plane rollup (ISSUE 5 satellite): the per-worker
        # counters rode the heartbeats all along but never summed — a
        # worker silently degraded to the byte path (arena full, /dev/shm
        # unusable) was invisible without reading every worker's row.
        shm = {key: sum(int(w.get(key, 0)) for w in workers.values())
               for key in ('shm_chunks', 'shm_degraded',
                           'shm_quota_degraded')}
        # Cluster cache tier rollup (ISSUE 10): worker counters summed
        # fleet-wide plus the dispatcher's own routing counters and the
        # directory's footprint — one `status`/`top` call says whether
        # the fleet is sharing decoded entries or re-paying decode.
        cluster = {key: sum(int(w.get(key, 0)) for w in workers.values())
                   for key in ('cache_remote_hits', 'cache_peer_fills',
                               'cache_peer_degraded')}
        with self._lock:
            cluster.update({
                'cache_affinity_routed': self.affinity_routed,
                'affinity_deferrals': self.affinity_deferrals,
                'directory_workers': len(self._worker_digests),
                'directory_digests': len(set().union(
                    *self._worker_digests.values()))
                if self._worker_digests else 0,
                'piece_map': self._piece_digests is not None,
            })
            draining = sum(1 for w in self._workers.values()
                           if w.get('draining'))
        # Crash-survivable control plane rollup (ISSUE 15): the ledger
        # lineage (how many restarts this job's control plane has
        # survived), drain traffic, and the fleet-summed retry counters
        # (the thundering-herd signal the unified backoff bounds).
        control = {
            'ledger': self._ledger is not None,
            'ledger_restores': self.ledger_restores,
            'ledger_adoptions': self.ledger_adoptions,
            'ledger_requeues': self.ledger_requeues,
            'ledger_saves': (self._ledger.saves
                             if self._ledger is not None else 0),
            'drains': self.drains,
            'drain_timeouts': self.drain_timeouts,
            'workers_draining': draining,
        }
        control.update({
            key: sum(int(w.get(key, 0)) for w in workers.values())
            for key in ('retry_attempts', 'retry_giveups')})
        # True fleet-wide stage latencies: the heartbeat registry
        # snapshots merge by histogram-bucket addition (the reason the
        # buckets are fixed log2), then each stage reports the ONE
        # canonical summary (`summarize_hist`) that `top` and
        # `petastorm-tpu-diagnose` also print — same snapshot, same
        # numbers, everywhere.
        from petastorm_tpu.telemetry import (health, merge_snapshots,
                                             snapshot_delta, summarize_hist)
        merged = merge_snapshots([w.get('registry') for w in
                                  workers.values()])
        stages = {name: summarize_hist(hist)
                  for name, hist in merged['histograms'].items()}
        # Derived fleet health (ISSUE 7): the CURRENT fleet snapshot
        # delta'd against the flight-ring frame nearest the window edge
        # (~60 s back, `flight.window_frames` — the one windowing rule;
        # the serve loop ticks the ring).  Deltaing live state — not
        # frame-vs-frame — keeps the report current even on a dispatcher
        # younger than one tick interval, so with a single frame that
        # frame IS the baseline.
        from petastorm_tpu.telemetry.flight import window_frames
        self.flight.maybe_tick()
        frames = self.flight.frames()
        baseline = window_frames(frames, 60.0)[0] or (
            frames[-1] if frames else None)
        delta = snapshot_delta(self._fleet_snapshot(),
                               baseline['snapshot'] if baseline else None)
        # Multi-tenant rollup (ISSUE 16): per-tenant queue/grant state
        # plus the fair-share scheduler's deficits — the `top` tenant
        # table and the explain cost attribution read this, and the
        # tenant-starved regime's evidence derives from it.
        grant_deltas = {
            name.split(':', 1)[1]: value
            for name, value in (delta.get('counters') or {}).items()
            if name.startswith('tenant_grants:')}
        with self._lock:
            deficits = self._scheduler.deficits()
            tenants = {}
            for job in self._tenants.jobs():
                span = self._splits[job.split_base:
                                    job.split_base + job.num_splits]
                tstates = collections.Counter(s.state for s in span)
                tenants[job.tenant] = {
                    'weight': job.weight,
                    'split_base': job.split_base,
                    'num_splits': job.num_splits,
                    'pending': tstates[_PENDING],
                    'leased': tstates[_LEASED],
                    'done': tstates[_DONE],
                    'failed': tstates[_FAILED],
                    'grants': job.grants,
                    'grants_delta': int(grant_deltas.get(job.tenant, 0)),
                    'deficit': round(deficits.get(job.tenant, 0.0), 3),
                }
        # A tenant is starved when it has pending work but took zero
        # grants over the window WHILE another tenant's grants climbed:
        # the fleet is moving, this tenant is not — the fair-share
        # regression signal (a wholly idle fleet is lease-starved, a
        # different regime).
        fleet_moving = any(row['grants_delta'] > 0
                           for row in tenants.values())
        starved_tenants = sorted(
            tid for tid, row in tenants.items()
            if row['pending'] > 0 and row['grants_delta'] == 0
            and fleet_moving)
        if self.autoscaler is not None:
            autoscale = self.autoscaler.snapshot()
        else:
            from petastorm_tpu.service import autoscaler as _autoscaler
            # Same shape as Autoscaler.snapshot() so `top`, the golden
            # stats schema, and trend diffs never branch on presence.
            autoscale = {'enabled': False,
                         'killed': _autoscaler.killed(),
                         'scale_outs': 0, 'scale_ins': 0, 'actions': 0,
                         'suppressed': 0, 'last_action': None}
        # Decision-journal rollup (ISSUE 20): the dispatcher's own
        # per-actor summary merged with every worker's heartbeat-shipped
        # one — `top`'s decisions line and the control-flapping evidence
        # read this.  Worker 'last' ages shift by the heartbeat age (the
        # record aged on the worker's clock since it was shipped).
        decisions_rollup = self._decisions.summary()
        for row in workers.values():
            wdec = row.get('decisions') or {}
            for actor, wrow in (wdec.get('summary') or {}).items():
                agg = decisions_rollup.setdefault(
                    actor, {'actions': 0, 'suppressed': 0, 'last': None})
                agg['actions'] += int(wrow.get('actions', 0))
                agg['suppressed'] += int(wrow.get('suppressed', 0))
                last = wrow.get('last')
                if last is not None:
                    last = dict(last, age_s=round(
                        last.get('age_s', 0.0) + row.get('age_s', 0.0), 1))
                    if agg['last'] is None \
                            or last['age_s'] < agg['last'].get('age_s', 0.0):
                        agg['last'] = last
        meta = {'pending': states[_PENDING], 'leased': states[_LEASED],
                'failed': states[_FAILED], 'workers_alive': alive,
                # control-plane-degraded evidence (ISSUE 15)
                'ledger_restores': self.ledger_restores,
                'drain_timeouts': self.drain_timeouts,
                'retry_giveups': control['retry_giveups'],
                # fair-share regression evidence (ISSUE 16)
                'starved_tenants': starved_tenants,
                'tenant_count': len(tenants),
                # control-flapping evidence (ISSUE 20): opposing real
                # actions (scale_out vs scale_in, admit vs evict) inside
                # the health window, straight from the decision journal.
                'control_flaps': self._decisions.opposing_actions(60.0)}
        fleet_health = health.health_report(
            delta, meta=meta,
            window_s=(time.monotonic() - baseline['t_mono'])
            if baseline else None)
        health.export_gauges(self.metrics, fleet_health)
        # The raw per-worker snapshots (44-int bucket arrays per
        # histogram) served their purpose in `stages`; shipping them per
        # worker per poll would grow the reply linearly with fleet size
        # for data neither `top` nor the status CLI reads.
        workers = {wid: {k: v for k, v in row.items() if k != 'registry'}
                   for wid, row in workers.items()}
        return {
            'num_splits': len(self._splits),
            'pending': states[_PENDING],
            'leased': states[_LEASED],
            'done': states[_DONE],
            'failed': states[_FAILED],
            'lease_churn': self.lease_churn,
            'cache': cache,
            'shm': shm,
            'cluster_cache': cluster,
            'control_plane': control,
            'tenants': tenants,
            'autoscale': autoscale,
            'decisions': decisions_rollup,
            'stages': stages,
            'health': fleet_health,
            'workers': workers,
        }

    def _op_decisions(self, request):
        """Decision-journal query surface (ISSUE 20) — what
        ``petastorm-tpu-why --dispatcher`` reads: the dispatcher's own
        journal with FULL records plus each worker's heartbeat-shipped
        journal payload (summary + recent records)."""
        with self._lock:
            worker_payloads = {
                wid: w['stats'].get('decisions')
                for wid, w in self._workers.items()
                if w['stats'].get('decisions')}
        return {'journal': self._decisions.dump(),
                'workers': worker_payloads}

    def _op_stop(self, request):
        self._stop.set()
        return {'ok': True}


def _count_row_groups(dataset_url, reader_kwargs):
    """Row-group count of the dataset — the only dataset fact the control
    plane needs (workers re-enumerate the same footer metadata, so indices
    agree by construction)."""
    from petastorm_tpu.etl.dataset_metadata import load_row_groups
    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths

    fs, path_or_paths = get_filesystem_and_path_or_paths(
        dataset_url,
        storage_options=reader_kwargs.get('storage_options'),
        filesystem=reader_kwargs.get('filesystem'))
    paths = (path_or_paths if isinstance(path_or_paths, list)
             else [path_or_paths])
    return sum(len(load_row_groups(fs, p)) for p in paths)
