"""Durable dispatcher ledger: the control plane survives its own death.

ROADMAP item 1c named the gap: the dispatcher keeps the lease ledger and
the cluster cache directory only in memory, so a restart "doesn't
re-decode the world" was aspiration, not fact — every split went back to
pending at attempt 0 and every worker's advertised digests were
forgotten.  This module is the crash-safe persistence for exactly that
state (ISSUE 15):

* **what persists** — per-split state + attempt counters (the lease
  ledger), the consumed/done set a resuming client already retired, the
  worker-advertised digest directory (keyed by *data address*, the one
  worker identity that survives a dispatcher restart — worker ids are
  dispatcher-assigned and restart-scoped), the once-per-job piece-digest
  map, and the partition-geometry fingerprint that gates every restore.
* **how it persists** — a snapshot + write-ahead journal pair.  The
  snapshot is ``provenance.atomic_json_dump`` (tmp + ``os.replace``: a
  SIGKILL mid-write leaves the previous one, never a torn one), written
  from the serve loop whenever state is dirty.  The transitions that
  retire work (``complete`` / ``mark_consumed``) append one O(1) line
  to ``<path>.journal`` BEFORE the reply — a split is never reported
  done to a worker before a durable record exists — so write-ahead cost
  stays constant per transition instead of re-serializing the whole
  state (O(splits)) on every complete.  ``load()`` replays the journal
  over the snapshot; each successful snapshot truncates it.  A line
  torn by SIGKILL mid-append is skipped on replay (the snapshot it
  amends is still consistent).  Lease grants/expiries only dirty the
  snapshot — losing one costs a grace-window reconciliation, never
  correctness.
* **single writer** — the ``.owner`` sidecar idiom from
  ``telemetry/flight.py``, hardened to exclusive: the dispatcher holds a
  lifetime ``LOCK_EX`` flock on ``<path>.owner``; a second dispatcher
  pointed at the same ledger fails at construction instead of
  split-braining the lease state.  The kernel releases the lock on ANY
  death, SIGKILL included.

Restore + reconciliation live in ``dispatcher.py`` (the state is its);
the contract: ``done``/``failed`` splits stay retired (no re-decode of
work the fleet already delivered), a ``leased`` split is restored as an
**orphan lease** — held by nobody, expiring one TTL out — that a
re-registering worker's ``held`` heartbeat claim *adopts* (the lease
resumes under the new worker id, attempt intact) and that, unclaimed,
requeues with its attempt count intact (the restart was not the
worker's failure, so it must not burn an attempt toward the
``max_split_attempts`` poison ceiling).
"""

import fcntl
import json
import logging
import os

from petastorm_tpu.errors import ServiceError
from petastorm_tpu.telemetry.provenance import atomic_json_dump

logger = logging.getLogger(__name__)

__all__ = ['DispatcherLedger', 'LedgerHeldError', 'LEDGER_KIND',
           'LEDGER_VERSION', 'encode_splits', 'decode_splits']

LEDGER_KIND = 'dispatcher_ledger'
#: v1 = single-tenant (PR 15); v2 adds the ``tenants`` table (ISSUE 16).
#: ``load()`` accepts both — a v1 file restores as one default-tenant
#: job — and cold-starts (with a distinct warning) on anything newer:
#: a downgraded dispatcher must not half-apply state it cannot parse.
LEDGER_VERSION = 2
_COMPAT_VERSIONS = (1, 2)

#: Compact per-split state codes (the splits list dominates the file).
_STATE_CODES = {'pending': 'p', 'leased': 'l', 'done': 'd', 'failed': 'f'}
_CODE_STATES = {code: state for state, code in _STATE_CODES.items()}


class LedgerHeldError(ServiceError):
    """Another live dispatcher holds this ledger's owner lock."""


def encode_splits(splits):
    """``[[state_code, attempt], ...]`` indexed by split id — the
    compact on-disk shape (ids are implicit: the split list is dense
    by construction)."""
    return [[_STATE_CODES[s.state], int(s.attempt)] for s in splits]


def decode_splits(records):
    """Inverse of :func:`encode_splits`: ``[(state, attempt), ...]``.
    Raises ``ValueError`` on any unknown code (a corrupt ledger must be
    rejected whole, not half-applied)."""
    return [(_CODE_STATES[code], int(attempt)) for code, attempt in records]


class DispatcherLedger(object):
    """One dispatcher's durable snapshot file + its owner lock.

    Lifecycle: ``acquire()`` at dispatcher construction (raises
    :class:`LedgerHeldError` against a live owner), ``load()`` for the
    restore-or-None decision, ``save(state)`` per snapshot,
    ``release()`` on clean shutdown (the file STAYS — it is the next
    incarnation's restore source; only the lock and sidecar go).
    """

    def __init__(self, path, kind=LEDGER_KIND):
        self.path = str(path)
        #: File-kind tag checked on load and stamped on save.  The
        #: materialize controller (ISSUE 18) persists its piece-granular
        #: job state through this exact snapshot+journal machinery under
        #: ``kind='materialize_ledger'`` — distinct kinds keep a
        #: dispatcher from adopting a materializer's file (and vice
        #: versa) when both are misconfigured onto one path.
        self.kind = str(kind)
        self._owner_fd = None
        self._journal_f = None
        #: Snapshots written (telemetry; the dispatcher surfaces it).
        self.saves = 0

    # -- owner lock ----------------------------------------------------------

    def acquire(self):
        """Take the exclusive lifetime flock on ``<path>.owner``."""
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)
        fd = os.open(self.path + '.owner', os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            raise LedgerHeldError(
                'ledger %r is owned by a live dispatcher (exclusive '
                'flock on %s.owner held elsewhere) — two control planes '
                'on one ledger would split-brain the lease state'
                % (self.path, self.path))
        self._owner_fd = fd
        return self

    def release(self):
        """Drop the owner lock + sidecar and close the journal.  The
        snapshot and journal files are deliberately kept: they are the
        restore source for the next dispatcher over the same job."""
        journal, self._journal_f = self._journal_f, None
        if journal is not None:
            try:
                journal.close()
            except OSError:
                pass
        fd, self._owner_fd = self._owner_fd, None
        if fd is None:
            return
        try:
            os.close(fd)
        except OSError:
            pass
        try:
            os.unlink(self.path + '.owner')
        except OSError:
            pass

    # -- snapshot + journal I/O ----------------------------------------------

    def load(self):
        """The last snapshot dict with the write-ahead journal replayed
        over its ``splits``, or None (missing / unreadable / wrong kind
        / wrong version — every reject path logs why and falls back to
        a cold start rather than raising: a corrupt ledger must cost a
        re-decode, never the job)."""
        try:
            with open(self.path) as f:
                state = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            logger.warning('ledger %s unreadable (%s); cold start',
                           self.path, e)
            return None
        if not isinstance(state, dict) or state.get('kind') != self.kind:
            logger.warning('ledger %s is not a %s file; cold start',
                           self.path, self.kind)
            return None
        try:
            version = int(state.get('version', -1))
        except (TypeError, ValueError):
            version = -1
        if version > LEDGER_VERSION:
            logger.warning(
                'ledger %s is version %d, newer than this dispatcher '
                'understands (v%d) — written by a newer release; cold '
                'start (the file is left untouched)',
                self.path, version, LEDGER_VERSION)
            return None
        if version not in _COMPAT_VERSIONS:
            logger.warning('ledger %s is not a v%s %s file; cold start',
                           self.path,
                           '/'.join(map(str, _COMPAT_VERSIONS)), self.kind)
            return None
        splits = state.get('splits')
        for entry in self._replay_journal():
            split_id = entry.get('split')
            if entry.get('op') == 'done' and isinstance(splits, list) \
                    and isinstance(split_id, int) \
                    and 0 <= split_id < len(splits) \
                    and isinstance(splits[split_id], (list, tuple)) \
                    and len(splits[split_id]) == 2:
                # Malformed split records are tolerated here (left
                # as-is) so load() keeps its never-raises contract; the
                # dispatcher's decode_splits gate then rejects the
                # snapshot WHOLE and cold-starts.
                splits[split_id] = [_STATE_CODES['done'],
                                    splits[split_id][1]]
        return state

    def _replay_journal(self):
        """Parsed journal entries, oldest first; a line torn by SIGKILL
        mid-append (always the last one) is skipped."""
        try:
            with open(self.path + '.journal') as f:
                lines = f.read().splitlines()
        except OSError:
            return []
        entries = []
        for line in lines:
            try:
                entry = json.loads(line)
            except ValueError:
                continue  # torn tail line: the snapshot is still whole
            if isinstance(entry, dict):
                entries.append(entry)
        return entries

    def append(self, entry):
        """One O(1) write-ahead journal line, flushed before returning
        — the constant-cost durable record for work-retiring
        transitions (re-snapshotting the whole state per complete would
        be O(splits) inside the serve loop).  Best-effort like every
        artifact write; returns whether the line landed."""
        try:
            if self._journal_f is None:
                directory = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(directory, exist_ok=True)
                self._journal_f = open(self.path + '.journal', 'a')
            self._journal_f.write(json.dumps(entry) + '\n')
            self._journal_f.flush()
            return True
        except (OSError, ValueError):
            return False

    def save(self, state):
        """Atomic snapshot write (tmp + replace; best-effort by the
        ``atomic_json_dump`` contract); a successful snapshot absorbs
        and truncates the journal.  Returns the path or None."""
        state = dict(state, kind=self.kind, version=LEDGER_VERSION)
        path = atomic_json_dump(self.path, state)
        if path is not None:
            self.saves += 1
            try:
                if self._journal_f is not None:
                    self._journal_f.truncate(0)
                    self._journal_f.seek(0)
                else:
                    os.truncate(self.path + '.journal', 0)
            except OSError:
                pass  # stale journal lines just re-mark done splits done
        return path
