"""JAX/TPU delivery plane — the first-class loader of this framework.

North star (BASELINE.json): ``petastorm.jax.DataLoader`` — double-buffered
``device_put`` batches straight into pjit/pmap training loops, per-host
row-group sharding by ``jax.process_index()``.
"""

from petastorm_tpu.jax import augment, packing, residency  # noqa: F401
from petastorm_tpu.jax.loader import (DataLoader,  # noqa: F401
                                      DeviceInMemDataLoader,
                                      DiskCachedDataLoader, InMemDataLoader,
                                      PackedDataLoader, ResidentDataLoader,
                                      make_jax_loader)
