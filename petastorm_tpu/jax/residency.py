"""Device-resident data plane: compressed-in-HBM tier + epoch-keyed shuffle + LRU.

Three composing pieces (ISSUE 17):

* **Compressed-in-HBM tier** — batches live on device in the transfer
  plane's narrowed *wire* dtypes (uint8 stays uint8, float32 rides as
  bfloat16 under the ``'auto'`` policy) and are widened inside the jitted
  step.  HBM holds roughly 2-4x more samples than a full-width
  ``DeviceInMemDataLoader`` cache, so "dataset too big for device" often
  becomes "fits".
* **On-device epoch shuffle** — :func:`epoch_permutation` derives each
  epoch's order from ``(seed, epoch)`` alone via ``jax.random.fold_in``,
  so a resident epoch is bit-identical to the equivalent streamed epoch
  and an order can be recomputed from a resume token without replaying
  history.  This is the forward-compatibility hook for the ROADMAP's
  cluster-wide global permutation: any worker can derive any epoch's
  order from the shared seed.
* **Multi-epoch residency LRU** — :class:`ResidencyTier` is a
  budget-bounded slab of wire-dtype rows.  Batches are admitted as they
  are delivered on streamed epochs; admission writes through a jitted
  ``dynamic_update_slice`` whose slab argument is *donated* off-CPU, so
  evicted rows are recycled in place rather than freed-and-reallocated.
  Once every dataset row is resident, warm epochs are served by a single
  jitted gather+widen and fetch **zero** host batches.

Degrade matrix (mirrors the transfer plane's conventions):

* ``PETASTORM_TPU_NO_RESIDENCY=1`` — kill switch; the loader streams
  full-width every epoch, reproducing the pre-residency schedule and
  delivery exactly.
* unsupported dtype anywhere in the batch — :func:`wire_plan` returns
  ``None`` and the loader degrades to full-width streaming (passthrough:
  no narrowing, no residency).
* budget too small for the dataset — streamed epochs still admit (the
  LRU churns, visible as ``residency_thrash``), but warm serving never
  activates; every epoch streams.

The module also hosts the degenerate single-entry case shared with
``DeviceInMemDataLoader`` (:func:`place_once` / :func:`device_cache_valid`),
so the full-width device cache and the resident tier validate buffers the
same way.
"""

import os
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from petastorm_tpu.jax.transfer import _supported, wire_dtype_for
from petastorm_tpu.telemetry import decisions as _decisions

#: Kill switch: set to any non-empty value to disable the resident tier.
#: The loader then streams full-width batches every epoch — byte-for-byte
#: the pre-residency schedule and delivery (PR 16 convention).
KILL_SWITCH = 'PETASTORM_TPU_NO_RESIDENCY'

#: Counter names created eagerly so stats rollups carry the full shape
#: even when the plane is off (kill switch, unsupported dtypes).
COUNTER_NAMES = (
    'residency_admitted',
    'residency_evictions',
    'residency_hits',
    'residency_bypass',
    'residency_thrash',
    'residency_host_batches',
)

GAUGE_NAMES = (
    'residency_rows',
    'residency_bytes',
    'residency_budget_bytes',
)


def killed():
    """True when the ``PETASTORM_TPU_NO_RESIDENCY`` kill switch is set."""
    return bool(os.environ.get(KILL_SWITCH))


def donation_supported():
    """Whether buffer donation actually recycles memory on this backend.

    ``jax.jit(..., donate_argnums=...)`` is a no-op (a copy) on CPU; the
    tier still runs there — tests and the CPU-emulated bench leg exercise
    the exact same code path — but the in-place recycling story only
    holds on accelerators.
    """
    try:
        return jax.default_backend() != 'cpu'
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Epoch-keyed shuffle
# ---------------------------------------------------------------------------

def epoch_key(seed, epoch):
    """PRNG key for one epoch: ``fold_in(PRNGKey(seed), epoch)``.

    A pure function of ``(seed, epoch)`` — no split chain, no history —
    so resident and streamed epochs derive identical orders and a resume
    token only needs the pair, not the traversal that led to it.
    """
    return jax.random.fold_in(jax.random.PRNGKey(int(seed)), int(epoch))


def epoch_permutation(seed, epoch, n):
    """On-device permutation of ``n`` rows keyed by ``(seed, epoch)``."""
    return jax.random.permutation(epoch_key(seed, epoch), int(n))


# ---------------------------------------------------------------------------
# Wire plan: narrow on host, widen in the jitted step
# ---------------------------------------------------------------------------

class _WireField(object):
    __slots__ = ('wire', 'out', 'row_shape')

    def __init__(self, wire, out, row_shape):
        self.wire = wire
        self.out = out
        self.row_shape = row_shape


class WirePlan(object):
    """Per-field wire/output dtypes for a flat dict of ``(N, ...)`` arrays.

    ``narrow`` runs on host (numpy ``astype`` to the wire dtype, identity
    for already-narrow fields); ``widen`` runs on device and is the jitted
    inverse ``astype`` back to the canonical output dtype.  For uint8 and
    other exact wires the round trip is bit-exact; for float32→bf16 it is
    lossy on the narrow side only — widening stored bf16 back to float32
    is exact, which is what makes resident and streamed epochs
    bit-identical (both deliver ``widen(narrow(rows))``).
    """

    def __init__(self, fields, wire_row_nbytes, logical_row_nbytes):
        self.fields = fields
        self.wire_row_nbytes = wire_row_nbytes
        self.logical_row_nbytes = logical_row_nbytes
        self.narrowed = any(f.wire != f.out for f in fields.values())
        self._widen_fn = None

    def narrow(self, host_rows):
        """Cast a host batch to wire dtypes (no copy when already narrow)."""
        return {name: np.asarray(host_rows[name]).astype(f.wire, copy=False)
                for name, f in self.fields.items()}

    def widen(self, wire_dev):
        """Widen a device batch of wire arrays back to canonical dtypes.

        Not donating: for exact fields widen is the identity, so the
        delivered batch aliases the wire arrays (which the resident tier
        may also hold) — donation would invalidate live aliases.
        """
        if not self.narrowed:
            return wire_dev
        if self._widen_fn is None:
            outs = {name: jnp.dtype(f.out) for name, f in self.fields.items()}

            def _widen(tree):
                return {name: tree[name].astype(outs[name]) for name in tree}

            self._widen_fn = jax.jit(_widen)
        return self._widen_fn(wire_dev)


def wire_plan(tree, policy):
    """Build a :class:`WirePlan` for a flat dict of host arrays.

    Returns ``None`` when the batch cannot ride the tier — empty tree, a
    dtype outside the transfer plane's support matrix, or the kill switch
    via the caller — in which case the loader degrades to full-width
    streaming rather than failing.
    """
    if not tree:
        return None
    fields = {}
    wire_row = 0
    logical_row = 0
    for name in sorted(tree):
        arr = np.asarray(tree[name])
        if arr.ndim < 1 or not _supported(arr.dtype):
            return None
        out = jnp.dtype(jax.dtypes.canonicalize_dtype(arr.dtype))
        wire = wire_dtype_for(name, out, policy)
        if not _supported(wire):
            return None
        row_shape = arr.shape[1:]
        row_elems = int(np.prod(row_shape, dtype=np.int64)) if row_shape else 1
        fields[name] = _WireField(np.dtype(wire), np.dtype(out), row_shape)
        wire_row += row_elems * np.dtype(wire).itemsize
        logical_row += row_elems * np.dtype(out).itemsize
    return WirePlan(fields, wire_row, logical_row)


def estimate_budget(tree, policy='auto'):
    """Budget math for the doctor: bytes/row on the wire vs full width.

    ``hbm_ratio`` is how many more rows the narrowed tier holds per byte
    of HBM compared to a full-width device cache (>= 1.0; 1.0 when
    nothing narrows).
    """
    plan = wire_plan(tree, policy)
    if plan is None:
        return None
    return {
        'wire_bytes_per_row': plan.wire_row_nbytes,
        'logical_bytes_per_row': plan.logical_row_nbytes,
        'hbm_ratio': (float(plan.logical_row_nbytes) / plan.wire_row_nbytes
                      if plan.wire_row_nbytes else 1.0),
        'narrowed': plan.narrowed,
    }


# ---------------------------------------------------------------------------
# Shared device-cache validity helpers (degenerate single-entry case)
# ---------------------------------------------------------------------------

def device_cache_valid(tree):
    """True when every leaf of a placed device pytree holds live buffers.

    Donated or explicitly ``delete()``-ed jax arrays report
    ``is_deleted() == True``; serving from them raises deep inside a
    gather with an opaque runtime error, so callers check here first.
    """
    if tree is None:
        return False
    for leaf in jax.tree_util.tree_leaves(tree):
        is_deleted = getattr(leaf, 'is_deleted', None)
        if callable(is_deleted):
            try:
                if is_deleted():
                    return False
            except Exception:
                return False
    return True


def place_once(numeric, plane=None, device=None):
    """Place a host pytree on device once (plane fast path, device_put else).

    The single-entry degenerate case of the residency LRU:
    ``DeviceInMemDataLoader`` holds exactly one "entry" (the whole
    dataset) that is admitted once and never evicted, so it shares this
    placement + :func:`device_cache_valid` revalidation path with the
    tier instead of re-issuing ``device_put`` per epoch.
    """
    if plane is not None:
        placed = plane.put_once(numeric)
        if placed is not None:
            return placed
    if device is not None:
        return {k: jax.device_put(v, device) for k, v in numeric.items()}
    return {k: jax.device_put(v) for k, v in numeric.items()}


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

class ResidencyCounters(object):
    """Eagerly-registered residency counters/gauges on a MetricsRegistry."""

    def __init__(self, metrics):
        self.admitted = metrics.counter('residency_admitted')
        self.evictions = metrics.counter('residency_evictions')
        self.hits = metrics.counter('residency_hits')
        self.bypass = metrics.counter('residency_bypass')
        self.thrash = metrics.counter('residency_thrash')
        self.host_batches = metrics.counter('residency_host_batches')
        self.rows = metrics.gauge('residency_rows')
        self.bytes = metrics.gauge('residency_bytes')
        self.budget = metrics.gauge('residency_budget_bytes')


def ensure_counters(metrics):
    """Create the full residency counter shape (all zeros when plane off)."""
    return ResidencyCounters(metrics)


# ---------------------------------------------------------------------------
# The residency LRU tier
# ---------------------------------------------------------------------------

class ResidencyTier(object):
    """Budget-bounded device-resident slab of wire-dtype rows with batch LRU.

    Rows live in per-field slabs of shape ``(capacity,) + row_shape`` in
    the wire dtype.  Each admitted batch occupies a contiguous slot range
    tracked as one LRU entry; ``slot_of_row`` maps dataset row id →
    slab slot (-1 when not resident).  Admission writes through a jitted
    ``dynamic_update_slice_in_dim`` with the slab donated off-CPU, so an
    "eviction" is just the LRU entry releasing its slot range — the bytes
    are overwritten in place by the next donated admission.

    Warm serving is one jitted gather: slice ``batch_size`` row ids out
    of the epoch permutation, map them through the device copy of
    ``slot_of_row``, ``take`` from each slab, and widen — no host work at
    all.
    """

    def __init__(self, plan, n_rows, batch_size, budget_bytes, counters,
                 device=None):
        self._plan = plan
        self._n = int(n_rows)
        self._bs = int(batch_size)
        self._device = device
        row_bytes = max(1, plan.wire_row_nbytes)
        if budget_bytes is None:
            self._capacity = self._n
        else:
            self._capacity = min(self._n, max(0, int(budget_bytes) // row_bytes))
        self._c = counters
        counters.budget.set(int(budget_bytes) if budget_bytes is not None
                            else self._capacity * row_bytes)
        self._slabs = None
        self._entries = OrderedDict()   # seq -> (slot, rows)
        self._seq = 0
        self._free = []                 # list of (slot, rows) released ranges
        self._bump = 0
        self._slot_of_row = np.full(self._n, -1, dtype=np.int32)
        self._slot_map_dev = None
        self._write_fns = {}
        self._gather_fn = None
        self._dropped = False
        self._donate = donation_supported()

    @property
    def capacity_rows(self):
        return self._capacity

    @property
    def can_hold_dataset(self):
        return self._capacity >= self._n

    @property
    def resident_rows(self):
        return int((self._slot_of_row >= 0).sum())

    @property
    def fully_resident(self):
        return (not self._dropped and self._slabs is not None
                and self.resident_rows == self._n)

    @property
    def dropped(self):
        return self._dropped

    def serving_ok(self):
        """Gatherable right now: fully resident with live slab buffers."""
        return self.fully_resident and device_cache_valid(self._slabs)

    # -- slot management ----------------------------------------------------

    def _ensure_slabs(self):
        if self._slabs is not None:
            return
        def _zeros():
            return {name: jnp.zeros((self._capacity,) + f.row_shape,
                                    dtype=jnp.dtype(f.wire))
                    for name, f in self._plan.fields.items()}
        if self._device is not None:
            with jax.default_device(self._device):
                self._slabs = _zeros()
        else:
            self._slabs = _zeros()

    def _alloc(self, rows):
        for i, (slot, free_rows) in enumerate(self._free):
            if free_rows == rows:
                del self._free[i]
                return slot
        if self._bump + rows <= self._capacity:
            slot = self._bump
            self._bump += rows
            return slot
        return None

    def _evict_lru(self):
        _, (slot, rows) = self._entries.popitem(last=False)
        # Clear only mappings still pointing into the evicted range — a row
        # re-admitted elsewhere keeps its newer slot.
        mask = (self._slot_of_row >= slot) & (self._slot_of_row < slot + rows)
        self._slot_of_row[mask] = -1
        self._free.append((slot, rows))
        self._slot_map_dev = None
        self._c.evictions.inc()

    def _update_gauges(self):
        rows = self.resident_rows
        self._c.rows.set(rows)
        self._c.bytes.set(rows * self._plan.wire_row_nbytes)

    # -- admission ----------------------------------------------------------

    def admit(self, row_ids, wire_dev):
        """Admit one batch of wire-dtype device arrays for the given rows.

        Returns the provenance outcome: ``'admitted'`` (fit without
        displacing anything, or rows already resident), ``'evicted'``
        (admitted, displacing the LRU entry — also counts a thrash), or
        ``'bypass'`` (tier dropped or batch larger than the whole budget).
        """
        row_ids = np.asarray(row_ids)
        rows = len(row_ids)
        if self._dropped or rows == 0 or rows > self._capacity:
            self._c.bypass.inc()
            _decisions.record_decision(
                'residency', 'bypass', 'residency_budget',
                {'rows': rows, 'capacity': self._capacity,
                 'dropped': bool(self._dropped)},
                suppressed=True)
            return 'bypass'
        if (self._slot_of_row[row_ids] >= 0).all():
            # Warm re-sight of already-resident rows: nothing is allocated or
            # displaced, so no decision record (this path runs every batch on
            # warm epochs and would flood the journal with non-decisions).
            return 'admitted'
        # Snapshot the allocator state the admission rule reads *before* the
        # evict loop mutates it, so the decision replay can re-derive the
        # outcome (admitted / evicted / bypass) from inputs alone.
        _inputs = {
            'rows': rows,
            'capacity': self._capacity,
            'bump': self._bump,
            'free_rows': [r for _, r in self._free],
            'entry_rows': [r for _, r in self._entries.values()],
        }
        self._ensure_slabs()
        evicted = False
        slot = self._alloc(rows)
        while slot is None and self._entries:
            self._evict_lru()
            evicted = True
            slot = self._alloc(rows)
        if slot is None:
            self._c.bypass.inc()
            _decisions.record_decision(
                'residency', 'bypass', 'residency_budget', _inputs,
                suppressed=True)
            return 'bypass'
        self._write(slot, rows, wire_dev)
        self._entries[self._seq] = (slot, rows)
        self._seq += 1
        self._slot_of_row[row_ids] = np.arange(slot, slot + rows,
                                               dtype=np.int32)
        self._slot_map_dev = None
        self._c.admitted.inc()
        if evicted:
            self._c.thrash.inc()
        self._update_gauges()
        outcome = 'evicted' if evicted else 'admitted'
        _decisions.record_decision(
            'residency', outcome, 'residency_budget', _inputs, slot=slot)
        return outcome

    def _write(self, slot, rows, wire_dev):
        fn = self._write_fns.get(rows)
        if fn is None:
            def _update(slabs, batch, start):
                return {name: jax.lax.dynamic_update_slice_in_dim(
                            slabs[name], batch[name], start, axis=0)
                        for name in slabs}
            donate = (0,) if self._donate else ()
            fn = jax.jit(_update, donate_argnums=donate)
            self._write_fns[rows] = fn
        self._slabs = fn(self._slabs, wire_dev, slot)

    def backfill(self, cache, plan):
        """Directly admit every row that no streamed delivery covered.

        With ``drop_last`` the epoch never ships the ragged tail, and a
        mid-epoch resume never re-ships skipped batches — but warm
        serving needs *every* row resident (any row can land anywhere in
        the next epoch's permutation).  Only runs when the budget can
        hold the whole dataset; otherwise admission churn would evict
        rows as fast as it fills them.
        """
        if self._dropped or not self.can_hold_dataset:
            return
        missing = np.flatnonzero(self._slot_of_row < 0)
        for i in range(0, len(missing), self._bs):
            idx = missing[i:i + self._bs]
            host_rows = {name: np.asarray(cache[name])[idx]
                         for name in plan.fields}
            wire = plan.narrow(host_rows)
            if self._device is not None:
                wire_dev = {k: jax.device_put(v, self._device)
                            for k, v in wire.items()}
            else:
                wire_dev = {k: jax.device_put(v) for k, v in wire.items()}
            self.admit(idx, wire_dev)

    # -- warm serving -------------------------------------------------------

    def _slot_map(self):
        if self._slot_map_dev is None:
            self._slot_map_dev = jnp.asarray(self._slot_of_row)
        return self._slot_map_dev

    def gather(self, order_dev, start):
        """One warm full batch: jitted slice→map→take→widen, zero host work."""
        if self._gather_fn is None:
            bs = self._bs
            outs = {name: jnp.dtype(f.out)
                    for name, f in self._plan.fields.items()}

            def _gather(slabs, slot_map, order, start):
                idx = jax.lax.dynamic_slice_in_dim(order, start, bs)
                slots = jnp.take(slot_map, idx)
                return {name: jnp.take(slabs[name], slots,
                                       axis=0).astype(outs[name])
                        for name in slabs}

            self._gather_fn = jax.jit(_gather)
        self._c.hits.inc()
        return self._gather_fn(self._slabs, self._slot_map(), order_dev, start)

    def gather_tail(self, order_dev, start):
        """Ragged final batch (``drop_last=False``): unjitted, once per epoch."""
        idx = order_dev[start:]
        slots = jnp.take(self._slot_map(), idx)
        self._c.hits.inc()
        return {name: jnp.take(self._slabs[name], slots,
                               axis=0).astype(jnp.dtype(f.out))
                for name, f in self._plan.fields.items()}

    # -- teardown -----------------------------------------------------------

    def drop(self):
        """Release the tier (explicit buffer delete); loader falls back to
        streaming.  Safe to call mid-epoch and more than once."""
        if self._dropped:
            return
        _decisions.record_decision(
            'residency', 'drop', 'residency_budget',
            {'entries': len(self._entries),
             'resident_rows': self.resident_rows,
             'capacity': self._capacity})
        if self._slabs is not None:
            live_entries = len(self._entries)
            if live_entries:
                self._c.evictions.inc(live_entries)
            for leaf in self._slabs.values():
                delete = getattr(leaf, 'delete', None)
                if callable(delete):
                    try:
                        delete()
                    except RuntimeError:
                        # Already freed — the slab was donated into a
                        # later admission write; nothing left to release.
                        pass
        self._slabs = None
        self._entries.clear()
        self._free = []
        self._bump = 0
        self._slot_of_row[:] = -1
        self._slot_map_dev = None
        self._dropped = True
        self._update_gauges()
