"""Double-buffered device loader: reader rows/batches -> jax.Array pytrees.

The TPU-native peer of the reference's framework adapters
(``petastorm/pytorch.py :: DataLoader/BatchedDataLoader``,
``petastorm/tf_utils.py :: make_petastorm_dataset``), designed for the XLA
execution model instead of translated from them:

* **Static shapes** — fixed ``batch_size``, ``drop_last=True`` by default, so
  every step hits the same compiled executable (no re-tracing).
* **Async dispatch double-buffering** — ``jax.device_put`` returns
  immediately while DMA proceeds; the loader keeps ``prefetch`` batches in
  flight so H2D transfer of batch N+1 overlaps the device step on batch N.
* **Pipelined transfer plane** (``petastorm_tpu.jax.transfer``) — on
  accelerator backends a background dispatch thread stages each batch
  into a reused ring slab (one coalesced ``device_put`` per batch, not
  one per column, opt-in bf16/uint8 wire narrowing, per-device parallel
  dispatch under a ``sharding``) so host staging, the link, and the
  step overlap as three pipeline stages; ``transfer=``/``wire_dtypes=``
  /``ring_slots=`` control it, unsupported shapes degrade bit-identical.
* **Multi-host global batches** — pass ``sharding`` (a ``NamedSharding``
  over a mesh) and each host contributes its local rows via
  ``jax.make_array_from_process_local_data``; the yielded pytree holds
  global jax.Arrays ready for pjit (every host must run the same number of
  steps — use ``drop_last=True`` and equal per-host shards, see
  SURVEY.md §7 risks).
* **Columnar fast path** — with a ``make_batch_reader`` underneath, arrow
  column chunks are re-batched with numpy concatenation; no per-row python
  loop (the analog of the reference's BatchedDataLoader speedup).
"""

import logging
import os
import time
from collections import deque
from contextlib import contextmanager

import numpy as np

import jax

from petastorm_tpu.parallel.mesh import global_batch_from_local

logger = logging.getLogger(__name__)


class DataLoader(object):
    """Iterate device-resident batches from a petastorm_tpu reader.

    Args:
        reader: ``make_reader``/``make_batch_reader`` result.
        batch_size: rows per (per-host) batch; with ``sharding`` this is the
            LOCAL batch — global batch = batch_size × process_count.
        shuffling_queue_capacity: >0 enables a host-side shuffling reservoir
            (row readers: row granularity; batch readers: columnar window).
        min_after_retrieve: minimum mixing radius once warm.
        transform_fn: host-side pytree hook applied to each numpy batch
            before transfer (casting, normalization, augmentation).
        drop_last: drop the trailing partial batch (default True: XLA static
            shapes; a ragged last batch would trigger recompilation).
        prefetch: device batches kept in flight (2 = double buffering).
        device / sharding: target placement. ``sharding`` wins and assembles
            global arrays from per-host local data.
        seed: shuffling seed.
        trace_recorder: optional ``benchmark.TraceRecorder`` — every timed
            section (host_batch / transform / device_put) is additionally
            recorded as a chrome-trace span (timeline view of the same
            time ``stats`` aggregates).
        transfer: the host→device transfer plane
            (``petastorm_tpu.jax.transfer``): ``'auto'`` (default) turns
            it on when an accelerator backend is live, ``True`` forces it
            on (CPU tests), ``False`` keeps the inline ``device_put``
            path.  When on, a background dispatch thread stages each
            batch into a reused ring slab (one coalesced ``device_put``
            per batch instead of one per column) so the link runs as its
            own overlapped pipeline stage; ``PETASTORM_TPU_NO_TRANSFER_
            PLANE=1`` kills it globally, and unsupported batch
            structures degrade per batch to the inline path with
            bit-identical results.
        wire_dtypes: opt-in wire narrowing for the transfer plane:
            ``'auto'`` ships float32/float64 leaves as bfloat16 and
            casts back on device (half/quarter the bytes on the link —
            values round to bf16), or a ``{field: dtype}`` dict for
            explicit control.  ``None`` (default) transfers every leaf
            at full width, bit-identical to ``jax.device_put``.
        ring_slots: device-buffer ring depth for the transfer plane
            (default ``prefetch + 1``): up to ``ring_slots - 1``
            transfers stay in flight while the step runs.
        autotune: stage autotuning (ISSUE 9).  ``'auto'`` (default)
            activates when the underlying reader runs the adaptive
            scheduler: a rate-limited, clamped tuner adjusts the
            ventilation lookahead window, the ventilator in-flight
            bound, and this loader's ``prefetch`` from measured stage
            p50/p99s (decode skew, host_batch vs device_put) and — when
            a ``StallMonitor`` is attached via
            :meth:`attach_stall_monitor` — the consumer's measured wait
            fraction.  Decisions export as ``sched_*`` gauges on
            ``self.metrics``.  ``True`` forces it on (FIFO readers tune
            prefetch only), ``False`` keeps every knob where you set it.
        batch_slo_ms: per-batch latency SLO (ISSUE 13).  When set (or
            via ``PETASTORM_TPU_BATCH_SLO_MS``), a sealed provenance
            record whose end-to-end wall exceeds the budget counts a
            ``slo_violations`` metric and auto-dumps the FULL journal
            (the whole causal chain) under ``PETASTORM_TPU_FLIGHT_DIR``
            for ``petastorm-tpu-explain``.  The journal itself
            (``self.provenance``) is on whenever provenance is
            (``PETASTORM_TPU_NO_PROVENANCE=1`` kills both).
    """

    def __init__(self, reader, batch_size, shuffling_queue_capacity=0,
                 min_after_retrieve=None, transform_fn=None, drop_last=True,
                 prefetch=2, device=None, sharding=None, seed=None,
                 resume_state=None, echo=1, trace_recorder=None,
                 transfer='auto', wire_dtypes=None, ring_slots=None,
                 autotune='auto', batch_slo_ms=None):
        if batch_size <= 0:
            raise ValueError('batch_size must be positive')
        if echo < 1:
            raise ValueError('echo must be >= 1')
        from petastorm_tpu.jax.transfer import validate_transfer
        validate_transfer(transfer)   # fail at construction, not first iter
        self.reader = reader
        self.batch_size = int(batch_size)
        self._shuffle_capacity = shuffling_queue_capacity
        self._min_after_retrieve = (min_after_retrieve if min_after_retrieve is not None
                                    else shuffling_queue_capacity // 2)
        self._transform_fn = transform_fn
        self._drop_last = drop_last
        self._echo = int(echo)
        self._prefetch = max(1, int(prefetch))
        self._device = device
        self._sharding = sharding
        self._seed = seed
        self._warned_fields = set()
        self._batched_input = getattr(reader, 'batched_output', False)
        # -- exact-resume machinery (see state_dict) --
        #: rows/chunks to serve BEFORE pulling from the reader: restored
        #: snapshot data first, then drained-but-unconsumed results that
        #: state_dict() reinjects so checkpointing never skips data locally.
        if resume_state is not None and 'batched' in resume_state \
                and bool(resume_state['batched']) != self._batched_input:
            raise ValueError(
                'resume_state came from a %s loader but this reader is %s — '
                'buffered data would be misinterpreted'
                % ('columnar' if resume_state['batched'] else 'row',
                   'columnar' if self._batched_input else 'row'))
        self._pushback = list((resume_state or {}).get('pushback', []))
        self._resume_state = resume_state
        self._pending = deque()
        self._shuffle_buf = None
        self._partial_rows = []
        self._col_chunks = None
        self._colsh = None
        #: Per-stage wall time (SURVEY.md §5.1 obligation): 'host_batch_s'
        #: covers waiting on the decode plane + collate, 'transform_s' the
        #: user hook, 'device_put_s' the H2D *dispatch* (the DMA itself is
        #: async and overlaps; on the transfer-plane path it covers the
        #: whole staged put — pack + dispatch + any ring commit wait —
        #: with the h2d_* histograms carrying the split).  Pair with StallMonitor for the consumer
        #: view and reader.diagnostics['decode_utilization'] for the
        #: worker-pool view (all three pools; the ZeroMQ pool ships child
        #: busy time back on each ack).  The source of truth is the
        #: telemetry registry (ISSUE 5): ``stats`` is a view over its
        #: counters, and each stage additionally feeds a log2-bucket
        #: latency histogram (``diagnostics`` reports the p50/p99s).
        from petastorm_tpu.telemetry import MetricsRegistry, flight
        # Always-on flight recorder for the trainer process (ISSUE 7):
        # the stage histograms below snapshot into its bounded ring so a
        # postmortem sees the minutes before a hang, not final totals.
        flight.enable(label='trainer')
        self.metrics = MetricsRegistry('loader')
        self._m_batches = self.metrics.counter('batches')
        self._m_stage = {
            stage: (self.metrics.counter(stage + '_s'),
                    self.metrics.histogram(stage))
            for stage in ('host_batch', 'transform', 'device_put')}
        #: ``device_put`` above times only the async DISPATCH; this
        #: histogram samples TRUE transfer completion (a periodic
        #: ``block_until_ready``, plus every ring-slot reuse wait when
        #: the transfer plane is on) so ``diagnostics`` reports both
        #: dispatch and commit p50/p99.
        self._m_commit = self.metrics.histogram('h2d_commit')
        self._commit_probe = 0
        # Per-batch provenance plane (ISSUE 13): every delivered batch
        # seals ONE record — the merge of its chunks' producer records
        # (pieces, worker pid/host, scheduling, cache, transport) with
        # this consumer's stage windows and the transfer-path outcome —
        # into a bounded journal; the stage histograms keep tail
        # exemplars ({'step': N}) pointing back into it, so any p99
        # resolves to the actual file/rowgroup/worker.
        from petastorm_tpu.telemetry import provenance as _provenance
        self._provenance_mod = _provenance
        self.provenance = None
        self._slo = None
        self._last_pull_window = None
        if _provenance.enabled():
            self.provenance = _provenance.ProvenanceJournal(label='loader')
            if batch_slo_ms is None:
                env_slo = os.environ.get('PETASTORM_TPU_BATCH_SLO_MS')
                if env_slo:
                    try:
                        batch_slo_ms = float(env_slo)
                    except ValueError:
                        batch_slo_ms = None
            if batch_slo_ms:
                self._slo = _provenance.SloWatchdog(
                    self.provenance, float(batch_slo_ms) / 1e3,
                    label='loader', metrics=self.metrics)
        self._transfer = transfer
        self._wire_dtypes = wire_dtypes
        self._ring_slots = ring_slots
        self._plane = None
        self._pump = None
        if autotune not in ('auto', True, False):
            raise ValueError("autotune must be 'auto', True or False; got %r"
                             % (autotune,))
        self._autotune = autotune
        self._tuner = None
        self._tuner_ventilator = None
        self._knobs = None
        self._stall_monitor = None
        self._trace = trace_recorder
        if trace_recorder is not None:
            # ProcessPool children ship their spans (pool/process,
            # pool/publish, cache/fill) on the ack channel; pointing the
            # pool at this recorder is what lands them on THIS timeline
            # — without it they sit in the pool's bounded remote_spans
            # buffer that nothing reads.  Same-host children share
            # CLOCK_MONOTONIC, so no offset is needed.
            pool = getattr(reader, '_pool', None)
            if pool is not None and hasattr(pool, 'trace_recorder'):
                pool.trace_recorder = trace_recorder

    def _observe(self, stage, t0, t1):
        """One stage sample: wall-time counter + latency histogram (the
        tail-exemplar refs attach at provenance-seal time, see
        :meth:`_seal_provenance`)."""
        counter, hist = self._m_stage[stage]
        counter.inc(t1 - t0)
        hist.observe(t1 - t0)

    def _seal_provenance(self, stages, transfer=None, residency=None):
        """Merge the reader records drained since the last batch with
        this batch's consumer-side stage windows, seal into the journal,
        and run the SLO watchdog.  ``residency`` is the resident tier's
        outcome for this batch (hit / admitted / evicted / bypass) when a
        residency-capable loader served it.  Returns the journal step,
        or None when provenance is off."""
        journal = self.provenance
        if journal is None:
            return None
        prov = self._provenance_mod
        records = []
        take = getattr(self.reader, 'take_provenance', None)
        if take is not None:
            try:
                records = take() or []
            except Exception:  # noqa: BLE001 — provenance is never load-bearing
                records = []
        record = prov.merge_records(records)
        for name, window in stages.items():
            if window is not None and window[1] > window[0]:
                record['stages'][name] = list(window)
        if transfer is not None:
            record['transfer'] = transfer
        if residency is not None:
            record['residency'] = residency
        record = journal.seal(record)
        # Back-annotate tail exemplars: the stage histograms observed
        # these windows before the step existed, so the refs attach
        # without re-counting — uniform across __iter__,
        # iter_host_batches and scan_batches consumption.
        ref = {'step': record['step']}
        for stage_name, hist_key in (('host_batch', 'host_batch'),
                                     ('transform', 'transform'),
                                     ('h2d_dispatch', 'device_put')):
            window = record['stages'].get(stage_name)
            if window is not None:
                self._m_stage[hist_key][1].note_exemplar(
                    window[1] - window[0], ref)
        if self._slo is not None:
            self._slo.check(record)
        return record['step']

    def dump_provenance(self, path):
        """Persist the provenance journal (atomic JSON) — the file
        ``petastorm-tpu-explain --journal`` reads.  Returns the path, or
        None when provenance is off or the write failed."""
        if self.provenance is None:
            return None
        return self.provenance.persist(path)

    @property
    def stats(self):
        """Aggregate per-stage seconds + batch count — the historical
        dict surface, now a view over ``self.metrics``."""
        return {'host_batch_s': self._m_stage['host_batch'][0].value,
                'transform_s': self._m_stage['transform'][0].value,
                'device_put_s': self._m_stage['device_put'][0].value,
                'batches': int(self._m_batches.value)}

    # -- iteration -----------------------------------------------------------

    def _transfer_plane(self):
        """The loader's transfer plane, or None when disabled (kill
        switch, ``transfer=False``, or ``'auto'`` on the CPU backend).
        Built once; shares the loader's registry and trace recorder so
        its ``h2d_*`` histograms and ``h2d/*`` spans land on the same
        surfaces as every other stage."""
        from petastorm_tpu.jax import transfer
        if not transfer.plane_enabled(self._transfer):
            return None
        if self._plane is None:
            ring = (self._ring_slots if self._ring_slots is not None
                    else self._prefetch + 1)
            self._plane = transfer.TransferPlane(
                device=self._device, sharding=self._sharding,
                wire_dtypes=self._wire_dtypes, ring_slots=ring,
                metrics=self.metrics, trace_recorder=self._trace)
        return self._plane

    def _sample_commit(self, dev, every=32):
        """Periodic true-completion sample for the INLINE path: 1-in-
        ``every`` device_puts additionally waits for the transfer to
        land, feeding the ``h2d_commit`` histogram (the plane path
        observes commits on every ring-slot reuse instead)."""
        self._commit_probe += 1
        if (self._commit_probe - 1) % every:
            return
        t0 = time.monotonic()
        jax.block_until_ready(dev)
        t1 = time.monotonic()
        self._m_commit.observe(t1 - t0)
        if self._trace is not None:
            self._trace.event('h2d/commit', t0, t1, kind='sample')

    def __iter__(self):
        plane = self._transfer_plane()
        if plane is not None:
            if self._pump is not None and self._pump.alive:
                # A previous iteration's dispatch thread is still winding
                # down (a pull parked in the reader): never share a ring
                # with it — a fresh plane gets fresh slabs.
                self._plane = None
                plane = self._transfer_plane()
            return self._iter_pumped(plane)
        return self._iter_inline()

    def _iter_pumped(self, plane):
        """Transfer-plane iteration: a background dispatch thread pulls
        host batches, transforms, and ring-transfers them, so host
        staging, the H2D link, and the device step overlap as three
        pipeline stages.  Batch order, values, accounting surfaces and
        the exact-resume contract are identical to the inline path."""
        from jax.profiler import TraceAnnotation

        from petastorm_tpu.jax.transfer import _DONE, DispatchPump

        restored = []
        if self._resume_state and self._resume_state.get('pending'):
            restored = [self._to_device(b)
                        for b in self._resume_state['pending']]
            self._resume_state = dict(self._resume_state, pending=[])

        def annotated_pulls(gen):
            # Same pt/* jax.profiler spans as the inline path (SURVEY
            # §5.1) — they land on the dispatch thread's track, which is
            # exactly where this pipeline stage now runs.
            while True:
                with TraceAnnotation('pt/host_batch'):
                    try:
                        item = next(gen)
                    except StopIteration:
                        return
                yield item

        def ship(host_batch):
            t1 = time.monotonic()
            if self._transform_fn is not None:
                with TraceAnnotation('pt/transform'):
                    host_batch = self._transform_fn(host_batch)
            t2 = time.monotonic()
            with TraceAnnotation('pt/device_put'):
                dev = plane.put(
                    _filter_numeric(host_batch, self._warned_fields))
                degraded = dev is None
                if degraded:   # structure degrades: the existing path
                    dev = self._to_device(host_batch)
            t3 = time.monotonic()
            if self.provenance is not None:
                last = (plane.last_put if not degraded else None) or {}
                stages = dict(last.get('stages') or {})
                stages['transform'] = [t1, t2]
                if degraded:
                    stages['h2d_dispatch'] = [t2, t3]
                if self._last_pull_window is not None:
                    # _timed_pulls runs on this same (pump) thread right
                    # before ship(), so the stash is this batch's pull.
                    stages['host_batch'] = list(self._last_pull_window)
                self._seal_provenance(
                    stages, transfer=('degraded' if degraded
                                      else last.get('outcome')))
            self._observe('transform', t1, t2)
            # Counter/histogram continuity: device_put_s covers the whole
            # put (stage + dispatch + any ring commit wait) on this path.
            self._observe('device_put', t2, t3)
            self._m_batches.inc()
            if self._trace is not None:
                n = int(self._m_batches.value)
                if self._transform_fn is not None:
                    self._trace.event('transform', t1, t2, batch=n)
                if degraded:
                    # Only the inline fallback records the generic
                    # 'device_put' SPAN: a plane-handled batch already
                    # emitted h2d/stage + h2d/dispatch (+ h2d/commit)
                    # inside this window, and a wrapper span here would
                    # fold staging time into the 'h2d' link component —
                    # h2d >= h2d_stage by construction — so stall
                    # attribution could never name staging as top.
                    self._trace.event('device_put', t2, t3, batch=n)
            return dev

        pump = DispatchPump(
            annotated_pulls(self._timed_pulls(self._echoed_host_batches())),
            ship, self._prefetch)
        for dev in restored:
            pump.pending.append(dev)
        self._pending = pump.pending
        self._pump = pump
        pump.start()
        try:
            while True:
                item = pump.get()
                if item is _DONE:
                    break
                yield item
        finally:
            # Keep self._pump referencing this (now stopping) pump:
            # __exit__'s plane-close guard must still see a thread that
            # outlived the bounded join below, and a paused/`state_dict`
            # call on a finished pump returns immediately.  The short
            # join keeps early `break`s cheap — a thread parked in a
            # slow reader pull is released by reader.stop() in __exit__.
            pump.stop(join_timeout_s=0.2)
            if not pump.alive:
                # Draining the ring under a still-shipping thread
                # (bounded join timed out on a slow/wedged backend)
                # would race _wait_slot/put, and block_until_ready
                # could hang this generator close.
                plane.drain()

    def _iter_inline(self):
        # TraceAnnotation spans make the data pipeline visible in
        # ``jax.profiler`` device traces (SURVEY.md §5.1): when a step
        # stalls, the trace shows whether the time went to the decode
        # plane (pt/host_batch), the user hook (pt/transform), or the H2D
        # dispatch (pt/device_put).  Overhead is negligible when no trace
        # is active.
        from jax.profiler import TraceAnnotation

        self._pending = deque()
        if self._resume_state and self._resume_state.get('pending'):
            for host_batch in self._resume_state['pending']:
                self._pending.append(self._to_device(host_batch))
            self._resume_state = dict(self._resume_state, pending=[])
        pending = self._pending
        batches = self._echoed_host_batches()
        while True:
            t0 = time.monotonic()
            try:
                with TraceAnnotation('pt/host_batch'):
                    host_batch = next(batches)
            except StopIteration:
                break
            t1 = time.monotonic()
            if self._transform_fn is not None:
                with TraceAnnotation('pt/transform'):
                    host_batch = self._transform_fn(host_batch)
            t2 = time.monotonic()
            with TraceAnnotation('pt/device_put'):
                pending.append(self._to_device(host_batch))
            t3 = time.monotonic()
            if self.provenance is not None:
                self._seal_provenance(
                    {'host_batch': [t0, t1], 'transform': [t1, t2],
                     'h2d_dispatch': [t2, t3]}, transfer='inline')
            self._observe('host_batch', t0, t1)
            self._observe('transform', t1, t2)
            self._observe('device_put', t2, t3)
            self._m_batches.inc()
            if self._trace is not None:
                n = int(self._m_batches.value)
                self._trace.event('host_batch', t0, t1, batch=n)
                if self._transform_fn is not None:
                    self._trace.event('transform', t1, t2, batch=n)
                self._trace.event('device_put', t2, t3, batch=n)
            self._sample_commit(pending[-1])
            if len(pending) > self._prefetch:
                yield pending.popleft()
        while pending:
            yield pending.popleft()

    def _host_batches(self):
        gen = (self._columnar_batches() if self._batched_input
               else self._row_batches())
        gen = self._autotuned(gen)
        if self._trace is not None:
            gen = self._ingest_spans_drained(gen)
        return gen

    def _ingest_spans_drained(self, gen):
        """Merge the ingest plane's ``ingest/fetch`` / ``ingest/hedge``
        spans (ISSUE 14) onto this recorder's timeline, once per host
        batch.  Same process, same CLOCK_MONOTONIC — offset 0; so stall
        attribution can name ``ingest_fetch`` as a component."""
        from petastorm_tpu.telemetry.spans import merge_into_recorder
        for batch in gen:
            plane = getattr(self.reader, 'ingest_plane', None)
            if plane is not None:
                merge_into_recorder(self._trace, plane.spans.drain())
            yield batch

    # -- stage autotuning (ISSUE 9) ------------------------------------------

    def attach_stall_monitor(self, monitor):
        """Give the autotuner the consumer's ``StallMonitor``: its
        measured wait fraction over each tuning window is the strongest
        prefetch signal (the consumer actually starving vs merely skewed
        stage quantiles)."""
        self._stall_monitor = monitor
        if self._tuner is not None:
            self._tuner.attach_stall_monitor(monitor)

    def _set_prefetch(self, depth):
        # Read per batch by the inline path; the pumped path picks the
        # new depth up at its next iteration (the pump's bound is fixed
        # per run).
        self._prefetch = max(1, int(depth))

    def _build_autotuner(self):
        """The loader-side autotuner, or None (autotune off, or 'auto'
        with a FIFO reader).  Binds live setters for the three knobs it
        owns: adaptive window, ventilator in-flight bound, prefetch."""
        if self._autotune is False:
            return None
        from petastorm_tpu.workers_pool import scheduling as sched
        ventilator = getattr(self.reader, '_ventilator', None)
        # cache keyed on the ventilator INSTANCE: reader.reset() builds a
        # new pool/ventilator/policy/cost model, and a tuner bound to the
        # old ones would freeze (the fresh-samples gate reads the dead
        # cost model) while writing knobs into stopped objects
        if self._tuner is not None and ventilator is self._tuner_ventilator:
            return self._tuner
        self._tuner = None
        policy = getattr(ventilator, '_policy', None)
        adaptive = bool(getattr(policy, 'adaptive', False))
        if self._autotune == 'auto' and not adaptive:
            return None
        knobs = sched.SchedulerKnobs(
            window=getattr(policy, 'window', sched.MIN_WINDOW),
            max_inflight=getattr(ventilator, 'max_inflight',
                                 sched.MIN_INFLIGHT),
            prefetch=self._prefetch)
        if adaptive:
            knobs.bind('window',
                       lambda v, p=policy: setattr(p, 'window', v))
            # the in-flight bound doubles as the reorder-depth knob, so
            # it is only the tuner's to move on adaptive readers — on a
            # FIFO reader (autotune=True) shrinking it would just
            # throttle the pipeline below the pool size ("FIFO readers
            # tune prefetch only", the documented contract)
            if ventilator is not None \
                    and hasattr(ventilator, 'set_max_inflight'):
                knobs.bind('max_inflight', ventilator.set_max_inflight)
        knobs.bind('prefetch', self._set_prefetch)
        # Ingest plane (ISSUE 14): the readahead window is the fourth
        # knob — grown when decode measurably blocks on fetches, shrunk
        # gently when a window of fetches completed with zero waits.
        ingest_plane = getattr(self.reader, 'ingest_plane', None)
        if ingest_plane is not None:
            knobs.ingest_window = ingest_plane.window
            knobs.bind('ingest_window', ingest_plane.set_window)
        self._knobs = knobs
        # the no-skew shrink floor scales with the pool: the in-flight
        # bound counts undelivered positions (ack-on-delivery), so
        # dropping it below 2x workers would idle workers FIFO's own
        # default bound keeps busy
        workers = getattr(getattr(self.reader, '_pool', None),
                          'workers_count', 0) or 0
        self._tuner = sched.Autotuner(
            registry=self.metrics,
            cost_model=getattr(self.reader, 'cost_model', None),
            stall_monitor=self._stall_monitor,
            min_inflight=max(sched.MIN_INFLIGHT, 2 * workers))
        if ingest_plane is not None:
            self._tuner.attach_ingest(ingest_plane)
            self.metrics.gauge('sched_ingest_window').set(knobs.ingest_window)
        self._tuner_ventilator = ventilator
        # publish the starting point so the gauges tell the whole story
        self.metrics.gauge('sched_window').set(knobs.window)
        self.metrics.gauge('sched_max_inflight').set(knobs.max_inflight)
        self.metrics.gauge('sched_prefetch').set(knobs.prefetch)
        return self._tuner

    def _autotuned(self, gen):
        tuner = self._build_autotuner()
        if tuner is None:
            return gen
        reader_metrics = getattr(self.reader, 'metrics', None)
        decode_hist = (reader_metrics.histogram('decode')
                       if reader_metrics is not None else None)
        host_hist = self._m_stage['host_batch'][1]
        put_hist = self._m_stage['device_put'][1]

        def ticked():
            for batch in gen:
                yield batch
                tuner.maybe_tune(self._knobs, decode=decode_hist,
                                 host_batch=host_hist, device_put=put_hist)
        return ticked()

    def _echoed_host_batches(self):
        """Host batches with data echoing: each decoded batch repeats
        ``echo`` times consecutively (Choi et al., "Faster Neural Network
        Training with Data Echoing") — when the decode plane, not the
        chip, is the bottleneck, e echoes cut the required decode rate
        e-fold; device-side augmentation (``petastorm_tpu.jax.augment``
        inside the step, fresh rng per step) keeps echoes from being
        exact repeats.  A mid-echo checkpoint resumes at the batch, not
        the echo repeat (echo is a schedule over data, not data).

        Echo repeats are dict-level-recursive copies, so a ``transform_fn``
        that REBINDS keys (at any nesting level — ngram batches are
        dict-of-dicts) is applied freshly per echo (host augmentation
        varies across echoes).  Transforms must not mutate input arrays
        in place — with echo the same arrays are visible to every
        repeat, so in-place mutation would compound."""
        if self._echo <= 1:
            return self._host_batches()

        def copy_tree(node):
            if isinstance(node, dict):
                return {k: copy_tree(v) for k, v in node.items()}
            return node

        def gen():
            for host_batch in self._host_batches():
                yield host_batch
                for _ in range(self._echo - 1):
                    yield copy_tree(host_batch)
        return gen()

    def _source(self, convert):
        """Pushback (restored/drained) items first, then converted reader
        output — re-checking pushback before every reader pull so data
        reinjected by ``state_dict`` keeps stream order."""
        reader_iter = iter(self.reader)
        while True:
            if self._pushback:
                yield self._pushback.pop(0)
                continue
            try:
                item = next(reader_iter)
            except StopIteration:
                if self._pushback:
                    continue
                return
            yield convert(item)

    def _row_source(self):
        return self._source(_row_as_dict)

    def _chunk_source(self):
        return self._source(
            lambda c: c._asdict() if hasattr(c, '_asdict') else dict(c))

    def _row_batches(self):
        """Row readers: buffer namedtuple/pytree rows, stack per batch."""
        if self._shuffle_capacity > 0:
            from petastorm_tpu.reader_impl.shuffling_buffer import RandomShufflingBuffer
            buffer = RandomShufflingBuffer(self._shuffle_capacity,
                                           self._min_after_retrieve, seed=self._seed)
        else:
            from petastorm_tpu.reader_impl.shuffling_buffer import NoopShufflingBuffer
            buffer = NoopShufflingBuffer()
        if self._resume_state and self._resume_state.get('shuffle_buffer'):
            buffer.load_state_dict(self._resume_state['shuffle_buffer'])
        self._shuffle_buf = buffer
        self._partial_rows = list((self._resume_state or {}).get('partial_rows', []))

        # State is detached BEFORE each yield: the generator suspends at the
        # yield, and a state_dict() taken there must not see rows that are
        # already inside the yielded batch.
        bs = self.batch_size
        for row in self._row_source():
            buffer.add_many([row])
            while buffer.can_retrieve():
                self._partial_rows.append(buffer.retrieve())
                if len(self._partial_rows) >= bs:
                    out, self._partial_rows = (self._partial_rows[:bs],
                                               self._partial_rows[bs:])
                    yield self._stack_rows(out)
        buffer.finish()
        while not buffer.finished:
            self._partial_rows.append(buffer.retrieve())
            if len(self._partial_rows) >= bs:
                out, self._partial_rows = (self._partial_rows[:bs],
                                           self._partial_rows[bs:])
                yield self._stack_rows(out)
        if self._partial_rows and not self._drop_last:
            out, self._partial_rows = self._partial_rows, []
            yield self._stack_rows(out)

    def _stack_rows(self, rows):
        """Stack a list of row structures (namedtuples / ngram dicts) into one
        dict pytree of (B, ...) arrays.  Plain-python recursion rather than
        tree_map: None cells (nullable fields) are data here, not empty
        subtrees."""
        return _stack_dicts([_row_as_dict(r) for r in rows])

    def _columnar_batches(self):
        """Batch readers: re-batch column chunks; no per-row loop.

        Non-shuffle path is copy-free where possible: a chunk exactly
        batch_size long passes through untouched; otherwise batches are
        sliced views across a chunk deque with at most one concatenate per
        boundary-straddling batch.
        """
        if self._shuffle_capacity > 0:
            yield from self._columnar_batches_shuffled()
            return

        chunks = deque()   # (chunk_dict, start_offset); shared for snapshots
        self._col_chunks = chunks
        count = 0
        if self._resume_state and self._resume_state.get('chunks'):
            for chunk_dict in self._resume_state['chunks']:
                n = len(next(iter(chunk_dict.values())))
                chunks.append((chunk_dict, 0))
                count += n
        for chunk_dict in self._chunk_source():
            n = len(next(iter(chunk_dict.values())))
            if count == 0 and n == self.batch_size:
                yield chunk_dict  # zero-copy pass-through (the common case)
                continue
            chunks.append((chunk_dict, 0))
            count += n
            while count >= self.batch_size:
                yield self._take_front(chunks, self.batch_size)
                count -= self.batch_size
        if count and not self._drop_last:
            yield self._take_front(chunks, count)

    @staticmethod
    def _take_front(chunks, size):
        """Pop ``size`` rows off the front of the chunk deque; slices are
        views, concatenation only happens across chunk boundaries."""
        parts = []
        need = size
        while need > 0:
            chunk_dict, start = chunks.popleft()
            n = len(next(iter(chunk_dict.values())))
            avail = n - start
            take = min(avail, need)
            parts.append({k: v[start:start + take] for k, v in chunk_dict.items()})
            if take < avail:
                chunks.appendleft((chunk_dict, start + take))
            need -= take
        if len(parts) == 1:
            return parts[0]
        return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

    def _columnar_batches_shuffled(self):
        """Windowed columnar shuffle: uniform draws from a >=capacity buffer.

        State (accumulated columns, row count, rng) lives in ``self._colsh``
        so ``state_dict`` can snapshot it mid-epoch."""
        st = self._colsh = {'rng': np.random.default_rng(self._seed),
                            'columns': None,  # field -> [np.ndarray]
                            'count': 0}
        if self._resume_state and self._resume_state.get('col_shuffle'):
            saved = self._resume_state['col_shuffle']
            st['rng'].bit_generator.state = saved['rng_state']
            if saved['columns'] is not None:
                st['columns'] = {k: [v] for k, v in saved['columns'].items()}
                st['count'] = len(next(iter(saved['columns'].values())))
        for chunk_dict in self._chunk_source():
            n = len(next(iter(chunk_dict.values())))
            if st['columns'] is None:
                st['columns'] = {k: [v] for k, v in chunk_dict.items()}
            else:
                for k, v in chunk_dict.items():
                    st['columns'][k].append(v)
            st['count'] += n
            threshold = max(self.batch_size, self._shuffle_capacity)
            while st['count'] >= threshold:
                st['columns'] = {k: [np.concatenate(v)] if len(v) > 1 else v
                                 for k, v in st['columns'].items()}
                take = st['rng'].permutation(st['count'])[:self.batch_size]
                batch = {k: np.take(v[0], take, axis=0)
                         for k, v in st['columns'].items()}
                keep = np.ones(st['count'], dtype=bool)
                keep[take] = False
                st['columns'] = {k: [v[0][keep]]
                                 for k, v in st['columns'].items()}
                st['count'] -= self.batch_size
                yield batch
        # Drain remainder.
        if st['count'] and st['columns']:
            st['columns'] = {k: [np.concatenate(v)] if len(v) > 1 else v
                             for k, v in st['columns'].items()}
            order = st['rng'].permutation(st['count'])
            start = 0
            while st['count'] - start >= self.batch_size:
                take = order[start:start + self.batch_size]
                yield {k: np.take(v[0], take, axis=0)
                       for k, v in st['columns'].items()}
                start += self.batch_size
            if st['count'] - start > 0 and not self._drop_last:
                take = order[start:]
                yield {k: np.take(v[0], take, axis=0)
                       for k, v in st['columns'].items()}

    # -- device transfer -----------------------------------------------------

    def _to_device(self, host_batch):
        numeric = _filter_numeric(host_batch, self._warned_fields)
        if self._sharding is not None:
            return global_batch_from_local(numeric, self._sharding)
        if self._device is not None:
            return jax.device_put(numeric, self._device)
        return jax.device_put(numeric)

    def iter_host_batches(self):
        """Yield the host-side numpy batch pytrees WITHOUT device transfer.

        The same batches ``__iter__`` would stage (shuffling, batching,
        ``transform_fn``, resume all apply) but stopping at the host
        boundary: for feeding non-JAX consumers, writing derived datasets,
        or measuring the host delivery plane in isolation (``bench.py``'s
        ``delivery_plane_images_per_sec_host`` leg uses this to prove the
        consumer path sustains chip rate independent of the transport).

        Caveat on resume: batches restored from ``resume_state`` were
        snapshotted AFTER the device-transfer filter, so they carry only
        numeric fields (string/object columns are gone) — fresh batches
        that follow carry every field.  Consumers that need non-numeric
        columns for every row should checkpoint with the prefetch queue
        drained, or tolerate the narrower leading batches.
        """
        # Restored prefetched batches first (already transformed when
        # snapshotted — do not run the transform twice).
        if self._resume_state and self._resume_state.get('pending'):
            restored = self._resume_state['pending']
            self._resume_state = dict(self._resume_state, pending=[])
            for host_batch in restored:
                self._m_batches.inc()
                yield host_batch
        # Same per-stage accounting as __iter__ (minus device_put — there
        # is none here), so the bottleneck advisor and the doctor can
        # diagnose a host-boundary consumer too.
        for host_batch in self._timed_pulls(self._echoed_host_batches()):
            t1 = time.monotonic()
            t2 = None
            if self._transform_fn is not None:
                host_batch = self._transform_fn(host_batch)
                t2 = time.monotonic()
                self._observe('transform', t1, t2)
                if self._trace is not None:
                    self._trace.event('transform', t1, t2)
            if self.provenance is not None:
                stages = {}
                if self._last_pull_window is not None:
                    stages['host_batch'] = list(self._last_pull_window)
                if t2 is not None:
                    stages['transform'] = [t1, t2]
                self._seal_provenance(stages)
            self._m_batches.inc()
            yield host_batch

    def _timed_pulls(self, gen):
        """Yield from ``gen``, accounting the wait on the decode plane
        into ``stats['host_batch_s']`` (+ a trace span) — the one place
        that owns pull accounting for every host-boundary consumer
        (``iter_host_batches``, ``scan_batches``)."""
        while True:
            t0 = time.monotonic()
            try:
                host_batch = next(gen)
            except StopIteration:
                return
            t1 = time.monotonic()
            # Provenance: the pull window of the batch about to be
            # consumed (read by ship() / the host-boundary consumers on
            # the same thread).
            self._last_pull_window = (t0, t1)
            self._observe('host_batch', t0, t1)
            if self._trace is not None:
                self._trace.event('host_batch', t0, t1)
            yield host_batch

    # -- fused multi-step consumption ----------------------------------------

    def scan_batches(self, step_fn, carry, steps_per_call=8,
                     donate_carry=True):
        """Consume the stream with ONE jitted dispatch per ``steps_per_call``
        steps instead of two per step.

        Host batches are collected in chunks of ``steps_per_call``, stacked
        to ``(k, batch, ...)``, transferred in a single ``device_put`` (same
        bytes, 1/k the transfer dispatches), and run through
        ``lax.scan(step_fn, carry, chunk)`` as one executable.  Per-step
        dispatch overhead — python + transport round-trips, the dominant
        stall for fast steps or high-latency links — shrinks by k×, while
        host decode of the next chunk still overlaps device compute (the
        scan call is async).

        ``step_fn(carry, batch) -> (carry, out)`` sees exactly the batches
        ``__iter__`` would deliver.  Yields ``(carry, outs)`` per chunk
        (``outs`` stacked along a leading axis of length k).  A trailing
        chunk shorter than ``steps_per_call`` triggers one extra compile
        for its size.  With ``sharding=``, each stacked leaf is assembled
        as a global array with a leading unsharded step axis.

        The HBM-cached sibling (``DeviceInMemDataLoader.scan_epochs``)
        removes host work entirely; this is the streaming-regime analog
        where data must flow host→device every step regardless.

        Checkpointing composes: batches restored from ``resume_state``
        (prefetched by the previous run) are served first, and every
        full-chunk ``yield`` has an empty fill buffer (each yield follows
        a flush), so a ``state_dict()`` taken between yields loses
        nothing under the default ``drop_last=True`` — the exact-resume
        contract survives switching between ``__iter__`` and
        ``scan_batches`` consumption.  One carve-out: with
        ``drop_last=False``, the yield forced by the ragged tail batch
        holds that tail outside the snapshot — checkpointing at exactly
        that yield (the stream's final flush) drops the tail rows; keep
        ``drop_last=True`` when mid-stream checkpoints must be exact.
        """
        from jax import lax

        if steps_per_call < 1:
            raise ValueError('steps_per_call must be >= 1')
        fn = jax.jit(lambda c, xs: lax.scan(step_fn, c, xs),
                     donate_argnums=(0,) if donate_carry else ())
        # The stacked chunk rides the transfer plane too (one coalesced
        # ring transfer per k-step chunk); the sharded scan spec shards
        # axis 1, not the leading axis, so it keeps the existing
        # assembly path.
        plane = self._transfer_plane() if self._sharding is None else None

        def put_stacked(chunk, transformed=False):
            # Same per-stage stats accounting as __iter__ (transform /
            # stack+upload), so the bottleneck advisor can diagnose a
            # scan_batches-consumed loader too.
            t0 = time.monotonic()
            if self._transform_fn is not None and not transformed:
                chunk = [self._transform_fn(b) for b in chunk]
            t1 = time.monotonic()
            stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *chunk)
            numeric = _filter_numeric(stacked, self._warned_fields)
            out = None
            if self._sharding is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                spec = PartitionSpec(None, *self._sharding.spec)
                out = global_batch_from_local(
                    numeric, NamedSharding(self._sharding.mesh, spec))
            elif plane is not None:
                out = plane.put(numeric)   # None: degrade to inline below
            planed = out is not None and plane is not None \
                and self._sharding is None
            if out is None:
                if self._device is not None:
                    out = jax.device_put(numeric, self._device)
                else:
                    out = jax.device_put(numeric)
            t2 = time.monotonic()
            self._observe('transform', t0, t1)
            self._observe('device_put', t1, t2)
            if self._trace is not None:
                if self._transform_fn is not None and not transformed:
                    self._trace.event('transform', t0, t1, chunk=len(chunk))
                if not planed:
                    # Plane-handled chunks already emitted h2d/* spans in
                    # this window; a wrapper 'device_put' span would fold
                    # staging into the link component (see ship()).
                    self._trace.event('device_put', t1, t2,
                                      chunk=len(chunk))
            if not planed:
                self._sample_commit(out, every=4)
            return out

        def rows_of(batch):
            return len(next(iter(jax.tree_util.tree_leaves(batch))))

        # Batches the interrupted run had already prefetched come first —
        # one 1-step scan each.  They were snapshotted POST-transform and
        # post-filter (state_dict stores what __iter__ had staged for the
        # device), so the transform must not run again; sizes may vary,
        # and mixing their numeric-only structure into a fresh chunk would
        # break stacking — hence one call each.
        if self._resume_state and self._resume_state.get('pending'):
            restored = self._resume_state['pending']
            self._resume_state = dict(self._resume_state, pending=[])
            for host_batch in restored:
                self._m_batches.inc()
                carry, outs = fn(carry, put_stacked([host_batch],
                                                    transformed=True))
                yield carry, outs

        chunk = []
        for host_batch in self._timed_pulls(self._echoed_host_batches()):
            if chunk and rows_of(host_batch) != rows_of(chunk[0]):
                # ragged tail (drop_last=False): flush so stacking stays
                # rectangular — the tail becomes its own (shorter) chunk
                carry, outs = fn(carry, put_stacked(chunk))
                chunk = []
                yield carry, outs
            chunk.append(host_batch)
            if self.provenance is not None:
                self._seal_provenance(
                    {'host_batch': list(self._last_pull_window)}
                    if self._last_pull_window is not None else {})
            self._m_batches.inc()
            if len(chunk) == steps_per_call:
                carry, outs = fn(carry, put_stacked(chunk))
                chunk = []
                yield carry, outs
        if chunk:
            carry, outs = fn(carry, put_stacked(chunk))
            yield carry, outs

    # -- exact mid-epoch checkpoint/resume -----------------------------------

    def state_dict(self):
        """EXACT mid-stream snapshot; resume with ``DataLoader(reader',
        batch_size, ..., resume_state=state)`` where ``reader'`` is built
        with ``resume_state=state['reader']``.

        Exactness contract: the restored loader yields precisely the
        batches the uninterrupted run had not yet yielded — same row
        multiset always, same order/content for seeded single-threaded
        (``dummy`` pool) runs.  Achieved by DRAINING: the reader pauses
        dispatch and every in-flight result is pulled into the snapshot
        (in-flight rows would otherwise replay or be lost at row-group
        granularity), alongside the prefetched device batches, the
        shuffling-buffer contents + rng state, the partial batch, and
        columnar chunk residue.  Snapshot size is bounded by the reader's
        in-flight window plus loader buffers.

        Call between batches from the consuming thread.  The loader keeps
        serving afterwards (drained rows are reinjected locally), so
        checkpoint-then-keep-training works.  The state is picklable
        (plain dicts/numpy); pair it with the model state in orbax via
        ``ocp.args.Pickle`` or bytes.

        With the transfer plane on, the background dispatch pump is
        paused first (it otherwise advances the shuffle/chunk buffers
        this snapshot reads) and every in-flight ring batch is already
        in ``pending`` by the time the pump is quiescent — the snapshot
        drains the ring by construction.
        """
        with self._pump_paused():
            return self._state_dict_quiesced()

    @contextmanager
    def _pump_paused(self):
        """Freeze the dispatch pump (when one is live) around a state
        snapshot.  EVERY ``state_dict`` in the loader family must read
        loader buffers under this bracket — outside it the dispatch
        thread races the shuffle/chunk/packer state being snapshotted.
        Counting pause, so brackets nest (PackedDataLoader wraps the
        base snapshot plus its packer residue in one outer bracket)."""
        pump = self._pump
        if pump is not None:
            pump.pause()
        try:
            yield
        finally:
            if pump is not None:
                pump.resume()

    def _state_dict_quiesced(self):
        drained = self.reader.drain_in_flight()
        if not self._batched_input:
            drained = [_row_as_dict(r) for r in drained]
        else:
            drained = [r._asdict() if hasattr(r, '_asdict') else dict(r)
                       for r in drained]
        # A loader restored from resume_state consumes the restored pieces
        # LAZILY (pending at first __iter__, buffers at first host batch);
        # until then the snapshot must carry them forward, not drop them.
        rs = self._resume_state or {}
        iterating = self._shuffle_buf is not None or self._col_chunks is not None \
            or self._colsh is not None
        state = {
            'version': 1,
            'batched': self._batched_input,
            'reader': self.reader.state_dict(),
            'pending': ([jax.device_get(b) for b in self._pending]
                        + list(rs.get('pending', []))),
            'pushback': list(self._pushback) + drained,
            'partial_rows': (list(self._partial_rows) if iterating
                             else list(rs.get('partial_rows', []))),
            'shuffle_buffer': (self._shuffle_buf.state_dict()
                               if self._shuffle_buf is not None
                               else rs.get('shuffle_buffer')),
            'chunks': ([{k: v[start:] for k, v in chunk.items()}
                        for chunk, start in self._col_chunks]
                       if self._col_chunks is not None
                       else list(rs.get('chunks', []))),
            'col_shuffle': rs.get('col_shuffle'),
        }
        if self._colsh is not None:
            cols = self._colsh['columns']
            state['col_shuffle'] = {
                'rng_state': self._colsh['rng'].bit_generator.state,
                'columns': (None if cols is None else
                            {k: (np.concatenate(v) if len(v) > 1 else v[0])
                             for k, v in cols.items()}),
            }
        self._pushback.extend(drained)
        self.reader.resume_dispatch()
        return state

    # -- lifecycle -----------------------------------------------------------

    @property
    def diagnostics(self):
        """The loader's registry view (per-stage seconds + log2-histogram
        p50/p99s) merged with the reader's pool diagnostics — including
        the epoch-cache plane counters (``cache_hits`` / ``cache_misses``
        / ``cache_evictions``) when the underlying reader runs
        ``cache_type='plane'``, so one gauge read says whether this epoch
        decoded or served warm."""
        out = self.metrics.as_dict()
        out['batches'] = int(out.get('batches', 0))
        if self.reader is not None:
            out.update(getattr(self.reader, 'diagnostics', None) or {})
        return out

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        pump = self._pump
        if pump is not None:
            # Ask the dispatch thread out first; a pull blocked inside
            # the reader is released by reader.stop() below, after which
            # the (daemonic) thread exits without shipping.
            pump.stop(join_timeout_s=0.5)
        if self.reader is not None:   # DiskCachedDataLoader allows None
            self.reader.stop()
            self.reader.join()
        if pump is not None:
            pump.join()
        if self._plane is not None and (pump is None or not pump.alive):
            # Only reclaim the slabs once the dispatch thread is truly
            # out — closing under a still-shipping thread (wedged
            # backend) would race the ring; the slabs are plain numpy
            # arrays and fall to the GC with the loader either way.
            self._plane.close()


def _row_as_dict(row):
    if hasattr(row, '_asdict'):
        row = row._asdict()
    if isinstance(row, dict):
        return {k: _row_as_dict(v) for k, v in row.items()}
    return row


def _stack_dicts(dicts):
    out = {}
    for key in dicts[0]:
        values = [d[key] for d in dicts]
        out[key] = _stack_dicts(values) if isinstance(values[0], dict) \
            else _stack_cells(values)
    return out


def _stack_cells(cells):
    first = next((c for c in cells if c is not None), None)
    if first is None or isinstance(first, str) or isinstance(first, bytes):
        out = np.empty(len(cells), dtype=object)
        out[:] = list(cells)
        return out
    return np.stack([c if c is not None else np.zeros_like(first) for c in cells])


def _filter_numeric(tree, warned):
    """Drop object-dtype (string/None) leaves — they cannot live in HBM."""
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    drop = set()
    for path, leaf in leaves_with_path:
        arr = np.asarray(leaf)
        if arr.dtype == object or arr.dtype.kind in ('U', 'S'):
            key = jax.tree_util.keystr(path)
            drop.add(key)
            if key not in warned:
                warned.add(key)
                logger.warning('Field %s has non-numeric dtype %s; kept on host '
                               '(excluded from device batch)', key, arr.dtype)

    def prune(path, leaf):
        return None if jax.tree_util.keystr(path) in drop else leaf

    pruned = jax.tree_util.tree_map_with_path(prune, tree)
    return _strip_none_leaves(pruned)


def _strip_none_leaves(obj):
    """Recursively drop None leaves; namedtuples become plain dicts (a
    device batch is a pytree, the row type is irrelevant past this point)."""
    if hasattr(obj, '_asdict'):
        obj = obj._asdict()
    if isinstance(obj, dict):
        out = {k: _strip_none_leaves(v) for k, v in obj.items()}
        return {k: v for k, v in out.items() if v is not None}
    return obj


def _canonical_row_order(cache):
    """Reorder an ``(N, ...)`` pytree of rows into a content-defined
    canonical order: sort by a per-row digest over fields in name order.

    Any worker pool delivers the same row MULTISET; after this sort any
    pool also yields the same SEQUENCE — which is what makes an exact
    in-memory resume token valid across a process restart that rebuilds
    the cache through a differently-ordered pool.  Identical rows tie on
    digest, and identical rows are interchangeable, so ties are harmless.
    Cost: one hashing pass over the decoded dataset at build time."""
    import hashlib

    items = sorted(cache.items()) if isinstance(cache, dict) else None
    if items is None:  # non-dict pytree: flatten with stable path order
        paths = jax.tree_util.tree_flatten_with_path(cache)[0]
        items = [(jax.tree_util.keystr(p), leaf) for p, leaf in paths]
        items.sort()
    n = len(items[0][1])
    digests = []
    for i in range(n):
        h = hashlib.blake2b(digest_size=16)
        for _, leaf in items:
            h.update(np.ascontiguousarray(leaf[i]).tobytes())
        digests.append(h.digest())
    idx = np.asarray(sorted(range(n), key=digests.__getitem__))
    return jax.tree_util.tree_map(lambda v: v[idx], cache)


class InMemDataLoader(DataLoader):
    """Epoch-cached loader: reads the dataset once, then serves ``num_epochs``
    (re)shuffled epochs straight from host RAM — no Parquet re-read, no
    decode-plane work after epoch 0.

    Parity: ``petastorm/pytorch.py :: InMemBatchedDataLoader``.  The right
    tool when the (decoded) dataset fits in host memory and epochs are short
    — e.g. MNIST-scale fine-tuning where reader startup would dominate.
    Construct the underlying reader with ``num_epochs=1``; epoch repetition
    happens here.

    ``deterministic_cache_order=True`` sorts the built cache into a
    content-defined canonical order (:func:`_canonical_row_order`), which
    makes the epoch sequence a pure function of ``(dataset, seed)`` — any
    pool, any restart — and unlocks exact mid-epoch ``state_dict`` /
    ``resume_state``, same contract as :class:`DiskCachedDataLoader`.
    """

    def __init__(self, reader, batch_size, num_epochs=1, shuffle=True,
                 seed=None, deterministic_cache_order=False, **kwargs):
        if getattr(reader, 'ngram', None) is not None:
            raise ValueError('InMemDataLoader does not support NGram readers')
        if kwargs.get('echo', 1) != 1:
            # Epochs serve from the cache — nothing decodes per step, so
            # echo would just duplicate cached batches silently.  (Covers
            # DeviceInMemDataLoader too; echo addresses decode-bound
            # STREAMING, where DataLoader and DiskCachedDataLoader keep it.)
            raise ValueError('%s does not support echo (epochs serve from '
                             'an in-memory cache; echo addresses '
                             'decode-bound streaming)' % type(self).__name__)
        reader_epochs = getattr(reader, 'num_epochs', 1)
        if reader_epochs != 1:
            # num_epochs=None (infinite) would hang the one-time cache build
            # forever; >1 would silently duplicate every row in the cache.
            raise ValueError(
                'InMemDataLoader requires a reader built with num_epochs=1 '
                '(got num_epochs=%r); epoch repetition happens in the loader'
                % (reader_epochs,))
        super(InMemDataLoader, self).__init__(reader, batch_size, seed=seed, **kwargs)
        self._num_epochs = num_epochs
        self._shuffle = shuffle
        self._deterministic = bool(deterministic_cache_order)
        self._cache = None
        self._im = None  # mid-epoch cursor (deterministic order only)

    def _build_cache(self):
        """One-time read of the whole dataset into ``self._cache`` (a dict
        pytree of (N, ...) host arrays); returns it, or None when empty."""
        if self._cache is None:
            # The cache must hold EVERY row: drop_last applies per epoch, not
            # to the one-time read — otherwise a ragged tail would be
            # excluded from all epochs permanently.
            drop_last, self._drop_last = self._drop_last, False
            try:
                parts = list(super(InMemDataLoader, self)._host_batches())
            finally:
                self._drop_last = drop_last
            if not parts:
                return None
            cache = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(xs), *parts)
            if self._deterministic:
                numeric = _filter_numeric(cache, self._warned_fields)
                if not jax.tree_util.tree_leaves(numeric):
                    raise ValueError(
                        'deterministic_cache_order=True requires at least '
                        'one numeric field (the canonical order hashes '
                        'numeric row content; every field here is '
                        'object/string-typed)')
                cache = _canonical_row_order(numeric)
            self._cache = cache
        return self._cache

    def _host_batches(self):
        if self._build_cache() is None:
            return
        n = len(next(iter(jax.tree_util.tree_leaves(self._cache))))
        if self._drop_last and n < self.batch_size:
            # num_epochs=None would otherwise spin forever yielding nothing
            logger.warning('epoch cache holds %d rows < batch_size=%d with '
                           'drop_last: no batches to serve', n, self.batch_size)
            return
        rng = np.random.default_rng(self._seed)
        epoch = 0
        order = None
        offset = 0
        resumed = (self._resume_state or {}).get('inmem_cache')
        if resumed:
            if not self._deterministic:
                raise ValueError(
                    'this resume token requires '
                    'deterministic_cache_order=True (the rebuilt cache '
                    'must reproduce the checkpointed row order)')
            rng.bit_generator.state = resumed['rng_state']
            epoch = int(resumed['epoch'])
            offset = int(resumed['offset'])
            order = (None if resumed['order'] is None
                     else np.asarray(resumed['order']))
        if self._deterministic:
            self._im = {'rng': rng, 'epoch': epoch, 'order': order,
                        'offset': offset}
        while self._num_epochs is None or epoch < self._num_epochs:
            if order is None:
                order = rng.permutation(n) if self._shuffle else np.arange(n)
            stop = n - self.batch_size + 1 if self._drop_last else n
            for start in range(offset, max(stop, 0), self.batch_size):
                if self._im is not None:
                    self._im.update(epoch=epoch, order=order,
                                    offset=start + self.batch_size)
                idx = order[start:start + self.batch_size]
                yield jax.tree_util.tree_map(lambda v: v[idx], self._cache)
            epoch += 1
            order = None
            offset = 0
            if self._im is not None:
                self._im.update(epoch=epoch, order=None, offset=0)

    def state_dict(self):
        """Exact mid-epoch resume token — requires
        ``deterministic_cache_order=True`` (the canonical cache order is
        what survives a restart; a pool-ordered cache does not)."""
        if not self._deterministic:
            raise NotImplementedError(
                'In-memory epoch caches are rebuilt from the reader, whose '
                'delivery order is pool-dependent, so an exact mid-epoch '
                'token cannot survive a process restart.  Build the loader '
                'with deterministic_cache_order=True (content-sorted cache, '
                'exact resume on any pool), checkpoint at epoch boundaries '
                '(rebuild with num_epochs reduced), or use '
                'DiskCachedDataLoader: its on-disk cache preserves row '
                'order and supports exact mid-epoch resume.')
        if self._im is None:
            raise ValueError('state_dict() is supported once iteration has '
                             'begun; call it between batches')
        with self._pump_paused():
            return self._inmem_state()

    def _inmem_state(self):
        im = self._im
        return {
            'version': 1,
            'pending': [jax.device_get(b) for b in self._pending],
            'inmem_cache': {
                'rng_state': im['rng'].bit_generator.state,
                'epoch': int(im['epoch']),
                'offset': int(im['offset']),
                'order': (None if im['order'] is None
                          else np.asarray(im['order'])),
            },
        }


class DeviceInMemDataLoader(InMemDataLoader):
    """Epoch cache in **device HBM**: decode the dataset once, then serve
    every subsequent batch with an on-device gather — zero host work per
    step after epoch 0.

    The TPU-native sibling of :class:`InMemDataLoader` (which caches in host
    RAM and still pays slice + H2D per batch).  When the decoded dataset fits
    in HBM (MNIST/CIFAR-scale, or a per-host ImageNet shard at low
    resolution), this is the idiomatic XLA pattern: the per-epoch shuffle is
    a device-side permutation (``jax.random.permutation``) and each batch is
    ``jnp.take`` over the resident arrays, so a fast chip is never throttled
    by host decode or PCIe/tunnel latency.

    Single-placement only: the cache lives on ``device`` (default: first
    local device).  Multi-host training wants per-host shards anyway — build
    the reader with ``cur_shard``/``shard_count`` (or rely on JAX auto-shard)
    and each host caches only its shard.
    """

    def __init__(self, reader, batch_size, num_epochs=1, shuffle=True,
                 seed=None, device=None, **kwargs):
        for unsupported in ('transform_fn', 'shuffling_queue_capacity'):
            if kwargs.get(unsupported):
                # Batches never exist on the host here, so the host-side
                # hooks cannot run — reject rather than silently drop them.
                # Transform inside the jitted step instead (the TPU-native
                # place for normalization/augmentation).
                raise ValueError('DeviceInMemDataLoader does not support %s'
                                 % unsupported)
        super(DeviceInMemDataLoader, self).__init__(
            reader, batch_size, num_epochs=num_epochs, shuffle=shuffle,
            seed=seed, device=device, **kwargs)
        if self._sharding is not None:
            raise ValueError('DeviceInMemDataLoader caches on one device; '
                             'use InMemDataLoader with sharding= for global '
                             'batch assembly')
        self._dev_cache = None
        self._gather_fn = None
        self._steps_into_epoch = 0
        #: (epochs, steps) to SKIP at the head of every pass (from a resume
        #: token); static — re-iterating the loader replays this baseline.
        self._start_epoch = 0
        self._start_step = 0
        #: live position of the CURRENT pass (state_dict reads it); reset
        #: to the baseline whenever a fresh pass begins.
        self._epochs_done = 0
        #: ``drop_last`` of the run that TOOK the resume token (None when
        #: not resuming, or for pre-drop_last tokens).  The step cursor's
        #: meaning depends on it: only a drop_last=False per-step pass can
        #: legitimately park the cursor AT the full-batch count (inside the
        #: ragged tail), so scan_epochs keys its max-cursor bound off this,
        #: not off the resuming loader's own flag.
        self._token_drop_last = None
        resumed = (self._resume_state or {}).get('device_inmem')
        if resumed:
            if seed is None or int(resumed['seed']) != int(seed):
                raise ValueError(
                    'device_inmem resume token was taken with seed=%r; '
                    'rebuild the loader with that explicit seed (the '
                    'permutation stream is derived from it)'
                    % (resumed['seed'],))
            self._start_epoch = int(resumed['epochs_done'])
            self._start_step = int(resumed.get('steps_into_epoch', 0))
            if resumed.get('drop_last') is not None:
                self._token_drop_last = bool(resumed['drop_last'])
            token_bs = resumed.get('batch_size')
            if self._start_step and token_bs is not None \
                    and int(token_bs) != int(batch_size):
                # Only the MID-epoch cursor counts batches of a particular
                # size; an epoch-boundary token stays batch-size-independent
                # (resuming with a different batch_size there is valid).
                raise ValueError(
                    'device_inmem resume token was taken %d steps into an '
                    'epoch of batch_size=%d batches; resume with that '
                    'batch_size (got %d), or checkpoint at an epoch '
                    'boundary to change it'
                    % (self._start_step, int(token_bs), int(batch_size)))
            if self._start_step and not self._deterministic:
                raise ValueError(
                    'mid-epoch device_inmem resume requires '
                    'deterministic_cache_order=True: the step cursor indexes '
                    'into the cached row order, which only the canonical '
                    'content-sorted cache reproduces across restarts')
            self._epochs_done = self._start_epoch
            # A state_dict() taken BEFORE the first next() must re-emit the
            # restored cursor, not an epoch-start rewind of it.
            self._steps_into_epoch = self._start_step

    def _materialize(self):
        """Build the HBM-resident epoch cache (idempotent); returns the
        device pytree or None when the dataset is empty.

        The degenerate single-entry case of the residency LRU
        (``petastorm_tpu.jax.residency``): the whole dataset is one
        "entry", admitted once via :func:`residency.place_once` and never
        evicted.  Re-entry (a new pass, a new ``scan_epochs`` call)
        revalidates the cached buffers instead of re-issuing a
        dataset-sized ``device_put`` per epoch; buffers invalidated
        underneath us (donated or deleted) raise a clear error rather
        than failing deep inside a gather."""
        from petastorm_tpu.jax import residency

        if self._dev_cache is not None:
            if residency.device_cache_valid(self._dev_cache):
                return self._dev_cache
            # The host copy was released after placement, so the cache
            # cannot be rebuilt from here.
            raise RuntimeError(
                'DeviceInMemDataLoader device cache buffers were deleted '
                '(donated or explicitly freed) after materialization; '
                'rebuild the loader to re-read the dataset')
        # Build the host cache via the parent's one-time read, then move
        # it to HBM wholesale (one transfer for the whole dataset; the
        # transfer plane coalesces it into one staging put when enabled).
        if self._build_cache() is None:
            return None
        numeric = _filter_numeric(self._cache, self._warned_fields)
        self._dev_cache = residency.place_once(
            numeric, plane=self._transfer_plane(), device=self._device)
        # The host copy is never read again — release dataset-sized RAM.
        self._cache = None
        return self._dev_cache

    def __iter__(self):
        import jax.numpy as jnp

        cache = self._materialize()
        if cache is None:
            return iter(())
        n = len(next(iter(jax.tree_util.tree_leaves(cache))))

        if self._gather_fn is None:
            batch_size = self.batch_size

            def _gather(tree, order, start):
                idx = jax.lax.dynamic_slice_in_dim(order, start, batch_size)
                return jax.tree_util.tree_map(
                    lambda v: jnp.take(v, idx, axis=0), tree)

            # One fused dispatch per step (slice + every leaf's gather in a
            # single executable) instead of 1 + n_leaves op-by-op dispatches —
            # per-step dispatch overhead is what separates this loader from
            # the pure device floor.
            self._gather_fn = jax.jit(_gather)

        def gen():
            self._epochs_done = self._start_epoch  # fresh pass
            self._steps_into_epoch = self._start_step
            skip = self._start_step  # mid-epoch baseline: first epoch only
            for order in self._epoch_orders(n):
                stop = n - self.batch_size + 1 if self._drop_last else n
                starts = list(range(0, max(stop, 0), self.batch_size))
                if skip and skip >= len(starts):
                    raise ValueError(
                        'device_inmem resume token is %d steps into an epoch '
                        'of %d steps — the dataset or batch geometry changed '
                        'since the checkpoint' % (skip, len(starts)))
                for j, start in enumerate(starts):
                    if j < skip:
                        continue
                    if start + self.batch_size <= n:
                        batch = self._gather_fn(cache, order, start)
                    else:  # ragged tail (drop_last=False): plain gather
                        idx = order[start:]
                        batch = jax.tree_util.tree_map(
                            lambda v: jnp.take(v, idx, axis=0), cache)
                    self._m_batches.inc()
                    # Account BEFORE the yield: once the consumer holds the
                    # epoch's last batch, a state_dict() taken there must
                    # read as an epoch boundary (the generator stays
                    # suspended at the yield until the next pull).
                    if j + 1 == len(starts):
                        self._steps_into_epoch = 0
                        self._epochs_done += 1
                    else:
                        self._steps_into_epoch = j + 1
                    yield batch
                skip = 0
        return gen()

    def _epoch_orders(self, n):
        """Per-epoch index order stream shared by the per-step iterator and
        ``scan_epochs`` — one place owns num_epochs/shuffle/seed semantics
        (an explicit seed reproduces, seed=None draws fresh entropy per
        loader, same as the host-RAM sibling).  Starts at
        ``self._start_epoch``: an epoch-boundary resume burns the earlier
        permutations so the continuation is exactly the uninterrupted
        stream's tail.  The baseline is static, so re-iterating the
        loader replays the same pass (fresh-entropy seeds replay THEIR
        pass; an explicit seed reproduces across processes)."""
        import jax.numpy as jnp

        seed = self._seed if self._seed is not None \
            else int(np.random.default_rng().integers(2 ** 31))
        key = jax.random.PRNGKey(seed)
        identity = None  # shuffle=False: one device array, not one per epoch
        epoch = 0
        while self._num_epochs is None or epoch < self._num_epochs:
            if self._shuffle:
                key, sub = jax.random.split(key)
                order = jax.random.permutation(sub, n)
            else:
                if identity is None:
                    identity = jnp.arange(n)
                order = identity
            if epoch >= self._start_epoch:
                yield order
            epoch += 1

    def scan_epochs(self, step_fn, carry, donate_carry=True,
                    epochs_per_call=1):
        """Consume the epochs as ONE ``lax.scan`` dispatch per
        ``epochs_per_call`` epochs.

        The per-step iterator (``__iter__``) costs two host dispatches per
        step (gather + user step); on high-latency transports (tunneled
        devices) or very fast steps that dispatch overhead IS the data
        stall.  This folds whole epochs — on-device batch gather and the
        training step — into a single jitted (nested) ``lax.scan``: zero
        host work and zero dispatch latency between steps, the idiomatic
        XLA consumption pattern for an HBM-resident epoch.  Raising
        ``epochs_per_call`` amortizes even the per-epoch dispatch
        (measured on a tunneled v5e: 1 epoch/call left ~0.25 ms/step of
        dispatch; 6 epochs/call measured indistinguishable from the pure
        device floor).

        Args:
            step_fn: ``step_fn(carry, batch) -> (carry, out)``; ``batch``
                is the same dict pytree a per-step iteration would yield
                (leading dim ``batch_size``).  Traced once, so it must be
                jittable.
            carry: initial carry pytree (params/optimizer state/...).
            donate_carry: donate the carry buffers to each call (halves
                peak param memory; the yielded carry replaces it).
            epochs_per_call: epochs folded into each dispatch.

        Yields ``(carry, outs)`` per call: ``outs`` stacks the per-step
        ``out`` along a leading ``steps_per_epoch`` axis, with an extra
        leading epochs axis when ``epochs_per_call > 1`` (a trailing
        partial group yields with its smaller epoch count — one extra
        compile).  Epoch count and shuffling follow the loader's
        ``num_epochs`` / ``shuffle`` / ``seed`` exactly like the per-step
        iterator; partial trailing batches are always dropped
        (``lax.scan`` needs static shapes).

        **Mid-epoch resume**: a loader restored from a mid-epoch token
        (taken by the per-step iterator; needs
        ``deterministic_cache_order=True`` + the same explicit ``seed``)
        finishes the partial epoch as its own first dispatch — ``outs``
        carries the remaining ``steps - start_step`` steps (one extra
        compile) — then continues in full ``epochs_per_call`` groups.  A
        token taken inside an epoch's ragged tail (every full batch
        consumed; only a ``drop_last=False`` pass parks the cursor there)
        resumes at the next epoch: scan always drops partial trailing
        batches.  Checkpoints taken *between scan yields* are epoch-group
        boundaries — ``scan_epochs`` never exposes an intra-dispatch
        cursor (the whole group is one XLA execution).

        **Shapes under** ``epochs_per_call > 1`` are uniform: EVERY yield
        carries the leading epochs axis.  Full groups are
        ``(E, steps, ...)``, a trailing partial group is the same shape
        with a smaller ``E``, and the resume-tail yield (one partial
        epoch) is ``(1, steps - start_step, ...)`` — consumers indexing
        ``outs`` by epoch need no special case.  (Earlier versions
        yielded the resume tail WITHOUT the epochs axis — ADVICE r05 #2's
        shape foot-gun.)  With ``epochs_per_call == 1`` no yield has an
        epochs axis: full epochs are ``(steps, ...)`` and the resume tail
        ``(steps - start_step, ...)``.
        """
        import itertools

        import jax.numpy as jnp
        from jax import lax

        if epochs_per_call < 1:
            raise ValueError('epochs_per_call must be >= 1')
        cache = self._materialize()
        if cache is None:
            return
        n = len(next(iter(jax.tree_util.tree_leaves(cache))))
        steps = n // self.batch_size
        if steps == 0:
            logger.warning('epoch cache holds %d rows < batch_size=%d: no '
                           'batches to scan', n, self.batch_size)
            return
        batch_size = self.batch_size

        def body_for(cache, order):
            def body(c, i):
                idx = lax.dynamic_slice_in_dim(order, i * batch_size,
                                               batch_size)
                batch = jax.tree_util.tree_map(
                    lambda v: jnp.take(v, idx, axis=0), cache)
                return step_fn(c, batch)
            return body

        def run_epoch(carry, cache, order):
            return lax.scan(body_for(cache, order), carry, jnp.arange(steps))

        def run_epochs(carry, cache, orders):  # orders: (E, n)
            return lax.scan(lambda c, order: run_epoch(c, cache, order),
                            carry, orders)

        donate = (0,) if donate_carry else ()
        fn_one = jax.jit(run_epoch, donate_argnums=donate)
        fn_many = jax.jit(run_epochs, donate_argnums=donate)

        self._epochs_done = self._start_epoch  # fresh pass
        self._steps_into_epoch = 0
        orders = self._epoch_orders(n)
        start = self._start_step
        if start:
            # Finish the token's partial epoch as its own dispatch: the
            # remaining steps of epoch 0 scan from the step cursor.  The
            # cursor counts per-step-iterator batches, which (only under
            # drop_last=False, only when a ragged tail exists) include one
            # tail batch scan would drop — a cursor AT the full-batch count
            # then means every scannable step is done and the epoch
            # completes with no dispatch.  Only a drop_last=False pass can
            # legitimately produce that cursor, so the token must RECORD
            # drop_last=False to accept it (ADVICE r05 #1): a stale/forged
            # token from a drop_last=True run — or one predating the
            # recorded flag, whose provenance cannot be verified — would
            # otherwise silently complete the epoch with zero dispatched
            # steps.  Any cursor past the geometry's legitimate maximum is
            # a changed dataset/batch shape, the same error the per-step
            # iterator raises for it.
            ragged_tail = (bool(n % self.batch_size)
                           and self._token_drop_last is False)
            max_cursor = steps if ragged_tail else steps - 1
            if start > max_cursor:
                raise ValueError(
                    'device_inmem resume token is %d steps into an epoch '
                    'of %d full batches (max legitimate cursor %d for a '
                    'token taken with drop_last=%r) — the dataset or batch '
                    'geometry changed since the checkpoint'
                    % (start, steps, max_cursor, self._token_drop_last))
            first = list(itertools.islice(orders, 1))
            if not first:
                return
            if start < steps:
                def run_epoch_tail(carry, cache, order):
                    return lax.scan(body_for(cache, order), carry,
                                    jnp.arange(start, steps))
                fn_tail = jax.jit(run_epoch_tail, donate_argnums=donate)
                carry, outs = fn_tail(carry, cache, first[0])
                if epochs_per_call > 1:
                    # Grouped consumption: EVERY yield carries the leading
                    # epochs axis, the resume tail included — it is one
                    # (partial) epoch, so shape (1, steps - start, ...).
                    # (ADVICE r05 #2: the bare tail shape was a foot-gun
                    # for consumers indexing outs by epoch.)
                    outs = jax.tree_util.tree_map(lambda x: x[None], outs)
                self._m_batches.inc(steps - start)
                self._epochs_done += 1
                yield carry, outs
            else:
                self._epochs_done += 1
        while True:
            group = list(itertools.islice(orders, epochs_per_call))
            if not group:
                return
            if epochs_per_call == 1:
                carry, outs = fn_one(carry, cache, group[0])
            else:
                # Always the (E, steps, ...) shape when grouping was
                # requested — a trailing 1-epoch group must not silently
                # drop the epochs axis consumers index by.
                carry, outs = fn_many(carry, cache, jnp.stack(group))
            self._m_batches.inc(steps * len(group))
            self._epochs_done += len(group)  # group yields ARE boundaries
            yield carry, outs

    def state_dict(self):
        """Resume token.  The permutation stream is a pure function of the
        explicit ``seed``, so ``(epochs_done, steps_into_epoch)`` fully
        determines the continuation: resume with
        ``DeviceInMemDataLoader(reader', ..., seed=same_seed,
        num_epochs=same_total, resume_state=token)`` and the remaining
        stream replays exactly.

        Exactness across a process restart also needs the rebuilt cache to
        hold the rows in the checkpointed order (the permutation indexes
        into it): at an **epoch boundary** any complete cache works (the
        continuation is a seed-exact permutation over the same row set);
        **mid-epoch** the row order itself must reproduce, so a mid-epoch
        token requires ``deterministic_cache_order=True`` — without it,
        checkpoint at a boundary or use :class:`DiskCachedDataLoader`."""
        if self._seed is None:
            raise ValueError('resume needs an explicit seed= (the device '
                             'permutation stream must be re-derivable '
                             'after restart)')
        if self._steps_into_epoch and not self._deterministic:
            raise ValueError(
                'mid-epoch checkpoint (%d steps into the current epoch) '
                'needs deterministic_cache_order=True — the step cursor '
                'indexes into the cached row order, which a pool-ordered '
                'rebuild does not reproduce; consume the epoch, rebuild '
                'with deterministic_cache_order=True, or use '
                'DiskCachedDataLoader' % self._steps_into_epoch)
        return {'version': 1,
                'device_inmem': {'epochs_done': int(self._epochs_done),
                                 'steps_into_epoch':
                                     int(self._steps_into_epoch),
                                 'batch_size': int(self.batch_size),
                                 'drop_last': bool(self._drop_last),
                                 'seed': int(self._seed)}}


class ResidentDataLoader(InMemDataLoader):
    """Device-resident data plane: a compressed-in-HBM tier with an
    epoch-keyed on-device shuffle and a multi-epoch residency LRU
    (``petastorm_tpu.jax.residency``).

    Sits beyond :class:`DeviceInMemDataLoader` on the tier ladder: batches
    live on device in the transfer plane's narrowed **wire** dtypes (uint8
    stays uint8, float32 rides as bfloat16 under ``wire_dtypes='auto'``)
    and are widened inside the jitted gather, so HBM holds roughly 2-4x
    more samples than the full-width device cache.  Epoch 0 streams
    through a :class:`~petastorm_tpu.jax.transfer.DispatchPump` and admits
    each delivered batch into the :class:`~petastorm_tpu.jax.residency.
    ResidencyTier`; once every row is resident, warm epochs are served by
    a single jitted gather+widen per step and fetch **zero** host batches.

    Determinism contract: every epoch's order is
    ``epoch_permutation(seed, epoch, n)`` — a pure function of the pair,
    not of traversal history — so a resident epoch is bit-identical to
    the equivalent streamed epoch (both deliver ``widen(narrow(rows))``),
    and dropping the tier mid-epoch (:meth:`drop_resident_tier`) falls
    back to streaming with an unchanged delivery digest.

    Degrades to full-width streaming (no narrowing, no residency) under
    ``PETASTORM_TPU_NO_RESIDENCY=1`` or when any field's dtype is outside
    the wire support matrix; a ``hbm_budget_bytes`` too small for the
    dataset keeps streaming every epoch (the LRU churns, visible as
    ``residency_thrash``) rather than failing.  Unlike
    :class:`DeviceInMemDataLoader` the host cache is **retained**, so the
    fallbacks always have rows to stream from.
    """

    def __init__(self, reader, batch_size, num_epochs=1, shuffle=True,
                 seed=None, device=None, wire_dtypes='auto',
                 hbm_budget_bytes=None, **kwargs):
        from petastorm_tpu.jax import residency

        for unsupported in ('transform_fn', 'shuffling_queue_capacity'):
            if kwargs.get(unsupported):
                # Same contract as DeviceInMemDataLoader: warm batches
                # never exist on the host, so host-side hooks cannot run.
                raise ValueError('ResidentDataLoader does not support %s'
                                 % unsupported)
        super(ResidentDataLoader, self).__init__(
            reader, batch_size, num_epochs=num_epochs, shuffle=shuffle,
            seed=seed, device=device, wire_dtypes=wire_dtypes, **kwargs)
        if self._sharding is not None:
            raise ValueError('ResidentDataLoader caches on one device; use '
                             'InMemDataLoader with sharding= for global '
                             'batch assembly')
        self._budget = hbm_budget_bytes
        self._tier = None
        self._plan = None
        self._identity_order = None
        #: Full counter shape exists from construction — stats rollups see
        #: every residency_* counter at 0 even when the plane is off.
        self._res_counters = residency.ensure_counters(self.metrics)
        #: Resolved at first iteration; fixed per loader so re-iterating
        #: replays the same epoch-order stream.
        self._res_seed = None
        self._steps_into_epoch = 0
        self._start_epoch = 0
        self._start_step = 0
        self._epochs_done = 0
        resumed = (self._resume_state or {}).get('resident')
        if resumed:
            if seed is None or int(resumed['seed']) != int(seed):
                raise ValueError(
                    'resident resume token was taken with seed=%r; rebuild '
                    'the loader with that explicit seed (every epoch order '
                    'is derived from (seed, epoch))' % (resumed['seed'],))
            self._start_epoch = int(resumed['epochs_done'])
            self._start_step = int(resumed.get('steps_into_epoch', 0))
            token_bs = resumed.get('batch_size')
            if self._start_step and token_bs is not None \
                    and int(token_bs) != int(batch_size):
                raise ValueError(
                    'resident resume token was taken %d steps into an epoch '
                    'of batch_size=%d batches; resume with that batch_size '
                    '(got %d), or checkpoint at an epoch boundary to change '
                    'it' % (self._start_step, int(token_bs), int(batch_size)))
            if self._start_step and not self._deterministic:
                raise ValueError(
                    'mid-epoch resident resume requires '
                    'deterministic_cache_order=True: the step cursor indexes '
                    'into the cached row order, which only the canonical '
                    'content-sorted cache reproduces across restarts')
            self._epochs_done = self._start_epoch
            self._steps_into_epoch = self._start_step

    @property
    def residency_stats(self):
        """Counter snapshot — full shape regardless of plane state."""
        c = self._res_counters
        return {'admitted': int(c.admitted.value),
                'evictions': int(c.evictions.value),
                'hits': int(c.hits.value),
                'bypass': int(c.bypass.value),
                'thrash': int(c.thrash.value),
                'host_batches': int(c.host_batches.value)}

    def drop_resident_tier(self):
        """Release the resident tier now (e.g. to reclaim HBM for a model
        that grew).  Safe mid-epoch: the remaining batches of the pass
        stream from the retained host cache with identical delivered
        values, so the delivery digest is unchanged."""
        if self._tier is not None:
            self._tier.drop()

    def _epoch_order(self, epoch, n):
        from petastorm_tpu.jax import residency
        import jax.numpy as jnp

        if not self._shuffle:
            if self._identity_order is None \
                    or len(self._identity_order) != n:
                self._identity_order = jnp.arange(n)
            return self._identity_order
        return residency.epoch_permutation(self._res_seed, epoch, n)

    def __iter__(self):
        from petastorm_tpu.jax import residency

        if self._build_cache() is None:
            return iter(())
        numeric = _filter_numeric(self._cache, self._warned_fields)
        leaves = jax.tree_util.tree_leaves(numeric)
        if not leaves:
            return iter(())
        n = len(leaves[0])
        if self._drop_last and n < self.batch_size:
            logger.warning('epoch cache holds %d rows < batch_size=%d with '
                           'drop_last: no batches to serve', n,
                           self.batch_size)
            return iter(())
        # Wire narrowing is TRANSFER-plane behavior (pre-residency
        # streaming already delivered widen(narrow(rows)) under 'auto'),
        # so the kill switch disables only the resident tier: a killed
        # loader must reproduce the pre-residency delivery exactly,
        # lossy wire dtypes included.
        plan = residency.wire_plan(numeric, self._wire_dtypes)
        tier = None
        if plan is not None and not residency.killed():
            if self._tier is None:
                self._tier = residency.ResidencyTier(
                    plan, n, self.batch_size, self._budget,
                    self._res_counters, device=self._device)
            tier = self._tier
        self._plan = plan
        if self._res_seed is None:
            self._res_seed = self._seed if self._seed is not None \
                else int(np.random.default_rng().integers(2 ** 31))
        return self._gen(numeric, n, plan, tier)

    def _gen(self, cache, n, plan, tier):
        self._epochs_done = self._start_epoch  # fresh pass
        self._steps_into_epoch = self._start_step
        skip = self._start_step  # mid-epoch baseline: first epoch only
        epoch = self._start_epoch
        while self._num_epochs is None or epoch < self._num_epochs:
            order_dev = self._epoch_order(epoch, n)
            stop = n - self.batch_size + 1 if self._drop_last else n
            starts = list(range(0, max(stop, 0), self.batch_size))
            if skip and skip >= len(starts):
                raise ValueError(
                    'resident resume token is %d steps into an epoch of %d '
                    'steps — the dataset or batch geometry changed since '
                    'the checkpoint' % (skip, len(starts)))
            if tier is not None and tier.serving_ok():
                batches = self._resident_epoch(cache, n, plan, tier,
                                               order_dev, starts, skip)
            else:
                batches = self._streamed_epoch(cache, n, plan, tier,
                                               order_dev, starts, skip)
            for j, batch in batches:
                self._m_batches.inc()
                # Account BEFORE the yield (same contract as
                # DeviceInMemDataLoader): a state_dict() taken while the
                # consumer holds the epoch's last batch reads as an epoch
                # boundary.
                if j + 1 == len(starts):
                    self._steps_into_epoch = 0
                    self._epochs_done += 1
                else:
                    self._steps_into_epoch = j + 1
                yield batch
            if tier is not None and not tier.fully_resident:
                # drop_last never streams the ragged tail and a resume
                # never re-streams skipped batches; admit the leftovers
                # directly so the next epoch can serve warm.
                tier.backfill(cache, plan)
            skip = 0
            epoch += 1

    def _put_wire(self, wire):
        if self._device is not None:
            return {k: jax.device_put(v, self._device)
                    for k, v in wire.items()}
        return {k: jax.device_put(v) for k, v in wire.items()}

    def _stream_one(self, cache, n, plan, idx):
        """Slice, narrow, place, widen one batch — the streamed delivery.
        Identical values to a warm gather over the same rows: both
        deliver ``widen(narrow(rows))``."""
        t0 = time.monotonic()
        host_rows = {name: np.asarray(v)[idx] for name, v in cache.items()}
        wire = plan.narrow(host_rows) if plan is not None else host_rows
        t1 = time.monotonic()
        wire_dev = self._put_wire(wire)
        batch = plan.widen(wire_dev) if plan is not None else wire_dev
        t2 = time.monotonic()
        self._observe('host_batch', t0, t1)
        self._observe('device_put', t1, t2)
        self._res_counters.host_batches.inc()
        return wire_dev, batch, [t0, t1], [t1, t2]

    def _streamed_epoch(self, cache, n, plan, tier, order_dev, starts, skip):
        """One epoch through the dispatch ring: a DispatchPump background
        thread slices/narrows/places while the consumer steps, and each
        delivered batch is admitted into the tier."""
        from petastorm_tpu.jax.transfer import _DONE, DispatchPump

        order_np = np.asarray(order_dev)
        bs = self.batch_size

        def source():
            for j, start in enumerate(starts):
                if j < skip:
                    continue
                yield j, order_np[start:min(start + bs, n)]

        def ship(item):
            j, idx = item
            wire_dev, batch, w_host, w_put = self._stream_one(
                cache, n, plan, idx)
            outcome = tier.admit(idx, wire_dev) if tier is not None \
                else 'bypass'
            if self.provenance is not None:
                self._seal_provenance({'host_batch': w_host,
                                       'h2d_dispatch': w_put},
                                      residency=outcome)
            return j, batch

        pump = DispatchPump(source(), ship, self._prefetch)
        self._pump = pump
        pump.start()
        try:
            while True:
                item = pump.get()
                if item is _DONE:
                    return
                yield item
        finally:
            pump.stop(join_timeout_s=0.2)

    def _resident_epoch(self, cache, n, plan, tier, order_dev, starts, skip):
        """One warm epoch: jitted gather+widen per step, zero host batches.
        If the tier is dropped mid-epoch, the remaining steps stream from
        the retained host cache — same values, digest intact."""
        order_np = None
        bs = self.batch_size
        for j, start in enumerate(starts):
            if j < skip:
                continue
            if tier.serving_ok():
                if start + bs <= n:
                    batch = tier.gather(order_dev, start)
                else:  # ragged tail (drop_last=False)
                    batch = tier.gather_tail(order_dev, start)
                outcome = 'hit'
            else:
                if order_np is None:
                    order_np = np.asarray(order_dev)
                idx = order_np[start:min(start + bs, n)]
                _, batch, _, _ = self._stream_one(cache, n, plan, idx)
                outcome = 'bypass'
                self._res_counters.bypass.inc()
            if self.provenance is not None:
                self._seal_provenance({}, residency=outcome)
            yield j, batch

    def state_dict(self):
        """Resume token.  Epoch orders are ``epoch_permutation(seed,
        epoch, n)`` — pure functions of the pair — so ``(epochs_done,
        steps_into_epoch)`` fully determines the continuation; resume
        with the same explicit ``seed`` and the remaining stream replays
        exactly (the tier rebuilds by streaming, values unchanged).
        Mid-epoch exactness across restarts additionally needs
        ``deterministic_cache_order=True``, same as the device-cache
        sibling."""
        if self._seed is None:
            raise ValueError('resume needs an explicit seed= (epoch orders '
                             'must be re-derivable after restart)')
        if self._steps_into_epoch and not self._deterministic:
            raise ValueError(
                'mid-epoch checkpoint (%d steps into the current epoch) '
                'needs deterministic_cache_order=True — the step cursor '
                'indexes into the cached row order, which a pool-ordered '
                'rebuild does not reproduce' % self._steps_into_epoch)
        return {'version': 1,
                'resident': {'epochs_done': int(self._epochs_done),
                             'steps_into_epoch': int(self._steps_into_epoch),
                             'batch_size': int(self.batch_size),
                             'drop_last': bool(self._drop_last),
                             'seed': int(self._seed)}}


class DiskCachedDataLoader(DataLoader):
    """Decoded-tensor disk cache tier: decode once, stream every later
    epoch from local disk at memory bandwidth.

    Fills the gap between :class:`DataLoader` (re-decode every epoch) and
    :class:`DeviceInMemDataLoader` (whole decoded epoch in HBM): epoch 0
    runs the normal decode path, serves its batches, AND appends every row
    to per-field row-major binary files under ``decoded_cache_dir``; every
    subsequent epoch memory-maps those files and serves (optionally
    reshuffled) batches with zero parquet/codec work — multi-epoch training
    over datasets far larger than HBM bypasses JPEG after the first pass.

    The reference's ``LocalDiskCache`` caches ENCODED row-group results
    (``petastorm/local_disk_arrow_table_cache.py``-style); a TPU-first
    pipeline caches POST-decode, because decode (not IO) is what a 1-core
    host cannot do at chip speed.  Layout matches the native decode plane's
    output: one contiguous ``[rows, *field_shape]`` buffer per field.

    Rules:

    * Construct the reader with ``num_epochs=1``; epoch repetition happens
      here (``num_epochs=None`` = forever).
    * Only fixed-shape numeric fields are cached (object/string leaves are
      dropped with the same warning as device transfer).
    * ``decoded_cache_dir`` identifies the DATASET (+ predicate/transform
      pipeline): point each distinct dataset/shard at its own directory.
      Multi-host: use per-host local paths — each host caches its shard.
    * A cache directory is reused only when its ``_COMPLETE`` marker
      exists; a partial build (crash mid-epoch-0) is re-built from scratch.
    * ``transform_fn`` still runs per served batch (cache holds
      pre-transform tensors, so random augmentation stays fresh per epoch).
    """

    _MANIFEST = 'manifest.json'
    _COMPLETE = '_COMPLETE'

    def __init__(self, reader, batch_size, decoded_cache_dir, num_epochs=1,
                 shuffle=True, seed=None, **kwargs):
        if kwargs.get('shuffling_queue_capacity'):
            raise ValueError('DiskCachedDataLoader shuffles via per-epoch '
                             'permutation; shuffling_queue_capacity is not '
                             'supported')
        if reader is not None:
            if getattr(reader, 'ngram', None) is not None:
                raise ValueError('DiskCachedDataLoader does not support '
                                 'NGram readers (windows are not '
                                 'fixed-shape rows)')
            reader_epochs = getattr(reader, 'num_epochs', 1)
            if reader_epochs != 1:
                raise ValueError(
                    'DiskCachedDataLoader requires a reader built with '
                    'num_epochs=1 (got num_epochs=%r); epoch repetition '
                    'happens in the loader' % (reader_epochs,))
        # ``reader=None`` serves a COMPLETE cache without touching parquet
        # at all (no worker pool decoding in the background — e.g. while a
        # training step loop is being timed).
        super(DiskCachedDataLoader, self).__init__(
            reader, batch_size, seed=seed, **kwargs)
        self._cache_dir = decoded_cache_dir
        self._num_epochs = num_epochs
        self._shuffle = shuffle

    # -- cache files ---------------------------------------------------------

    @classmethod
    def cache_complete(cls, decoded_cache_dir):
        """True when ``decoded_cache_dir`` holds a finished cache — i.e.
        a loader over it may be built with ``reader=None`` (no parquet or
        decode work at all).  Public so callers share the loader's own
        completeness rule instead of hardcoding marker names."""
        import os
        return os.path.exists(os.path.join(decoded_cache_dir, cls._COMPLETE))

    def _cache_complete(self):
        return self.cache_complete(self._cache_dir)

    def _manifest(self):
        import json
        import os
        with open(os.path.join(self._cache_dir, self._MANIFEST)) as f:
            return json.load(f)

    def _open_cache(self):
        """mmap every field buffer; returns ``(fields_dict, n_rows)``."""
        import os
        man = self._manifest()
        fields = {
            name: np.memmap(os.path.join(self._cache_dir, spec['file']),
                            dtype=np.dtype(spec['dtype']), mode='r',
                            shape=tuple([man['rows']] + spec['shape']))
            for name, spec in man['fields'].items()}
        return fields, man['rows']

    def _build_and_serve_epoch0(self):
        """Epoch 0: serve decoded batches while spilling rows to disk."""
        import json
        import os
        import shutil

        if os.path.isdir(self._cache_dir):
            # stale partial build (no _COMPLETE marker): start clean
            shutil.rmtree(self._cache_dir)
        os.makedirs(self._cache_dir)
        sinks = {}
        specs = {}
        rows = 0
        drop_last = self._drop_last
        self._drop_last = False     # the cache must hold EVERY row
        try:
            for batch in super(DiskCachedDataLoader, self)._host_batches():
                batch = _filter_numeric(batch, self._warned_fields)
                for name, value in batch.items():
                    value = np.ascontiguousarray(value)
                    if name not in sinks:
                        specs[name] = {'file': '%s.bin' % name,
                                       'dtype': value.dtype.str,
                                       'shape': list(value.shape[1:])}
                        sinks[name] = open(
                            os.path.join(self._cache_dir, specs[name]['file']),
                            'wb')
                    elif list(value.shape[1:]) != specs[name]['shape']:
                        raise ValueError(
                            'field %r changed shape %r -> %r; the decoded '
                            'cache requires fixed-shape fields'
                            % (name, specs[name]['shape'],
                               list(value.shape[1:])))
                    sinks[name].write(memoryview(value))
                n = len(next(iter(batch.values())))
                rows += n
                if n == self.batch_size or not drop_last:
                    yield batch
        finally:
            self._drop_last = drop_last
            for sink in sinks.values():
                sink.close()
        with open(os.path.join(self._cache_dir, self._MANIFEST), 'w') as f:
            json.dump({'version': 1, 'rows': rows, 'fields': specs}, f)
        # the marker is the atomicity boundary: no marker -> rebuild
        tmp = os.path.join(self._cache_dir, self._COMPLETE + '.tmp')
        with open(tmp, 'w') as f:
            f.write('%d rows\n' % rows)
        os.replace(tmp, os.path.join(self._cache_dir, self._COMPLETE))

    # -- epochs --------------------------------------------------------------

    def _host_batches(self):
        epochs_served = 0
        resumed = (self._resume_state or {}).get('disk_cache')
        if not self._cache_complete():
            if resumed:
                raise ValueError('resume_state requires the decoded cache '
                                 'to be complete; the epoch-0 build was '
                                 'interrupted — rebuild from scratch')
            if self.reader is None:
                raise ValueError('reader=None serves a COMPLETE cache only; '
                                 '%r has no _COMPLETE marker'
                                 % (self._cache_dir,))
            yield from self._build_and_serve_epoch0()
            epochs_served = 1
            if self._num_epochs is not None \
                    and epochs_served >= self._num_epochs:
                return
        fields, n = self._open_cache()
        if n == 0:
            return
        if self._drop_last and n < self.batch_size:
            # num_epochs=None would otherwise spin forever yielding nothing
            logger.warning('decoded cache holds %d rows < batch_size=%d with '
                           'drop_last: no batches to serve', n, self.batch_size)
            return
        rng = np.random.default_rng(self._seed)
        epoch = epochs_served
        order = None
        offset = 0
        if resumed:
            rng.bit_generator.state = resumed['rng_state']
            epoch = int(resumed['epoch'])
            offset = int(resumed['offset'])
            order = (None if resumed['order'] is None
                     else np.asarray(resumed['order']))
        self._dc = {'rng': rng, 'epoch': epoch, 'order': order,
                    'offset': offset}
        while self._num_epochs is None or epoch < self._num_epochs:
            if order is None:
                order = rng.permutation(n) if self._shuffle else np.arange(n)
            stop = n - self.batch_size + 1 if self._drop_last else n
            for start in range(offset, max(stop, 0), self.batch_size):
                self._dc.update(epoch=epoch, order=order,
                                offset=start + self.batch_size)
                idx = order[start:start + self.batch_size]
                # fancy-indexing a memmap materializes just this batch —
                # the per-step host cost is one batch-sized memcpy
                yield {name: np.asarray(buf[idx])
                       for name, buf in fields.items()}
            epoch += 1
            order = None
            offset = 0
            self._dc.update(epoch=epoch, order=None, offset=0)

    def state_dict(self):
        """Exact resume token over the complete cache: (epoch, offset,
        epoch order, rng state) + prefetched batches.  The on-disk cache IS
        the persisted row order, so restoration is exact regardless of the
        original reader's pool type."""
        if getattr(self, '_dc', None) is None:
            raise ValueError(
                'state_dict() is supported once the decoded cache is '
                'complete (from epoch 1 on); during the epoch-0 build, '
                'checkpoint at the epoch boundary instead')
        with self._pump_paused():
            return self._disk_cache_state()

    def _disk_cache_state(self):
        dc = self._dc
        return {
            'version': 1,
            'pending': [jax.device_get(b) for b in self._pending],
            'disk_cache': {
                'rng_state': dc['rng'].bit_generator.state,
                'epoch': int(dc['epoch']),
                'offset': int(dc['offset']),
                'order': (None if dc['order'] is None
                          else np.asarray(dc['order'])),
            },
        }


class PackedDataLoader(DataLoader):
    """Pack a variable-length sequence column into fixed-shape LM batches
    with the DataLoader's prefetch/device delivery.

    The loader-layer home of ``petastorm_tpu.jax.packing.pack_stream``:
    rows stream out of the reader, their ``tokens_field`` column is packed
    into ``(rows_per_batch, max_len)`` batches with ``segment_ids`` /
    ``positions``, and batches ride the same double-buffered
    ``device_put`` path as :class:`DataLoader` (``prefetch`` /
    ``device`` / ``sharding`` / ``transform_fn`` all apply)::

        with make_reader(url, schema_fields=['tokens']) as reader:
            loader = PackedDataLoader(reader, 'tokens', max_len=4096,
                                      rows_per_batch=8, sharding=sharding)
            for batch in loader:
                step(batch['tokens'], batch['segment_ids'],
                     batch['positions'])

    Ordering comes from the reader (shuffle row groups there);
    ``shuffling_queue_capacity`` is rejected — reordering between packing
    and delivery would break nothing but adds no mixing the reader can't
    already provide.  With ``drop_last=False`` the final short batch is
    padded with all-padding rows (static shapes), not ragged.
    """

    def __init__(self, reader, tokens_field, max_len, rows_per_batch,
                 pad_id=0, open_rows=32, **loader_kwargs):
        if loader_kwargs.get('shuffling_queue_capacity'):
            raise ValueError('PackedDataLoader does not support '
                             'shuffling_queue_capacity; shuffle in the '
                             'reader (shuffle_row_groups)')
        if getattr(reader, 'batched_output', False):
            raise ValueError('PackedDataLoader needs a ROW reader '
                             '(make_reader): batch readers yield columnar '
                             'chunks, not per-document sequences')
        super().__init__(reader, batch_size=rows_per_batch, **loader_kwargs)
        self._tokens_field = tokens_field
        self._max_len = int(max_len)
        self._pad_id = pad_id
        self._open_rows = int(open_rows)
        self._packer = None

    def _host_batches(self):
        from petastorm_tpu.jax.packing import StreamPacker

        packer = StreamPacker(self._max_len, self.batch_size,
                              pad_id=self._pad_id, open_rows=self._open_rows,
                              drop_last=self._drop_last)
        if self._resume_state and self._resume_state.get('packer'):
            packer.load_state_dict(self._resume_state['packer'])
        self._packer = packer
        # Ready-but-unyielded batches stage here so a state_dict() taken
        # between two yields of the same add() loses nothing.
        self._packed_ready = list((self._resume_state or {})
                                  .get('packed_ready', []))
        for row in self._row_source():
            value = (row[self._tokens_field] if isinstance(row, dict)
                     else getattr(row, self._tokens_field))
            self._packed_ready.extend(packer.add(value))
            while self._packed_ready:
                yield self._packed_ready.pop(0)
        self._packed_ready.extend(packer.flush())
        while self._packed_ready:
            yield self._packed_ready.pop(0)

    def state_dict(self):
        """Exact packed snapshot: DataLoader state + the packer residue
        (open rows, closed rows, sticky dtype) + ready-but-unyielded
        batches.

        The pump stays paused across BOTH reads (the base snapshot and
        the packer residue): ``_pump_paused`` counts, so the nested
        pause inside ``super().state_dict()`` composes — resuming
        between the two would let the dispatch thread pack
        just-snapshotted pushback rows into the packer and duplicate
        them in the token."""
        with self._pump_paused():
            state = super().state_dict()
            rs = self._resume_state or {}
            if self._packer is not None:   # iteration started
                state['packer'] = self._packer.state_dict()
                state['packed_ready'] = list(self._packed_ready)
            else:                          # restored but not yet iterated
                state['packer'] = rs.get('packer')
                state['packed_ready'] = list(rs.get('packed_ready', []))
            return state


def make_jax_loader(dataset_url, batch_size, batched=True, loader_kwargs=None, **reader_kwargs):
    """Convenience: reader + DataLoader in one call.

    ``batched=True`` uses the columnar ``make_batch_reader`` path (fastest);
    ``False`` uses ``make_reader`` with codec decoding.
    """
    from petastorm_tpu.reader import make_batch_reader, make_reader
    factory = make_batch_reader if batched else make_reader
    reader = factory(dataset_url, **reader_kwargs)
    return DataLoader(reader, batch_size, **(loader_kwargs or {}))
