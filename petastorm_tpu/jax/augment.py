"""Device-side image augmentation: jitted, keyed-RNG, static-shape ops.

TPU-first rationale: the reference pushes all preprocessing into host worker
pools (reference analog: ``petastorm/transform.py :: TransformSpec`` — the
only augmentation hook it has), which is the right place for *decode* but
the wrong place for *augmentation* on a TPU host: the host core budget is
the pipeline bottleneck (see ``docs/performance.md``), while random crops /
flips / color jitter are trivially cheap, bandwidth-bound elementwise work
for the chip and fuse into the first convolution under XLA.  Every op here:

* takes a ``jax.random`` key first — pure, reproducible, vmap/pjit-safe;
* is static-shape (per-sample crops use clamped ``dynamic_slice``, never
  data-dependent shapes), so nothing recompiles step to step;
* consumes the loader's uint8 NHWC batches directly (transfer stays 4x
  cheaper than f32; normalization happens on-device at the end).

Typical wiring — augment INSIDE the jitted train step, downstream of the
``DataLoader``::

    @jax.jit
    def train_step(params, ..., images_u8, labels, key):
        k1, k2, k3 = jax.random.split(key, 3)
        x = augment.random_crop(k1, images_u8, (224, 224), padding=8)
        x = augment.random_flip_left_right(k2, x)
        x = augment.normalize(x, IMAGENET_MEAN, IMAGENET_STD)   # -> bf16
        x, la, lb, lam = augment.mixup(k3, x, labels, alpha=0.2)
        ...

Under a data-parallel mesh the batch axis is sharded; the per-sample ops
(crop, flip, color, cutout, normalize) partition with zero collectives.
:func:`mixup` and :func:`cutmix` combine each sample with a *shuffled
partner*, so with a sharded batch axis XLA realizes ``x[perm]`` with a
cross-device gather — cheap relative to a train step, but not free; apply
them per-host (e.g. in the loader's ``transform_fn``) if ICI budget is
tight.
"""

import jax
import jax.numpy as jnp

__all__ = [
    'IMAGENET_MEAN', 'IMAGENET_STD',
    'normalize', 'center_crop', 'random_crop', 'random_flip_left_right',
    'random_brightness', 'random_contrast', 'random_saturation',
    'color_jitter', 'random_cutout', 'mixup', 'cutmix', 'mixup_loss',
]

#: ImageNet channel statistics in 0..255 scale (match torchvision's
#: 0..1-scale constants times 255).
IMAGENET_MEAN = (123.675, 116.28, 103.53)
IMAGENET_STD = (58.395, 57.12, 57.375)


def _as_float(images):
    """uint8 -> f32 in 0..255; float inputs pass through unchanged."""
    if jnp.issubdtype(images.dtype, jnp.integer):
        return images.astype(jnp.float32)
    return images


def normalize(images, mean=IMAGENET_MEAN, std=IMAGENET_STD,
              dtype=jnp.bfloat16):
    """Channel-wise ``(x - mean) / std`` -> ``dtype`` (default bf16 for MXU).

    ``mean``/``std`` are in the same scale as the input (0..255 for the
    loader's uint8 batches).
    """
    x = _as_float(images)
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    return ((x - mean) / std).astype(dtype)


def center_crop(images, crop_hw):
    """Static center crop of NHWC ``images`` to ``crop_hw = (ch, cw)``."""
    ch, cw = crop_hw
    h, w = images.shape[1], images.shape[2]
    if ch > h or cw > w:
        raise ValueError('crop %r larger than image %r' % (crop_hw, (h, w)))
    top, left = (h - ch) // 2, (w - cw) // 2
    return images[:, top:top + ch, left:left + cw, :]


def random_crop(key, images, crop_hw, padding=0):
    """Per-sample random crop (optionally zero-padding first).

    ``images``: NHWC.  With ``padding=p`` the image is zero-padded by ``p``
    on each spatial side before cropping (the CIFAR/ImageNet-style "pad and
    crop" augmentation).  Crop offsets are uniform per sample; shapes stay
    static (``dynamic_slice`` with clamped starts).
    """
    ch, cw = crop_hw
    if padding:
        images = jnp.pad(
            images, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    n, h, w, c = images.shape
    if ch > h or cw > w:
        raise ValueError('crop %r larger than padded image %r'
                         % (crop_hw, (h, w)))
    kt, kl = jax.random.split(key)
    tops = jax.random.randint(kt, (n,), 0, h - ch + 1)
    lefts = jax.random.randint(kl, (n,), 0, w - cw + 1)

    def crop_one(img, top, left):
        return jax.lax.dynamic_slice(img, (top, left, 0), (ch, cw, c))

    return jax.vmap(crop_one)(images, tops, lefts)


def random_flip_left_right(key, images, prob=0.5):
    """Per-sample horizontal flip with probability ``prob``."""
    n = images.shape[0]
    flip = jax.random.bernoulli(key, prob, (n,))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


def random_brightness(key, images, max_delta=0.125):
    """Additive brightness jitter: ``x + u*255``, ``u ~ U(-d, d)`` per sample.

    Output is f32 in 0..255 scale (clipped); feed to :func:`normalize` last.
    """
    x = _as_float(images)
    n = x.shape[0]
    delta = jax.random.uniform(key, (n, 1, 1, 1), minval=-max_delta,
                               maxval=max_delta) * 255.0
    return jnp.clip(x + delta, 0.0, 255.0)


def random_contrast(key, images, lower=0.8, upper=1.2):
    """Per-sample contrast: ``(x - mean_sample) * f + mean_sample``."""
    x = _as_float(images)
    n = x.shape[0]
    f = jax.random.uniform(key, (n, 1, 1, 1), minval=lower, maxval=upper)
    mean = jnp.mean(x, axis=(1, 2, 3), keepdims=True)
    return jnp.clip((x - mean) * f + mean, 0.0, 255.0)


def random_saturation(key, images, lower=0.8, upper=1.2):
    """Per-sample saturation: blend with the grayscale (Rec.601) image."""
    x = _as_float(images)
    n = x.shape[0]
    f = jax.random.uniform(key, (n, 1, 1, 1), minval=lower, maxval=upper)
    gray = (0.299 * x[..., 0:1] + 0.587 * x[..., 1:2] + 0.114 * x[..., 2:3])
    return jnp.clip(gray + (x - gray) * f, 0.0, 255.0)


def color_jitter(key, images, brightness=0.125, contrast=0.2, saturation=0.2):
    """Brightness -> contrast -> saturation jitter (each per-sample)."""
    kb, kc, ks = jax.random.split(key, 3)
    x = random_brightness(kb, images, brightness)
    x = random_contrast(kc, x, 1.0 - contrast, 1.0 + contrast)
    return random_saturation(ks, x, 1.0 - saturation, 1.0 + saturation)


def random_cutout(key, images, size, fill=0.0):
    """Zero out one random ``size x size`` square per sample (DeVries &
    Taylor 2017).  The mask is built from broadcasted iotas — static shapes,
    squares clamp at image borders like the paper's implementation.
    """
    n, h, w, _ = images.shape
    ky, kx = jax.random.split(key)
    cy = jax.random.randint(ky, (n, 1, 1), 0, h)
    cx = jax.random.randint(kx, (n, 1, 1), 0, w)
    ys = jnp.arange(h)[None, :, None]
    xs = jnp.arange(w)[None, None, :]
    half = size // 2
    inside = ((ys >= cy - half) & (ys < cy + (size - half)) &
              (xs >= cx - half) & (xs < cx + (size - half)))
    fill = jnp.asarray(fill, images.dtype)
    return jnp.where(inside[..., None], fill, images)


def mixup(key, images, labels, alpha=0.2):
    """Batch mixup (Zhang et al. 2018): convex-combine each sample with a
    shuffled partner.

    Returns ``(mixed_images, labels_a, labels_b, lam)``; train with
    :func:`mixup_loss`.  ``lam`` is a scalar Beta(alpha, alpha) draw shared
    by the batch (the paper's formulation — keeps the op a cheap
    batch-axis-parallel lerp).
    """
    x = _as_float(images)
    k_lam, k_perm = jax.random.split(key)
    lam = jax.random.beta(k_lam, alpha, alpha)
    perm = jax.random.permutation(k_perm, x.shape[0])
    mixed = lam * x + (1.0 - lam) * x[perm]
    return mixed, labels, labels[perm], lam


def cutmix(key, images, labels, alpha=1.0):
    """CutMix (Yun et al. 2019): paste a random rectangle from a shuffled
    partner; label weight = kept-area fraction.

    Returns ``(mixed_images, labels_a, labels_b, lam)`` with ``lam`` the
    *actual* area fraction of the original image kept (recomputed after
    border clamping, as in the paper).
    """
    x = _as_float(images)
    n, h, w, _ = x.shape
    k_lam, k_perm, ky, kx = jax.random.split(key, 4)
    lam0 = jax.random.beta(k_lam, alpha, alpha)
    perm = jax.random.permutation(k_perm, n)
    ratio = jnp.sqrt(1.0 - lam0)
    cut_h = (ratio * h).astype(jnp.int32)
    cut_w = (ratio * w).astype(jnp.int32)
    cy = jax.random.randint(ky, (), 0, h)
    cx = jax.random.randint(kx, (), 0, w)
    y0 = jnp.clip(cy - cut_h // 2, 0, h)
    y1 = jnp.clip(cy + cut_h // 2, 0, h)
    x0 = jnp.clip(cx - cut_w // 2, 0, w)
    x1 = jnp.clip(cx + cut_w // 2, 0, w)
    ys = jnp.arange(h)[:, None]
    xs = jnp.arange(w)[None, :]
    inside = ((ys >= y0) & (ys < y1) & (xs >= x0) & (xs < x1))
    mixed = jnp.where(inside[None, :, :, None], x[perm], x)
    lam = 1.0 - ((y1 - y0) * (x1 - x0)) / (h * w)
    return mixed, labels, labels[perm], lam


def mixup_loss(logits, labels_a, labels_b, lam):
    """Convex cross-entropy for :func:`mixup` / :func:`cutmix` targets."""
    import optax
    la = optax.softmax_cross_entropy_with_integer_labels(logits, labels_a)
    lb = optax.softmax_cross_entropy_with_integer_labels(logits, labels_b)
    return (lam * la + (1.0 - lam) * lb).mean()
