"""Pipelined host→device transfer plane (ISSUE 6).

BENCH_TPU_LAST showed the link, not the data plane, as the frontier:
``stall_pct_streaming`` ≈ 96% while ``hbm_scan`` sits at 5.4% — once
batches are in HBM the framework is nearly stall-free, so everything
between host memory and HBM must be hidden, not paid inline.  This
module makes the transfer a first-class pipeline stage:

* **Ring-buffered staging** — a fixed ring of reused host staging slabs
  (reuse matters: first-touch page faults cost ~20x the memcpy on the
  virtualized bench kernel).  A slot is rewritten only after the batch
  it last carried is committed on device (``jax.block_until_ready`` on
  slot reuse), so with ``ring_slots`` slots up to ``ring_slots - 1``
  transfers are in flight while the step runs — batch N+1's DMA
  overlaps batch N's compute.  The device-side slab is donated into the
  unpack executable (off the CPU backend, where donation is a no-op),
  so steady-state transfer recycles buffers instead of allocating.
* **Transfer coalescing** — the many small per-column arrays of a batch
  are packed into ONE C-contiguous staging slab per step: one
  ``device_put`` instead of one per column, then a jitted on-device
  unpack slices/bitcasts the slab back into the pytree.  The win is the
  per-dispatch fixed cost (python + transport round-trip per put), which
  dominates for wide-table batches.
* **Wire-dtype narrowing** — opt-in (``wire_dtypes='auto'`` or a
  ``{field: dtype}`` map): float32/float64 leaves travel as bfloat16
  and are cast back inside the jitted unpack, halving/quartering
  bytes-on-wire.  uint8 images already travel at their natural width
  and pass through bit-exact.  Without the opt-in every leaf travels at
  its canonical width and the result is bit-identical to
  ``jax.device_put``.
* **Sharded parallel transfer** — with a ``sharding`` whose spec shards
  only the leading (batch) axis, per-device slices of the staging batch
  are dispatched concurrently (one ``device_put`` per device — the DMAs
  overlap) and reassembled with
  ``jax.make_array_from_single_device_arrays`` instead of funneling the
  whole global batch through one host-thread call.

**Degrade matrix** (the plane NEVER changes delivered values; every
fallback is the existing inline path, bit-identical):

=====================================  =====================================
condition                              behaviour
=====================================  =====================================
``PETASTORM_TPU_NO_TRANSFER_PLANE=1``  plane off (inline ``device_put``)
``transfer='auto'`` on the CPU         plane off — the "link" is a memcpy
backend                                and the staging pass buys nothing
unsupported leaf dtype (datetime64,    that batch structure degrades to the
strings already filtered upstream)     inline path (``h2d_degraded`` counts)
single already-full-width leaf         inline path (coalescing is a no-op
                                       and the staging copy isn't free)
staging slab over the cap              inline path (a slab is a second host
(``PETASTORM_TPU_TRANSFER_MAX_        copy of the batch)
STAGING_MB``, default 512)
sharding not leading-axis /            ``global_batch_from_local`` as today
multi-host
=====================================  =====================================

Telemetry (ISSUE 5 plane): every transfer records ``h2d/stage`` (host
pack), ``h2d/dispatch`` (async put + unpack dispatch) and ``h2d/commit``
(observed wait for true transfer completion: ring-slot reuse waits, plus
a periodic 1-in-32 full sample) spans into the loader's
``TraceRecorder``, and the same stages into ``h2d_stage`` /
``h2d_dispatch`` / ``h2d_commit`` histograms on the loader's metrics
registry — ``attribute_stalls`` can now split staging-copy time from
link time (components ``h2d_stage`` vs ``h2d``).
"""

import logging
import os
import threading
from petastorm_tpu.utils.locks import make_condition
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

__all__ = ['TransferPlane', 'DispatchPump', 'plane_enabled', 'KILL_SWITCH',
           'wire_dtype_for']

#: Environment kill switch: set to any non-empty value to force every
#: loader onto the inline ``device_put`` path regardless of ``transfer=``.
KILL_SWITCH = 'PETASTORM_TPU_NO_TRANSFER_PLANE'

#: Staging slabs above this bound degrade to the inline path — a slab is
#: a second host-side copy of the batch, and a whole-dataset transfer
#: (DeviceInMemDataLoader._materialize) must not double host RAM.
MAX_STAGING_BYTES = int(os.environ.get(
    'PETASTORM_TPU_TRANSFER_MAX_STAGING_MB', '512')) << 20

#: Per-field slab alignment: keeps every wire-dtype view aligned and the
#: per-device segments cache-line separated.
_ALIGN = 64

#: 1-in-N full commit sample (dispatch → device-ready wall time); ring
#: reuse additionally observes the *residual* commit wait on every slot.
_COMMIT_SAMPLE_EVERY = 32

_BF16 = np.dtype(jnp.bfloat16)


#: Accepted ``transfer=`` values — ONE place, validated both at loader
#: construction (fail fast) and in :func:`plane_enabled` (direct users).
_TRANSFER_MODES = (True, False, None, 'auto')


def validate_transfer(transfer):
    """Strict on purpose: 'off'/'false'/'disabled' from a config parse
    are truthy and would silently ENABLE the plane under a
    fall-through-to-auto reading."""
    if transfer not in _TRANSFER_MODES:
        raise ValueError("transfer must be True, False, None, or 'auto' "
                         '(got %r)' % (transfer,))


def plane_enabled(transfer):
    """Resolve a loader's ``transfer=`` kwarg against the environment.

    ``False``/``None`` → off; ``True`` → on (tests force the plane on the
    CPU backend this way); ``'auto'`` → on only when an accelerator
    backend is live — on the CPU fallback the "link" is a memcpy and the
    extra staging pass buys nothing (measured: bench.py
    ``transfer_plane`` leg).  The kill switch wins over everything.
    """
    validate_transfer(transfer)
    if os.environ.get(KILL_SWITCH):
        return False
    if transfer is True:
        return True
    if not transfer:
        return False
    try:
        return jax.default_backend() != 'cpu'
    except Exception:  # noqa: BLE001 — no backend at all: nothing to feed
        return False


def _supported(dtype):
    """Wire-packable dtypes: fixed-width bool/int/uint/float (bfloat16
    included).  datetime64/timedelta64/object/str degrade."""
    return dtype.kind in 'biuf' or dtype == _BF16


def _leaf_name(path):
    """Last path component name ('image' from "['image']") — the key the
    ``wire_dtypes`` dict matches on."""
    last = path[-1]
    key = getattr(last, 'key', None)
    if key is None:
        key = getattr(last, 'name', None)
    if key is None:
        key = getattr(last, 'idx', None)
    return str(key)


def _resolve_wire(name, out_dtype, policy):
    """Wire dtype for one leaf: the canonical dtype unchanged (exact), or
    the policy's narrowed dtype.  ``'auto'`` narrows >=32-bit floats to
    bfloat16; a dict names fields explicitly (absent fields stay exact).
    """
    if not policy:
        return out_dtype
    if policy == 'auto':
        if out_dtype.kind == 'f' and out_dtype.itemsize >= 4:
            return _BF16
        return out_dtype
    want = policy.get(name)
    return np.dtype(want) if want is not None else out_dtype


def wire_dtype_for(name, out_dtype, policy):
    """Public form of the wire-narrowing rule for one named leaf.

    The residency tier (``petastorm_tpu.jax.residency``) stores batches
    on device in exactly these wire dtypes, so the compressed-in-HBM
    budget math and the H2D link both follow one policy.
    """
    return _resolve_wire(name, np.dtype(out_dtype), policy)


class _Unsupported(Exception):
    """This batch structure cannot ride the plane; fall back inline."""


class _Field(object):
    __slots__ = ('offset', 'nbytes', 'wire', 'out', 'shape')

    def __init__(self, offset, nbytes, wire, out, shape):
        self.offset = offset
        self.nbytes = nbytes
        self.wire = wire
        self.out = out
        self.shape = shape


def _align(n):
    return -(-n // _ALIGN) * _ALIGN


def _signature(tree):
    """Cheap per-batch structure key: path + shape + source dtype per
    leaf.  Layouts, unpack executables and shard plans cache under it."""
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return tuple((jax.tree_util.keystr(path), np.asarray(leaf).shape,
                  np.asarray(leaf).dtype.str) for path, leaf in paths)


class _Layout(object):
    """Static packing plan for one batch structure: per-leaf slab offset,
    wire dtype (narrowed or canonical) and on-device output dtype.  The
    output dtype is ``jax.dtypes.canonicalize_dtype`` of the source —
    exactly what ``jax.device_put`` itself would deliver (int64 → int32
    under default x64-disabled JAX), so the no-narrowing plane output is
    bit-identical to the inline path."""

    def __init__(self, tree, policy):
        paths, self.treedef = jax.tree_util.tree_flatten_with_path(tree)
        if not paths:
            raise _Unsupported('empty pytree')
        self.fields = []
        offset = 0
        logical = 0
        for path, leaf in paths:
            arr = np.asarray(leaf)
            if arr.size == 0:
                raise _Unsupported('zero-size leaf %s'
                                   % jax.tree_util.keystr(path))
            if not _supported(arr.dtype):
                raise _Unsupported('leaf %s dtype %s is not wire-packable'
                                   % (jax.tree_util.keystr(path), arr.dtype))
            out = np.dtype(jax.dtypes.canonicalize_dtype(arr.dtype))
            wire = np.dtype(_resolve_wire(_leaf_name(path), out, policy))
            if not _supported(wire):
                raise _Unsupported('wire dtype %s for leaf %s is not '
                                   'packable'
                                   % (wire, jax.tree_util.keystr(path)))
            offset = _align(offset)
            nbytes = arr.size * wire.itemsize
            self.fields.append(_Field(offset, nbytes, wire, out, arr.shape))
            offset += nbytes
            logical += arr.size * out.itemsize
        self.slab_nbytes = offset
        self.logical_nbytes = logical
        #: True when the wire policy narrows at least one leaf — the
        #: provenance 'transfer' outcome distinguishes narrowed from
        #: plain coalesced batches (ISSUE 13).
        self.narrowed = any(f.wire != f.out for f in self.fields)
        if len(self.fields) == 1 and self.fields[0].wire == self.fields[0].out:
            # One full-width leaf: coalescing is a no-op and the staging
            # memcpy is pure cost — the inline put is already one dispatch.
            raise _Unsupported('single full-width leaf')

    def pack(self, tree, slab):
        """One cast-or-copy pass per leaf into the staging slab (numpy
        assignment casts unsafely — the same canonicalization/narrowing
        semantics the unpack side expects)."""
        for field, leaf in zip(self.fields, jax.tree_util.tree_leaves(tree)):
            dst = slab[field.offset:field.offset + field.nbytes]
            dst.view(field.wire)[...] = np.asarray(leaf).reshape(-1)

    def build_unpack(self):
        """The on-device inverse: slice each leaf's bytes out of the slab,
        bitcast to the wire dtype, reshape, and cast back to the output
        dtype when the wire was narrowed.  Jitted by the plane, so the
        whole batch materializes in ONE executable."""
        fields = list(self.fields)
        treedef = self.treedef

        def unpack(slab):
            leaves = []
            for f in fields:
                seg = slab[f.offset:f.offset + f.nbytes]
                if f.wire == np.uint8:
                    arr = seg
                elif f.wire.kind == 'b':
                    arr = seg.astype(jnp.bool_)
                elif f.wire.itemsize == 1:
                    arr = jax.lax.bitcast_convert_type(seg, jnp.dtype(f.wire))
                else:
                    arr = jax.lax.bitcast_convert_type(
                        seg.reshape(-1, f.wire.itemsize), jnp.dtype(f.wire))
                arr = arr.reshape(f.shape)
                if f.wire != f.out:
                    arr = arr.astype(jnp.dtype(f.out))
                leaves.append(arr)
            return jax.tree_util.tree_unflatten(treedef, leaves)

        return unpack


def _slab_bytes(prepared):
    """Host staging bytes a prepared (layout, unpack, plan) needs."""
    layout, _, plan = prepared
    return layout.slab_nbytes if plan is None else plan.total_nbytes


class _ShardPlan(object):
    """Per-device split of one layout: unique leading-axis row ranges (a
    replicated mesh axis maps several devices to one range), the
    per-shard sub-layout, and the device order the reassembly uses."""

    __slots__ = ('devices', 'ranges', 'uniq', 'seg_offsets', 'shard_layout',
                 'total_nbytes')

    def __init__(self, devices, ranges, uniq, seg_offsets, shard_layout,
                 total_nbytes):
        self.devices = devices
        self.ranges = ranges
        self.uniq = uniq
        self.seg_offsets = seg_offsets
        self.shard_layout = shard_layout
        self.total_nbytes = total_nbytes


class TransferPlane(object):
    """Coalescing, narrowing, ring-buffered host→device transfer.

    ``put`` returns the device pytree — or ``None`` when this batch
    structure degrades, in which case the caller runs its existing
    inline path (the plane never guesses; the fallback is the code that
    was already correct).  One plane instance serves one loader: the
    ring slabs, layout caches and unpack executables are all keyed by
    batch structure and reused across steps.
    """

    def __init__(self, device=None, sharding=None, wire_dtypes=None,
                 ring_slots=3, metrics=None, trace_recorder=None,
                 max_staging_bytes=None):
        if wire_dtypes not in (None, 'auto') \
                and not isinstance(wire_dtypes, dict):
            raise ValueError("wire_dtypes must be None, 'auto', or a "
                             '{field: dtype} dict (got %r)' % (wire_dtypes,))
        self._device = device
        self._sharding = sharding
        self._policy = wire_dtypes
        nslots = max(2, int(ring_slots))
        self._slabs = [None] * nslots
        self._inflight = [None] * nslots
        self._turn = 0
        self._max_staging = (MAX_STAGING_BYTES if max_staging_bytes is None
                             else int(max_staging_bytes))
        self._prepared = {}   # signature -> (layout, unpack, plan) | None
        self._trace = trace_recorder
        if metrics is None:
            from petastorm_tpu.telemetry import MetricsRegistry
            metrics = MetricsRegistry('transfer')
        self.metrics = metrics
        self._m_batches = metrics.counter('h2d_batches')
        self._m_degraded = metrics.counter('h2d_degraded')
        self._m_wire = metrics.counter('h2d_bytes_wire')
        self._m_logical = metrics.counter('h2d_bytes_logical')
        self._h_stage = metrics.histogram('h2d_stage')
        self._h_dispatch = metrics.histogram('h2d_dispatch')
        self._h_commit = metrics.histogram('h2d_commit')
        # Donation recycles the device-side slab buffer into the unpack
        # outputs; on the CPU backend it is a no-op that only warns.
        try:
            self._donate = jax.default_backend() != 'cpu'
        except Exception:  # noqa: BLE001 — resolved again at first put
            self._donate = False
        #: Per-batch provenance (ISSUE 13): outcome + stage windows of
        #: the most recent put — ``{'outcome': 'coalesced'|'narrowed'|
        #: 'degraded', 'stages': {'h2d_stage'/'h2d_dispatch'/
        #: 'h2d_commit': [t0, t1]}}`` — read by the loader right after
        #: ``put`` returns (the plane is single-consumer by contract).
        self.last_put = None

    # -- public API ----------------------------------------------------------

    def put(self, tree):
        """Ring-buffered coalesced transfer of one batch pytree; returns
        the device pytree, or None when the structure degrades."""
        prepared = self._prepare(tree)
        if prepared is None:
            self._m_degraded.inc()
            self.last_put = {'outcome': 'degraded'}
            return None
        slot = self._turn % len(self._slabs)
        self._turn += 1
        commit_window = self._wait_slot(slot)
        slab = self._slot_slab(slot, _slab_bytes(prepared))
        batch = self._staged_put(prepared, tree, slab)
        if commit_window is not None and self.last_put is not None:
            # The ring-slot reuse barrier is observed link time of this
            # put's wall — part of its causal chain.
            self.last_put.setdefault('stages', {})['h2d_commit'] = \
                list(commit_window)
        self._inflight[slot] = batch
        return batch

    def put_once(self, tree):
        """One-shot coalesced transfer outside the ring (whole-dataset
        placement: ``DeviceInMemDataLoader._materialize``).  The
        transient slab is released immediately after the dispatch."""
        prepared = self._prepare(tree)
        if prepared is None:
            self._m_degraded.inc()
            self.last_put = {'outcome': 'degraded'}
            return None
        slab = np.empty(_slab_bytes(prepared), np.uint8)
        return self._staged_put(prepared, tree, slab, sample_commit=False)

    def _staged_put(self, prepared, tree, slab, sample_commit=True):
        """Pack → dispatch → on-device unpack + accounting — the shared
        core of ``put`` (ring slab) and ``put_once`` (transient slab)."""
        layout, unpack, plan = prepared
        t0 = time.monotonic()
        if plan is None:
            layout.pack(tree, slab)
            t1 = time.monotonic()
            dev_slab = (jax.device_put(slab, self._device)
                        if self._device is not None else jax.device_put(slab))
            batch = unpack(dev_slab)
            wire = layout.slab_nbytes
        else:
            t1, batch = self._put_sharded(layout, unpack, plan, tree, slab)
            # One device_put PER DEVICE: a replicated mesh axis ships the
            # same segment to every replica, and those bytes are on the
            # link too.
            wire = plan.shard_layout.slab_nbytes * len(plan.devices)
        t2 = time.monotonic()
        self._account(layout, batch, wire, t0, t1, t2,
                      sample_commit=sample_commit)
        return batch

    def drain(self):
        """Block until every in-flight ring transfer is committed (the
        checkpoint / teardown quiesce); host slabs stay for reuse."""
        for i, batch in enumerate(self._inflight):
            if batch is not None:
                jax.block_until_ready(batch)
                self._inflight[i] = None

    def close(self):
        """Drain the ring and release the staging slabs."""
        self.drain()
        self._slabs = [None] * len(self._slabs)

    # -- ring ----------------------------------------------------------------

    def _wait_slot(self, slot):
        """Commit barrier for slab reuse: the batch this slot last staged
        must be device-resident before the slab is rewritten (the H2D
        copy reads the host slab asynchronously).  The observed wait is
        the ring's view of true link time → ``h2d/commit``.  Returns the
        wait window (or None when the slot was free)."""
        batch = self._inflight[slot]
        if batch is None:
            return None
        t0 = time.monotonic()
        jax.block_until_ready(batch)
        t1 = time.monotonic()
        self._inflight[slot] = None
        self._h_commit.observe(t1 - t0)
        if self._trace is not None:
            self._trace.event('h2d/commit', t0, t1, kind='ring')
        return (t0, t1)

    def _slot_slab(self, slot, nbytes):
        slab = self._slabs[slot]
        if slab is None or slab.nbytes < nbytes:
            slab = self._slabs[slot] = np.empty(nbytes, np.uint8)
        return slab[:nbytes]

    def _account(self, layout, batch, wire_bytes, t0, t1, t2,
                 sample_commit=True):
        self._m_batches.inc()
        self._m_wire.inc(wire_bytes)
        self._m_logical.inc(layout.logical_nbytes)
        self._h_stage.observe(t1 - t0)
        self._h_dispatch.observe(t2 - t1)
        self.last_put = {
            'outcome': 'narrowed' if layout.narrowed else 'coalesced',
            'stages': {'h2d_stage': [t0, t1], 'h2d_dispatch': [t1, t2]}}
        if self._trace is not None:
            self._trace.event('h2d/stage', t0, t1)
            self._trace.event('h2d/dispatch', t1, t2)
        if sample_commit \
                and int(self._m_batches.value) % _COMMIT_SAMPLE_EVERY == 1:
            # Periodic FULL commit sample: dispatch → device-ready wall
            # time of the batch just put (the ring wait in _wait_slot
            # only ever sees the residual after a full lap of overlap).
            t3 = time.monotonic()
            jax.block_until_ready(batch)
            t4 = time.monotonic()
            self._h_commit.observe(t4 - t3)
            if self._trace is not None:
                self._trace.event('h2d/commit', t3, t4, kind='sample')

    # -- layout / plan cache -------------------------------------------------

    def _prepare(self, tree):
        sig = _signature(tree)
        if sig in self._prepared:
            return self._prepared[sig]
        try:
            layout = _Layout(tree, self._policy)
            plan = None
            if self._sharding is not None:
                plan = self._plan_shards(tree)
                total = plan.total_nbytes
            else:
                total = layout.slab_nbytes
            if total > self._max_staging:
                raise _Unsupported('staging slab %d B exceeds the %d B cap'
                                   % (total, self._max_staging))
            unpack = jax.jit((layout if plan is None
                              else plan.shard_layout).build_unpack(),
                             donate_argnums=(0,) if self._donate else ())
            prepared = (layout, unpack, plan)
        except _Unsupported as e:
            logger.debug('transfer plane degrades for this batch '
                         'structure: %s', e)
            prepared = None
        self._prepared[sig] = prepared
        return prepared

    # -- sharded parallel transfer -------------------------------------------

    def _plan_shards(self, tree):
        """Validate that the sharding splits only the leading axis of
        every leaf (replication over other mesh axes allowed) and build
        the per-device packing plan.  Anything else degrades to
        ``global_batch_from_local``."""
        sharding = self._sharding
        if jax.process_count() != 1:
            raise _Unsupported('multi-host sharding assembles via '
                               'make_array_from_process_local_data')
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]
        ref_ranges = None
        for arr in leaves:
            if arr.ndim == 0:
                raise _Unsupported('scalar leaf cannot shard a batch axis')
            try:
                index_map = sharding.addressable_devices_indices_map(
                    arr.shape)
            except Exception as e:  # noqa: BLE001 — e.g. indivisible dim
                raise _Unsupported('sharding rejects leaf shape %s: %s'
                                   % (arr.shape, e))
            ranges = {}
            for dev, idx in index_map.items():
                idx = idx if isinstance(idx, tuple) else (idx,)
                start, stop, step = (idx[0] if idx else slice(None)) \
                    .indices(arr.shape[0])
                if step != 1:
                    raise _Unsupported('strided shard index')
                for dim, sub in zip(arr.shape[1:], idx[1:]):
                    lo, hi, st = sub.indices(dim)
                    if (lo, hi, st) != (0, dim, 1):
                        raise _Unsupported('sharding splits a non-leading '
                                           'axis')
                ranges[dev] = (start, stop)
            if ref_ranges is None:
                ref_ranges = ranges
            elif ranges != ref_ranges:
                raise _Unsupported('leaves shard to different row ranges')
        uniq = sorted(set(ref_ranges.values()))
        rows = {stop - start for start, stop in uniq}
        if len(rows) != 1 or 0 in rows:
            raise _Unsupported('unequal shard row counts')
        rows = rows.pop()
        devices = sorted(ref_ranges, key=lambda d: (ref_ranges[d][0], d.id))
        shard_tree = jax.tree_util.tree_map(
            lambda v: np.asarray(v)[:rows], tree)
        shard_layout = _Layout(shard_tree, self._policy)
        stride = _align(shard_layout.slab_nbytes)
        seg_offsets = {rng: i * stride for i, rng in enumerate(uniq)}
        return _ShardPlan(devices, ref_ranges, uniq, seg_offsets,
                          shard_layout, stride * len(uniq))

    def _put_sharded(self, layout, unpack, plan, tree, slab):
        """Pack each unique row range once, dispatch every device's slice
        concurrently (async ``device_put`` per device — the DMAs
        overlap), unpack on-device per shard, and reassemble each leaf
        as one global array."""
        nbytes = plan.shard_layout.slab_nbytes
        for start, stop in plan.uniq:
            seg = slab[plan.seg_offsets[(start, stop)]:]
            plan.shard_layout.pack(
                jax.tree_util.tree_map(
                    lambda v: np.asarray(v)[start:stop], tree),
                seg[:nbytes])
        t1 = time.monotonic()
        shards = {}
        for dev in plan.devices:   # all dispatches before any unpack
            off = plan.seg_offsets[plan.ranges[dev]]
            shards[dev] = jax.device_put(slab[off:off + nbytes], dev)
        per_dev = [jax.tree_util.tree_leaves(unpack(shards[dev]))
                   for dev in plan.devices]
        out_leaves = []
        for li, field in enumerate(layout.fields):
            out_leaves.append(jax.make_array_from_single_device_arrays(
                field.shape, self._sharding,
                [per_dev[di][li] for di in range(len(plan.devices))]))
        return t1, jax.tree_util.tree_unflatten(layout.treedef, out_leaves)


_DONE = object()


class DispatchPump(object):  # ptlint: disable=pickle-unsafe-attrs — the pump lives and dies inside one loader iteration in the consuming process; it is never pickled (resume tokens carry drained host batches, not the pump)
    """Background H2D dispatch thread: pulls host batches from the
    loader's (single-consumer) host-batch generator, ships each through
    the transfer plane, and appends the resulting device batches to the
    shared ``pending`` deque the loader yields from — so host staging,
    the link, and the device step run as three overlapped pipeline
    stages instead of one serial loop.

    Checkpoint contract: ``pause()`` blocks until the thread is
    quiescent (not touching the generator, the plane, or ``pending``) —
    ``DataLoader.state_dict`` brackets its snapshot with
    ``pause()``/``resume()`` so the exact-resume machinery (reader
    drain, shuffle-buffer snapshot, pending drain) sees a frozen
    pipeline.  ``stop()`` ends the thread; a pull blocked inside the
    reader cannot be interrupted mid-call, so the thread is daemonic and
    exits right after that pull returns (the loader's ``reader.stop()``
    is what unblocks it during teardown).
    """

    def __init__(self, source, ship, prefetch):
        self._source = source
        self._ship = ship
        self._cap = max(1, int(prefetch))
        self.pending = deque()
        self._cond = make_condition('jax.transfer.DispatchPump._cond')
        self._idle = False
        self._pause = 0
        self._stopped = False
        self._done = False
        self._error = None
        self._thread = threading.Thread(target=self._run,
                                        name='petastorm-tpu-h2d-dispatch',
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        try:
            while True:
                with self._cond:
                    while (self._pause or len(self.pending) >= self._cap) \
                            and not self._stopped:
                        self._idle = True
                        self._cond.notify_all()
                        self._cond.wait()
                    self._idle = False
                    if self._stopped:
                        return
                item = next(self._source)   # outside the lock: may block
                with self._cond:
                    if self._stopped:
                        return
                dev = self._ship(item)
                with self._cond:
                    self.pending.append(dev)
                    self._cond.notify_all()
        except StopIteration:
            pass
        except BaseException as e:  # noqa: BLE001 — re-raised by get()
            self._error = e
        finally:
            with self._cond:
                self._done = True
                self._idle = True
                self._cond.notify_all()

    def get(self):
        """Next device batch in stream order; raises the pump's pending
        error once the buffered batches are served; the module-level
        ``_DONE`` sentinel ends the stream."""
        with self._cond:
            while not self.pending and not self._done:
                self._cond.wait()
            if self.pending:
                item = self.pending.popleft()
                self._cond.notify_all()
                return item
            if self._error is not None:
                raise self._error
            return _DONE

    def pause(self):
        """Checkpoint barrier: returns once the pump thread is parked
        (or finished) and guaranteed not to advance the generator or
        mutate ``pending`` until ``resume()``.  Counting, so brackets
        nest (PackedDataLoader wraps the base snapshot).

        A pull already in progress must complete first — an in-flight
        ``next()`` cannot be snapshotted consistently — so on a starved
        source a checkpoint waits out the current batch wait.  That is
        the same wall-clock position the inline path puts the caller
        in: without the pump, the consuming thread sits inside
        ``next(loader)`` for that same stall and cannot call
        ``state_dict`` at all until it returns."""
        with self._cond:
            self._pause += 1
            self._cond.notify_all()
            while not (self._idle or self._done):
                self._cond.wait()

    def resume(self):
        with self._cond:
            self._pause = max(0, self._pause - 1)
            self._cond.notify_all()

    def stop(self, join_timeout_s=2.0):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(join_timeout_s)

    def join(self, timeout_s=2.0):
        self._thread.join(timeout_s)

    @property
    def alive(self):
        return self._thread.is_alive()
