"""Sequence packing: fixed-shape batches from variable-length sequences.

XLA compiles one program per shape, so variable-length sequences must
become static shapes before they reach the chip.  Naive padding wastes
FLOPs quadratically (attention) on pad tokens; *packing* lays several
sequences end-to-end in one row of length ``max_len`` and tracks ownership
with ``segment_ids``, recovering most of the padding waste (the approach
of T5's pack_dataset and jax grain's pack-and-batch; no reference analog —
the closest reference machinery is host-side window assembly in
``petastorm/ngram.py :: NGram``, which emits per-window rows and leaves
batching shape problems to the consumer).

Host side (numpy, runs in the loader's worker pool or ``transform_fn``):

* :func:`pack_sequences` — pack a list of 1-D token arrays into
  ``(rows, max_len)`` with first-fit-decreasing (offline, best utilization).
* :func:`pack_stream` — streaming greedy packer: wraps any iterator of
  sequences (e.g. a reader column) and yields fixed-shape batches forever
  ready for ``device_put``.

Device side (jitted):

* :func:`segment_mask` — block-diagonal (optionally causal) attention mask
  from segment ids.
* :func:`packed_attention` — dense attention restricted to segments; same
  ``[batch, seq, heads, head_dim]`` convention as
  ``petastorm_tpu.ops.flash_attention`` and a drop-in ``attn_fn`` for
  ``models.transformer.TransformerLM`` via ``functools.partial``.
* :func:`next_token_targets` — LM targets + loss weights that never cross
  a packing boundary.

Packing invariant used throughout: segments within a row are CONTIGUOUS
(sequence i occupies one unbroken span), so "causal within segment" equals
"row-causal AND same segment" — a cheap mask, no per-segment position
bookkeeping on device.
"""

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ['pack_sequences', 'pack_stream', 'StreamPacker', 'segment_mask',
           'packed_attention', 'next_token_targets']


def _emit(rows, max_len, dtype, pad_id):
    """Render packed rows (lists of sequences) to the batch dict.

    ``dtype=None`` promotes over the actual sequences in this batch (the
    streaming packer can't know future dtypes, so each batch is exactly
    wide enough for its own rows — never a silent narrowing cast).
    """
    n = len(rows)
    if dtype is None:
        dtype = np.result_type(*[s.dtype for seqs in rows for s in seqs])
    tokens = np.full((n, max_len), pad_id, dtype)
    segment_ids = np.zeros((n, max_len), np.int32)
    positions = np.zeros((n, max_len), np.int32)
    for r, seqs in enumerate(rows):
        off = 0
        for s, seq in enumerate(seqs):
            L = len(seq)
            tokens[r, off:off + L] = seq
            segment_ids[r, off:off + L] = s + 1
            positions[r, off:off + L] = np.arange(L)
            off += L
    return {'tokens': tokens, 'segment_ids': segment_ids,
            'positions': positions}


def pack_sequences(sequences, max_len, pad_id=0):
    """Pack 1-D arrays into ``(rows, max_len)`` via first-fit-decreasing.

    Returns ``{'tokens', 'segment_ids', 'positions'}``; ``segment_ids`` is
    1-based per row (0 marks padding), ``positions`` restarts at 0 for each
    sequence.  Raises if any sequence exceeds ``max_len`` (truncation is a
    modeling decision — do it upstream where the tokenizer lives).
    """
    seqs = [np.asarray(s) for s in sequences]
    if not seqs:
        raise ValueError('no sequences to pack')
    for s in seqs:
        if s.ndim != 1:
            raise ValueError('expected 1-D sequences, got shape %r' % (s.shape,))
        if len(s) > max_len:
            raise ValueError('sequence of length %d exceeds max_len=%d; '
                             'truncate upstream' % (len(s), max_len))
    order = sorted(range(len(seqs)), key=lambda i: -len(seqs[i]))
    rows, room = [], []
    for i in order:
        L = len(seqs[i])
        for r in range(len(rows)):          # first fit
            if room[r] >= L:
                rows[r].append(seqs[i])
                room[r] -= L
                break
        else:
            rows.append([seqs[i]])
            room.append(max_len - L)
    return _emit(rows, max_len, np.result_type(*seqs), pad_id)


def pack_stream(seq_iter, max_len, rows_per_batch, pad_id=0,
                open_rows=32, drop_last=False):
    """Greedy streaming packer: yields fixed-shape batches from an iterator.

    Keeps up to ``open_rows`` partially-filled rows; each incoming sequence
    goes to the fullest row it fits in (best-fit — keeps rows closing
    fast), or opens a new row, and full-enough batches are emitted as soon
    as ``rows_per_batch`` rows have closed.  The tail is flushed as a final
    short-padded batch unless ``drop_last``.

    Suited to wrapping a reader column::

        seqs = (row.tokens for row in make_reader(url, ...))
        for batch in pack_stream(seqs, max_len=4096, rows_per_batch=8):
            step(batch['tokens'], batch['segment_ids'])

    The token dtype is STICKY: each batch is emitted in the promotion of
    every sequence dtype seen so far, so a stream mixing e.g. int32 and
    int64 widens once and stays wide instead of alternating batch dtypes
    (which would retrigger XLA compilation in a jitted step).
    """
    packer = StreamPacker(max_len, rows_per_batch, pad_id=pad_id,
                          open_rows=open_rows, drop_last=drop_last)
    for seq in seq_iter:
        for batch in packer.add(seq):
            yield batch
    for batch in packer.flush():
        yield batch


class StreamPacker(object):
    """The stateful engine under :func:`pack_stream`.

    ``add(seq)`` returns the batches that became ready; ``flush()`` drains
    the tail.  Exposed as a class (not just a generator) so loaders can
    snapshot the residue — open rows, closed rows, sticky dtype — for
    exact mid-epoch checkpoint/resume
    (``petastorm_tpu.jax.PackedDataLoader.state_dict``).
    """

    def __init__(self, max_len, rows_per_batch, pad_id=0, open_rows=32,
                 drop_last=False):
        if rows_per_batch < 1 or open_rows < 1:
            raise ValueError('rows_per_batch and open_rows must be >= 1')
        self._max_len = max_len
        self._rows_per_batch = rows_per_batch
        self._pad_id = pad_id
        self._open_rows = open_rows
        self._drop_last = drop_last
        self._open = []      # list of (room, [seqs])
        self._closed = []
        self._dtype = None   # promoted over everything seen; never narrows

    def _close_fullest(self):
        i = min(range(len(self._open)), key=lambda j: self._open[j][0])
        self._closed.append(self._open.pop(i)[1])

    def _ready_batches(self):
        out = []
        while len(self._closed) >= self._rows_per_batch:
            out.append(_emit(self._closed[:self._rows_per_batch],
                             self._max_len, self._dtype, self._pad_id))
            self._closed = self._closed[self._rows_per_batch:]
        return out

    def add(self, seq):
        """Fold one sequence in; returns the batches that became ready."""
        seq = np.asarray(seq)
        if seq.ndim != 1:
            raise ValueError('expected 1-D sequences, got %r' % (seq.shape,))
        self._dtype = (seq.dtype if self._dtype is None
                       else np.result_type(self._dtype, seq.dtype))
        max_len = self._max_len
        if len(seq) > max_len:
            raise ValueError('sequence of length %d exceeds max_len=%d'
                             % (len(seq), max_len))
        if len(seq) == max_len:     # exactly-full row: close it now
            self._closed.append([seq])
        else:
            fits = [i for i, (room, _) in enumerate(self._open)
                    if room >= len(seq)]
            if fits:
                i = min(fits, key=lambda j: self._open[j][0])   # best fit
                room, seqs = self._open[i]
                seqs.append(seq)
                self._open[i] = (room - len(seq), seqs)
                if self._open[i][0] == 0:
                    self._closed.append(self._open.pop(i)[1])
            else:
                self._open.append((max_len - len(seq), [seq]))
                if len(self._open) > self._open_rows:
                    self._close_fullest()
        return self._ready_batches()

    def flush(self):
        """Drain open rows; returns the final batches (tail short-padded
        to full shape unless ``drop_last``)."""
        self._closed.extend(
            seqs for _, seqs in sorted(self._open, key=lambda e: e[0]))
        self._open = []
        out = self._ready_batches()
        if self._closed and not self._drop_last:
            pad_rows = self._rows_per_batch - len(self._closed)
            batch = _emit(self._closed, self._max_len, self._dtype,
                          self._pad_id)
            if pad_rows:
                batch = {k: np.concatenate(
                    [v, np.zeros((pad_rows,) + v.shape[1:], v.dtype)])
                    for k, v in batch.items()}
                if self._pad_id != 0:
                    batch['tokens'][-pad_rows:] = self._pad_id
            out.append(batch)
        self._closed = []
        return out

    # -- exact-checkpoint support --------------------------------------------

    def state_dict(self):
        return {
            'open': [(room, [np.asarray(s) for s in seqs])
                     for room, seqs in self._open],
            'closed': [[np.asarray(s) for s in seqs]
                       for seqs in self._closed],
            'dtype': None if self._dtype is None else np.dtype(self._dtype).str,
        }

    def load_state_dict(self, state):
        self._open = [(room, list(seqs)) for room, seqs in state['open']]
        self._closed = [list(seqs) for seqs in state['closed']]
        self._dtype = (None if state['dtype'] is None
                       else np.dtype(state['dtype']))


def segment_mask(segment_ids_q, segment_ids_kv, causal=False):
    """Boolean attention mask ``[batch, 1, len_q, len_kv]`` from segment ids.

    A query may attend a key iff both carry the same NONZERO segment id;
    with ``causal=True`` additionally key_pos <= query_pos (valid because
    packed segments are contiguous — see module docstring).  The head axis
    is kept size-1 for broadcast.
    """
    q = jnp.asarray(segment_ids_q)
    kv = jnp.asarray(segment_ids_kv)
    mask = (q[:, :, None] == kv[:, None, :]) & (q[:, :, None] != 0)
    if causal:
        lq, lkv = q.shape[-1], kv.shape[-1]
        mask = mask & (jnp.arange(lkv)[None, :] <= jnp.arange(lq)[:, None])
    return mask[:, None, :, :]


def packed_attention(q, k, v, segment_ids, causal=True, scale=None):
    """Dense attention over packed rows: segments never attend each other.

    Same tensor convention as ``ops.flash_attention`` (``[batch, seq,
    heads, head_dim]``); softmax statistics in fp32.  Use as the
    ``attn_fn`` of ``models.transformer.TransformerLM``::

        attn = functools.partial(packed_attention, segment_ids=seg)
        TransformerLM(..., attn_fn=attn)

    O(seq^2) score memory — the correctness oracle and the moderate-length
    path; at long context use ``ops.flash_attention(..., segment_ids=seg)``
    — the same semantics as Pallas kernels with O(seq) memory.
    """
    if q.ndim != 4:
        raise ValueError('expected [batch, seq, heads, head_dim], got %r'
                         % (q.shape,))
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    mask = segment_mask(segment_ids, segment_ids, causal=causal)
    scores = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask, scores, -jnp.inf)
    # Fully-masked query rows (padding) would softmax over -inf -> NaN;
    # give them a finite row and zero them after.
    any_valid = mask.any(axis=-1, keepdims=True)
    scores = jnp.where(any_valid, scores, 0.0)
    weights = jax.nn.softmax(scores, axis=-1)
    weights = jnp.where(any_valid, weights, 0.0)
    out = jnp.einsum('bhqk,bkhd->bqhd', weights.astype(q.dtype), v)
    return out


def next_token_targets(tokens, segment_ids):
    """LM ``(targets, weights)`` that never cross a packing boundary.

    ``targets[t] = tokens[t+1]``; ``weights[t] = 1`` only where position
    ``t`` and ``t+1`` belong to the same nonzero segment (the last token of
    each sequence and all padding get weight 0).  Works on numpy or jax
    arrays; shapes ``[batch, seq]`` in, same out.
    """
    xp = jnp if isinstance(tokens, jnp.ndarray) else np
    targets = xp.concatenate(
        [tokens[:, 1:], xp.zeros_like(tokens[:, :1])], axis=1)
    seg_next = xp.concatenate(
        [segment_ids[:, 1:], xp.zeros_like(segment_ids[:, :1])], axis=1)
    weights = ((segment_ids == seg_next) & (segment_ids != 0)).astype(
        xp.float32)
    return targets, weights

