"""One-call train-state checkpointing: model pytree + data-plane state.

SURVEY.md §5.4's build obligation is that the input pipeline checkpoints
*alongside* orbax model state.  The tokens themselves are plain picklable
dicts (``Reader.state_dict`` / ``DataLoader.state_dict`` /
``WeightedSamplingReader.state_dict`` — and the elastic reshard outputs),
but they mix numpy arrays, rng ``bit_generator`` states, and python
scalars, which a pytree checkpointer won't round-trip leaf-for-leaf.
These helpers pin the working recipe: the model state rides as a normal
orbax pytree (sharded arrays restore as such), the data-plane state rides
as one pickled-bytes leaf.

    from petastorm_tpu import checkpoint as pt_ckpt

    pt_ckpt.save_train_state(path, {'params': params, 'opt': opt_state},
                             data_state=loader.state_dict())
    ...
    model, data_state = pt_ckpt.restore_train_state(path)
    reader = make_reader(url, ..., resume_state=data_state['reader'])
    loader = DataLoader(reader, B, resume_state=data_state)

Multi-host: tokens are PER HOST — save each host's ``data_state`` under
its own directory (e.g. ``f'{path}/host_{jax.process_index()}'``) or
gather all hosts' tokens first and save the list from process 0; the
elastic reshard functions consume exactly such a list
(``docs/deployment.md`` §4).  Pass ``checkpointer=ocp.AsyncCheckpointer(
ocp.PyTreeCheckpointHandler())`` for async saves (call ``wait_until_
finished()`` before relying on the files).
"""

import pickle

import numpy as np

__all__ = ['save_train_state', 'restore_train_state', 'TrainStateManager']

_DATA_KEY = 'petastorm_tpu_data_state'
_WRAP_KEY = 'petastorm_tpu_wrapped_model'


def _default_checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_train_state(path, model_state, data_state=None, checkpointer=None):
    """Save ``model_state`` (any orbax-compatible pytree) plus the data
    plane's resume state (any picklable token structure) in one checkpoint.

    ``data_state`` accepts whatever the framework's ``state_dict`` methods
    produce — reader tokens, exact loader snapshots, weighted-mixer states,
    elastic reshard outputs, or a dict/list combining several.
    """
    # Non-dict pytrees wrap under a RESERVED sentinel key so restore can
    # unwrap unambiguously — inferring from ordinary key names would
    # mangle a user dict that happens to use them (e.g. {'model': ...}).
    payload = _wrap_payload(model_state, data_state)
    (checkpointer or _default_checkpointer()).save(str(path), payload)


def restore_train_state(path, checkpointer=None):
    """Returns ``(model_state, data_state)``; ``data_state`` is None when
    the checkpoint was saved without one.  ``model_state`` comes back with
    the same top-level structure it was saved with (a dict stays a dict;
    a non-dict pytree comes back under its original structure)."""
    restored = (checkpointer or _default_checkpointer()).restore(str(path))
    return _split_payload(restored)


def _wrap_payload(model_state, data_state):
    """model pytree + pickled data-plane token -> one orbax payload."""
    if isinstance(model_state, dict):
        clash = {_DATA_KEY, _WRAP_KEY} & set(model_state)
        if clash:
            raise ValueError('model_state uses reserved key(s) %s'
                             % sorted(clash))
        payload = dict(model_state)
    else:
        payload = {_WRAP_KEY: model_state}
    if data_state is not None:
        payload[_DATA_KEY] = np.frombuffer(pickle.dumps(data_state),
                                           np.uint8).copy()
    return payload


def _split_payload(restored):
    data_state = None
    blob = restored.pop(_DATA_KEY, None)
    if blob is not None:
        data_state = pickle.loads(np.asarray(blob, np.uint8).tobytes())
    if set(restored) == {_WRAP_KEY}:
        return restored[_WRAP_KEY], data_state
    return restored, data_state


class TrainStateManager(object):
    """Periodic train-state checkpointing: cadence, retention, async
    saves, resume-latest — one object for the whole training-loop story.

    Composes orbax's ``CheckpointManager`` with the data-plane-token
    convention of :func:`save_train_state`, so every retained step holds
    the model pytree AND the exact input-pipeline position it was taken
    at.  Async by default: the TPU keeps training while the previous
    step's state serializes (the idiomatic overlap on hardware where a
    save would otherwise stall the step loop)::

        mgr = TrainStateManager(path, save_interval_steps=500,
                                max_to_keep=3)
        for step, batch in enumerate(loader):
            params, opt, loss = train_step(params, opt, batch)
            mgr.save(step, {'params': params, 'opt': opt},
                     data_state=loader.state_dict())   # no-op off-cadence
        mgr.wait_until_finished()

        step, model, data_state = TrainStateManager.restore_latest_from(path)

    ``save`` returns True only on the steps the cadence actually
    persists, so callers may gate the (possibly costly) ``state_dict``
    snapshot: ``if mgr.should_save(step): mgr.save(step, ...,
    data_state=loader.state_dict())``.
    """

    def __init__(self, directory, save_interval_steps=1, max_to_keep=3,
                 async_save=True):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._mgr = ocp.CheckpointManager(
            str(directory),
            options=ocp.CheckpointManagerOptions(
                save_interval_steps=save_interval_steps,
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save))

    def should_save(self, step):
        """True when the cadence would persist ``step`` — gate expensive
        ``state_dict()`` drains on this."""
        return self._mgr.should_save(step)

    def save(self, step, model_state, data_state=None, force=False):
        """Persist ``(model_state, data_state)`` at ``step`` when the
        cadence says so (or always, with ``force=True``); returns whether
        a save actually happened.  Async: returns as soon as the arrays
        are snapshotted; serialization overlaps subsequent steps."""
        if not force and not self._mgr.should_save(step):
            # Off-cadence: skip BEFORE building the payload — pickling a
            # loader token every step would be recurring hot-loop work.
            return False
        payload = _wrap_payload(model_state, data_state)
        return self._mgr.save(step, args=self._ocp.args.PyTreeSave(payload),
                              force=force)

    def restore(self, step, restore_args=None):
        """Returns ``(model_state, data_state)`` for a retained step.

        ``restore_args``: an ``ocp.args.*`` instance (e.g.
        ``ocp.args.PyTreeRestore(target_with_shardings)``) — REQUIRED in
        practice when restoring sharded arrays on a different device
        topology than the save (orbax's sharding-from-file fallback is
        unsafe across topology changes)."""
        restored = self._mgr.restore(step, args=restore_args) \
            if restore_args is not None else self._mgr.restore(step)
        return _split_payload(restored)

    def restore_latest(self, restore_args=None):
        """Returns ``(step, model_state, data_state)``, or
        ``(None, None, None)`` when the directory holds no checkpoint."""
        step = self._mgr.latest_step()
        if step is None:
            return None, None, None
        model_state, data_state = self.restore(step,
                                               restore_args=restore_args)
        return step, model_state, data_state

    @classmethod
    def restore_latest_from(cls, directory, restore_args=None):
        """One-shot resume: open, restore the latest step, close.  Use
        this (not a throwaway instance) outside a training loop — the
        manager owns background threads that only ``close()`` releases."""
        with cls(directory) as mgr:
            return mgr.restore_latest(restore_args=restore_args)

    def all_steps(self):
        return self._mgr.all_steps()

    def latest_step(self):
        return self._mgr.latest_step()

    def wait_until_finished(self):
        """Block until pending async saves are durable — call before
        relying on the files (end of training, pre-emption handler)."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.wait_until_finished()
        self.close()
