"""One-call train-state checkpointing: model pytree + data-plane state.

SURVEY.md §5.4's build obligation is that the input pipeline checkpoints
*alongside* orbax model state.  The tokens themselves are plain picklable
dicts (``Reader.state_dict`` / ``DataLoader.state_dict`` /
``WeightedSamplingReader.state_dict`` — and the elastic reshard outputs),
but they mix numpy arrays, rng ``bit_generator`` states, and python
scalars, which a pytree checkpointer won't round-trip leaf-for-leaf.
These helpers pin the working recipe: the model state rides as a normal
orbax pytree (sharded arrays restore as such), the data-plane state rides
as one pickled-bytes leaf.

    from petastorm_tpu import checkpoint as pt_ckpt

    pt_ckpt.save_train_state(path, {'params': params, 'opt': opt_state},
                             data_state=loader.state_dict())
    ...
    model, data_state = pt_ckpt.restore_train_state(path)
    reader = make_reader(url, ..., resume_state=data_state['reader'])
    loader = DataLoader(reader, B, resume_state=data_state)

Multi-host: tokens are PER HOST — save each host's ``data_state`` under
its own directory (e.g. ``f'{path}/host_{jax.process_index()}'``) or
gather all hosts' tokens first and save the list from process 0; the
elastic reshard functions consume exactly such a list
(``docs/deployment.md`` §4).  Pass ``checkpointer=ocp.AsyncCheckpointer(
ocp.PyTreeCheckpointHandler())`` for async saves (call ``wait_until_
finished()`` before relying on the files).
"""

import pickle

import numpy as np

__all__ = ['save_train_state', 'restore_train_state']

_DATA_KEY = 'petastorm_tpu_data_state'
_WRAP_KEY = 'petastorm_tpu_wrapped_model'


def _default_checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_train_state(path, model_state, data_state=None, checkpointer=None):
    """Save ``model_state`` (any orbax-compatible pytree) plus the data
    plane's resume state (any picklable token structure) in one checkpoint.

    ``data_state`` accepts whatever the framework's ``state_dict`` methods
    produce — reader tokens, exact loader snapshots, weighted-mixer states,
    elastic reshard outputs, or a dict/list combining several.
    """
    # Non-dict pytrees wrap under a RESERVED sentinel key so restore can
    # unwrap unambiguously — inferring from ordinary key names would
    # mangle a user dict that happens to use them (e.g. {'model': ...}).
    if isinstance(model_state, dict):
        clash = {_DATA_KEY, _WRAP_KEY} & set(model_state)
        if clash:
            raise ValueError('model_state uses reserved key(s) %s'
                             % sorted(clash))
        payload = dict(model_state)
    else:
        payload = {_WRAP_KEY: model_state}
    if data_state is not None:
        blob = np.frombuffer(pickle.dumps(data_state), np.uint8).copy()
        payload[_DATA_KEY] = blob
    (checkpointer or _default_checkpointer()).save(str(path), payload)


def restore_train_state(path, checkpointer=None):
    """Returns ``(model_state, data_state)``; ``data_state`` is None when
    the checkpoint was saved without one.  ``model_state`` comes back with
    the same top-level structure it was saved with (a dict stays a dict;
    a non-dict pytree comes back under its original structure)."""
    restored = (checkpointer or _default_checkpointer()).restore(str(path))
    data_state = None
    blob = restored.pop(_DATA_KEY, None)
    if blob is not None:
        data_state = pickle.loads(np.asarray(blob, np.uint8).tobytes())
    if set(restored) == {_WRAP_KEY}:
        return restored[_WRAP_KEY], data_state
    return restored, data_state
