"""Spark integration (optional — pyspark is not installed on TPU-VM images)."""

from petastorm_tpu.spark.spark_dataset_converter import (  # noqa: F401
    SparkDatasetConverter, make_pandas_converter, make_spark_converter,
)
