"""Spark DataFrame -> cached Parquet -> training loaders.

Parity: reference ``petastorm/spark/spark_dataset_converter.py ::
SparkDatasetConverter, make_spark_converter, CachedDataFrameMeta`` and the
conf key ``petastorm.spark.converter.parentCacheDirUrl`` (kept identical).

Design notes for the TPU build:

* ``make_spark_converter`` needs a live pyspark session (gated import —
  pyspark is an optional extra and absent on TPU-VM images).  Everything
  downstream of the materialized Parquet (the converter object and its
  ``make_*`` methods) is Spark-free and fully testable here.
* The north-star deployment is "materialize to GCS for pod workers": the
  parent cache dir is a ``gs://`` URL, every TPU host constructs loaders
  from the same cache URL, sharded by ``jax.process_index()`` automatically.
* ``make_jax_loader`` is the TPU-first addition next to the reference's
  ``make_tf_dataset`` / ``make_torch_dataloader``.
"""

import atexit
import hashlib
import logging
from petastorm_tpu.utils.locks import make_lock
import uuid

from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths

logger = logging.getLogger(__name__)

_CACHED_CONVERTERS = {}
_CACHE_LOCK = make_lock('spark.spark_dataset_converter._CACHE_LOCK')


class CachedDataFrameMeta(object):
    """Bookkeeping for one materialized DataFrame.

    Parity: ``petastorm/spark/spark_dataset_converter.py :: CachedDataFrameMeta``.
    """

    def __init__(self, df_plan_hash, cache_dir_url, row_count, parquet_row_group_size_bytes):
        self.df_plan_hash = df_plan_hash
        self.cache_dir_url = cache_dir_url
        self.row_count = row_count
        self.parquet_row_group_size_bytes = parquet_row_group_size_bytes


class SparkDatasetConverter(object):
    """Handle to a materialized (cached) Parquet copy of a DataFrame.

    Parity: ``petastorm/spark/spark_dataset_converter.py :: SparkDatasetConverter``
    incl. the conf-key constant.
    """

    PARENT_CACHE_DIR_URL_CONF = 'petastorm.spark.converter.parentCacheDirUrl'

    def __init__(self, cache_dir_url, dataset_size):
        self.cache_dir_url = cache_dir_url
        self.dataset_size = dataset_size

    def __len__(self):
        return self.dataset_size

    # -- loader constructors (Spark-free) ------------------------------------

    def make_tf_dataset(self, batch_size=None, num_epochs=None, workers_count=None,
                        cur_shard=None, shard_count=None, prefetch=None,
                        preprocess_fn=None, **petastorm_reader_kwargs):
        """tf.data over the cached Parquet.

        Parity: reference ``make_tf_dataset`` — returns a context manager
        yielding the dataset; exiting stops the underlying reader.
        """
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.tf_utils import make_petastorm_dataset

        kwargs = dict(petastorm_reader_kwargs)
        if workers_count is not None:
            kwargs['workers_count'] = workers_count
        reader = make_batch_reader(self.cache_dir_url, num_epochs=num_epochs,
                                   cur_shard=cur_shard, shard_count=shard_count,
                                   **kwargs)
        dataset = make_petastorm_dataset(reader)
        if batch_size is not None:
            dataset = dataset.unbatch().batch(batch_size)
        if preprocess_fn is not None:
            dataset = dataset.map(preprocess_fn)
        if prefetch is not None:
            dataset = dataset.prefetch(prefetch)
        return _ReaderScope(dataset, reader)

    def make_torch_dataloader(self, batch_size=32, num_epochs=None, workers_count=None,
                              cur_shard=None, shard_count=None, transform_fn=None,
                              shuffling_queue_capacity=0, **petastorm_reader_kwargs):
        """torch BatchedDataLoader over the cached Parquet (context manager).

        Parity: reference ``make_torch_dataloader``.
        """
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader

        kwargs = dict(petastorm_reader_kwargs)
        if workers_count is not None:
            kwargs['workers_count'] = workers_count
        reader = make_batch_reader(self.cache_dir_url, num_epochs=num_epochs,
                                   cur_shard=cur_shard, shard_count=shard_count,
                                   **kwargs)
        return BatchedDataLoader(reader, batch_size=batch_size, transform_fn=transform_fn,
                                 shuffling_queue_capacity=shuffling_queue_capacity)

    def make_jax_loader(self, batch_size=32, num_epochs=None, workers_count=None,
                        cur_shard=None, shard_count=None, sharding=None,
                        loader_kwargs=None, **petastorm_reader_kwargs):
        """TPU-native loader over the cached Parquet (context manager) —
        double-buffered device batches, optional pjit global-batch sharding."""
        from petastorm_tpu.jax import DataLoader
        from petastorm_tpu.reader import make_batch_reader

        kwargs = dict(petastorm_reader_kwargs)
        if workers_count is not None:
            kwargs['workers_count'] = workers_count
        reader = make_batch_reader(self.cache_dir_url, num_epochs=num_epochs,
                                   cur_shard=cur_shard, shard_count=shard_count,
                                   **kwargs)
        return DataLoader(reader, batch_size=batch_size, sharding=sharding,
                          **(loader_kwargs or {}))

    # -- lifecycle -----------------------------------------------------------

    def delete(self):
        """Delete the cached Parquet files.

        Parity: reference ``SparkDatasetConverter.delete``.
        """
        fs, path = get_filesystem_and_path_or_paths(self.cache_dir_url)
        try:
            fs.rm(path, recursive=True)
        except FileNotFoundError:
            pass
        with _CACHE_LOCK:
            for key, meta in list(_CACHED_CONVERTERS.items()):
                if meta.cache_dir_url == self.cache_dir_url:
                    del _CACHED_CONVERTERS[key]


class _ReaderScope(object):
    """Context manager pairing a tf.data dataset with its reader lifetime."""

    def __init__(self, dataset, reader):
        self._dataset = dataset
        self._reader = reader

    def __enter__(self):
        return self._dataset

    def __exit__(self, exc_type, exc_value, tb):
        self._reader.stop()
        self._reader.join()


def make_spark_converter(df, parent_cache_dir_url=None, parquet_row_group_size_bytes=32 << 20,
                         compression_codec=None, dtype='float32'):
    """Materialize ``df`` to Parquet under the parent cache dir (deduplicated
    by analyzed-plan hash) and return a :class:`SparkDatasetConverter`.

    Parity: reference ``make_spark_converter`` — type normalization
    (``VectorUDT`` -> array via ``vector_to_array``, float precision cast),
    plan-hash dedup, atexit GC.  Requires pyspark.
    """
    try:
        from pyspark.ml.functions import vector_to_array
        from pyspark.sql import functions as F
        from pyspark.sql import types as T
    except ImportError as e:
        raise ImportError(
            'make_spark_converter requires pyspark (optional extra). The cached-'
            'Parquet side (SparkDatasetConverter(cache_dir_url, size)) works '
            'without it.') from e

    spark = df.sparkSession
    parent_cache_dir_url = parent_cache_dir_url or spark.conf.get(
        SparkDatasetConverter.PARENT_CACHE_DIR_URL_CONF, None)
    if not parent_cache_dir_url:
        raise ValueError('Specify parent_cache_dir_url or set spark conf %r'
                         % SparkDatasetConverter.PARENT_CACHE_DIR_URL_CONF)

    # Normalize: ML vectors -> arrays, float64 -> requested precision.
    for field in df.schema.fields:
        type_name = type(field.dataType).__name__
        if type_name == 'VectorUDT':
            df = df.withColumn(field.name, vector_to_array(F.col(field.name), dtype=dtype))
        elif isinstance(field.dataType, T.DoubleType) and dtype == 'float32':
            df = df.withColumn(field.name, F.col(field.name).cast(T.FloatType()))

    plan_hash = hashlib.sha1(
        df._jdf.queryExecution().analyzed().toString().encode('utf-8')).hexdigest()

    def materialize(cache_dir_url):
        writer = df.write.option('parquet.block.size', parquet_row_group_size_bytes)
        if compression_codec:
            writer = writer.option('compression', compression_codec)
        writer.parquet(cache_dir_url)
        return df.count()

    return _get_or_materialize(plan_hash, parent_cache_dir_url,
                               parquet_row_group_size_bytes, materialize)


def _get_or_materialize(cache_key, parent_cache_dir_url, row_group_size_bytes,
                        materialize_fn):
    """Dedup-or-materialize shared by the Spark and pandas converters.

    ``materialize_fn(cache_dir_url) -> row_count`` writes the Parquet copy.
    Concurrent callers with the same key may both materialize; the loser's
    directory is deleted and the winner's registration is returned, so no
    orphan dir ever escapes the atexit GC.
    """
    with _CACHE_LOCK:
        cached = _CACHED_CONVERTERS.get(cache_key)
    if cached is not None:
        return SparkDatasetConverter(cached.cache_dir_url, cached.row_count)

    cache_dir_url = '%s/%s' % (parent_cache_dir_url.rstrip('/'), uuid.uuid4().hex)
    row_count = materialize_fn(cache_dir_url)
    meta = CachedDataFrameMeta(cache_key, cache_dir_url, row_count, row_group_size_bytes)
    with _CACHE_LOCK:
        winner = _CACHED_CONVERTERS.setdefault(cache_key, meta)
    if winner is not meta:
        try:
            fs, path = get_filesystem_and_path_or_paths(cache_dir_url)
            fs.rm(path, recursive=True)
        except Exception:  # noqa: BLE001 — losing copy is best-effort cleanup
            logger.warning('Failed to remove raced cache dir %s', cache_dir_url)
        return SparkDatasetConverter(winner.cache_dir_url, winner.row_count)
    return SparkDatasetConverter(cache_dir_url, row_count)


def make_pandas_converter(df, parent_cache_dir_url, parquet_row_group_size_bytes=32 << 20,
                          compression_codec=None, dtype='float32'):
    """Spark-free twin of :func:`make_spark_converter` for pandas DataFrames.

    No reference equivalent (the reference is Spark-only here); this is the
    TPU-VM-native "DataFrame → training data in two lines" path: materialize
    ``df`` to cached Parquet (content-hash dedup, atexit GC) and hand back
    the same :class:`SparkDatasetConverter` loader surface
    (``make_jax_loader`` / ``make_tf_dataset`` / ``make_torch_dataloader``).
    """
    import numpy as np
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    if dtype == 'float32':
        def narrow(a):
            return a.astype(np.float32) \
                if isinstance(a, np.ndarray) and a.dtype == np.float64 else a
        for name in df.columns:
            if df[name].dtype == np.float64:
                df = df.assign(**{name: df[name].astype(np.float32)})
            elif df[name].dtype == object:
                df = df.assign(**{name: df[name].map(narrow)})

    # Cache key covers values AND schema (column names/dtypes) AND the
    # materialization config — content-only hashing would alias frames that
    # differ in any of those and hand back Parquet with the wrong shape or
    # under the wrong cache root.  Numeric columns hash vectorized; only
    # object columns pay a per-cell map (ndarray/list cells -> bytes).
    def cell_key(v):
        if isinstance(v, np.ndarray):
            return v.tobytes()
        if isinstance(v, (list, tuple)):
            return repr(v)
        return v

    hasher = hashlib.sha1()
    hasher.update(repr([parent_cache_dir_url, parquet_row_group_size_bytes,
                        compression_codec, list(df.columns),
                        [str(t) for t in df.dtypes]]).encode('utf-8'))
    for name in df.columns:
        col = df[name]
        if col.dtype == object:
            col = col.map(cell_key)
        hasher.update(pd.util.hash_pandas_object(col, index=False).values.tobytes())
    content_hash = hasher.hexdigest()

    def materialize(cache_dir_url):
        fs, path = get_filesystem_and_path_or_paths(cache_dir_url)
        fs.makedirs(path, exist_ok=True)
        columns = {}
        for name in df.columns:
            has_arrays = df[name].dtype == object and any(
                isinstance(c, np.ndarray) for c in df[name])
            if has_arrays:  # array cells -> arrow lists (None cells -> null)
                columns[name] = pa.array(
                    [c.ravel().tolist() if isinstance(c, np.ndarray) else None
                     for c in df[name]])
            else:
                columns[name] = pa.array(df[name])
        table = pa.table(columns)
        row_bytes = max(1, table.nbytes // max(1, table.num_rows))
        with fs.open(path + '/part_00000.parquet', 'wb') as out:
            pq.write_table(table, out,
                           row_group_size=max(1, parquet_row_group_size_bytes // row_bytes),
                           compression=compression_codec or 'snappy')
        return len(df)

    return _get_or_materialize(content_hash, parent_cache_dir_url,
                               parquet_row_group_size_bytes, materialize)


@atexit.register
def _cleanup_cache_dirs():
    """GC cache dirs at interpreter exit (parity: reference atexit cleanup)."""
    with _CACHE_LOCK:
        metas = list(_CACHED_CONVERTERS.values())
        _CACHED_CONVERTERS.clear()
    for meta in metas:
        try:
            fs, path = get_filesystem_and_path_or_paths(meta.cache_dir_url)
            fs.rm(path, recursive=True)
        except Exception:  # noqa: BLE001 — best-effort exit GC
            logger.warning('Failed to GC converter cache dir %s', meta.cache_dir_url)
