"""Regenerate/repair footer metadata for datasets written without the writer.

Parity: reference ``petastorm/etl/petastorm_generate_metadata.py ::
generate_petastorm_metadata`` (console script
``petastorm-generate-metadata``) — there it spins a local Spark session;
here it is a pure pyarrow pass.
"""

import argparse
import importlib

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.dataset_metadata import _write_common_metadata, get_schema
from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
from petastorm_tpu.unischema import Unischema


def generate_petastorm_metadata(dataset_url, unischema_class=None, storage_options=None,
                                filesystem=None):
    """Stamp ``_common_metadata`` (schema pickle + row-group map) onto an
    existing Parquet directory.

    ``unischema_class``: dotted path to a ``Unischema`` instance (e.g.
    ``examples.mnist.generate_petastorm_mnist.MnistSchema``).  When omitted,
    the existing footer schema is reused (metadata refresh after appends) or,
    failing that, inferred from the arrow schema (scalar fields only).
    """
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem)

    if unischema_class is not None:
        module_path, _, attr = unischema_class.rpartition('.')
        schema = getattr(importlib.import_module(module_path), attr)
        if not isinstance(schema, Unischema):
            raise ValueError('%r is not a Unischema instance' % (unischema_class,))
    else:
        try:
            schema = get_schema(fs, path)
        except MetadataError:
            import sys
            import pyarrow as pa
            from petastorm_tpu.etl.dataset_metadata import infer_or_load_unischema
            schema = infer_or_load_unischema(fs, path)
            binary_fields = [n for n, f in schema.fields.items()
                             if f.codec_or_default.arrow_dtype() in (pa.binary(), pa.string())
                             and f.shape == ()]
            if binary_fields:
                print('WARNING: schema inferred from arrow types only — binary columns %s '
                      'will read back as raw bytes (codec metadata cannot be inferred). '
                      'Pass --unischema-class to restore tensor/image decoding.'
                      % binary_fields, file=sys.stderr)
    _write_common_metadata(fs, path, schema)
    return schema


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('dataset_url')
    parser.add_argument('--unischema-class', default=None,
                        help='Dotted path to the Unischema instance to stamp')
    args = parser.parse_args(argv)
    schema = generate_petastorm_metadata(args.dataset_url, args.unischema_class)
    print('Stamped metadata for schema %s onto %s' % (schema.name, args.dataset_url))


if __name__ == '__main__':
    main()
