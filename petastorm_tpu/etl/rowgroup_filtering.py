"""``filters=`` support: prune row groups by Parquet statistics and hive
partition values before any data I/O.

Parity: the reference forwards ``filters=`` to pyarrow's legacy
``ParquetDataset`` (``petastorm/reader.py :: make_batch_reader(filters=...)``).
Modern pyarrow dropped that plumbing for externally-enumerated row groups, so
we evaluate the same DNF filter expressions ourselves against row-group
min/max statistics — a strictly-at-init, conservative prune (a kept row group
may still contain non-matching rows; predicates handle row-level filtering).

Filter format (pyarrow-compatible DNF): ``[(col, op, value), ...]`` (ANDed)
or ``[[...], [...]]`` (OR of ANDs); ops: ``= == != < > <= >= in not in``.
"""


import pyarrow.parquet as pq

__all__ = ['apply_arrow_filters']


def apply_arrow_filters(fs, pieces, filters, schema):
    if not filters:
        return pieces
    dnf = _normalize_dnf(filters)
    stats = _StatisticsReader(fs)
    return [p for p in pieces if _piece_matches(p, dnf, stats)]


def _normalize_dnf(filters):
    if not isinstance(filters, list) or not filters:
        raise ValueError('filters must be a non-empty list')
    if isinstance(filters[0], tuple):
        return [filters]
    return filters


class _StatisticsReader(object):
    """Caches per-file parquet metadata; returns {column: (min, max, has_nulls)}."""

    def __init__(self, fs):
        self._fs = fs
        self._cache = {}

    def row_group_stats(self, path, row_group):
        md = self._cache.get(path)
        if md is None:
            with self._fs.open(path, 'rb') as f:
                md = pq.ParquetFile(f).metadata
            self._cache[path] = md
        rg = md.row_group(row_group)
        stats = {}
        for i in range(rg.num_columns):
            col = rg.column(i)
            s = col.statistics
            if s is not None and s.has_min_max:
                stats[col.path_in_schema] = (s.min, s.max)
        return stats


def _piece_matches(piece, dnf, stats_reader):
    partition_values = dict(piece.partition_values)
    stats = None
    for conjunction in dnf:
        ok = True
        for col, op, value in conjunction:
            if col in partition_values:
                if not _evaluate_exact(partition_values[col], op, value):
                    ok = False
                    break
                continue
            if stats is None:
                stats = stats_reader.row_group_stats(piece.path, piece.row_group)
            rng = stats.get(col)
            if rng is None:
                continue  # no statistics: cannot prune, keep conservative
            if not _range_may_match(rng, op, value):
                ok = False
                break
        if ok:
            return True
    return False


def _evaluate_exact(actual, op, value):
    # Hive partition values are strings on disk; coerce the string to the
    # comparand's type (or the type of a set element for in/not-in).
    template = next(iter(value), None) if isinstance(value, (list, set, tuple)) else value
    value_cast = _coerce_like(template, actual) if template is not None else actual
    if op in ('=', '=='):
        return value_cast == value
    if op == '!=':
        return value_cast != value
    if op == '<':
        return value_cast < value
    if op == '>':
        return value_cast > value
    if op == '<=':
        return value_cast <= value
    if op == '>=':
        return value_cast >= value
    if op == 'in':
        return value_cast in value
    if op == 'not in':
        return value_cast not in value
    raise ValueError('Unsupported filter op %r' % (op,))


def _coerce_like(template, actual):
    try:
        return type(template)(actual)
    except (TypeError, ValueError):
        return actual


def _range_may_match(rng, op, value):
    lo, hi = rng
    try:
        if op in ('=', '=='):
            return lo <= value <= hi
        if op == '!=':
            return not (lo == value == hi)
        if op == '<':
            return lo < value
        if op == '>':
            return hi > value
        if op == '<=':
            return lo <= value
        if op == '>=':
            return hi >= value
        if op == 'in':
            return any(lo <= v <= hi for v in value)
        if op == 'not in':
            return not all(lo == v == hi for v in value)
    except TypeError:
        return True  # incomparable types: keep conservative
    raise ValueError('Unsupported filter op %r' % (op,))
