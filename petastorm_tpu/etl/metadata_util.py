"""Inspect dataset footer metadata from the command line.

Parity: reference ``petastorm/etl/metadata_util.py`` (print/inspect CLI).
"""

import argparse

from petastorm_tpu.etl.dataset_metadata import (ROW_GROUPS_PER_FILE_KEY, UNISCHEMA_KEY,
                                                _read_common_metadata, get_schema,
                                                load_row_groups)
from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths


def print_dataset_metadata(dataset_url, print_values=False):
    fs, path = get_filesystem_and_path_or_paths(dataset_url)
    arrow_schema = _read_common_metadata(fs, path)
    if arrow_schema is None:
        print('No _common_metadata at %s' % dataset_url)
        return
    meta = arrow_schema.metadata or {}
    print('Footer keys: %s' % sorted(meta))
    if UNISCHEMA_KEY in meta:
        schema = get_schema(fs, path)
        print('Unischema %r:' % schema.name)
        for name, field in schema.fields.items():
            print('  %-24s %-12s shape=%-16s codec=%s nullable=%s'
                  % (name, str(field.numpy_dtype), field.shape,
                     type(field.codec).__name__ if field.codec else None,
                     field.nullable))
    if ROW_GROUPS_PER_FILE_KEY in meta:
        pieces = load_row_groups(fs, path)
        print('Row groups: %d across %d files'
              % (len(pieces), len({p.path for p in pieces})))


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('dataset_url')
    parser.add_argument('--print-values', action='store_true')
    args = parser.parse_args(argv)
    print_dataset_metadata(args.dataset_url, args.print_values)


if __name__ == '__main__':
    main()
