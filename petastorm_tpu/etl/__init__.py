"""ETL plane: dataset writing, footer metadata, row-group indexing.

Parity: reference ``petastorm/etl/``.  The reference's write path is Spark;
ours is a pyarrow ``ParquetWriter`` (Spark optional), because TPU-VM hosts
run no JVM.
"""

from petastorm_tpu.etl.dataset_metadata import (  # noqa: F401
    materialize_dataset, materialize_dataset_pyarrow, get_schema,
    get_schema_from_dataset_url, infer_or_load_unischema, load_row_groups,
    RowGroupPiece,
)
