"""Indexer objects: scan decoded rows, build value -> row-group-id maps.

Parity: reference ``petastorm/etl/rowgroup_indexers.py :: SingleFieldIndexer``.
"""

from collections import defaultdict

__all__ = ['SingleFieldIndexer', 'FieldNotPresentError']


class FieldNotPresentError(ValueError):
    pass


class SingleFieldIndexer(object):
    """Inverted index over one field: ``value -> {row-group ordinals}``."""

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._index_field = index_field
        self._index_data = defaultdict(set)

    @property
    def index_name(self):
        return self._index_name

    @property
    def index_field(self):
        return self._index_field

    #: Field names this indexer must read (reader-side column pruning).
    def get_field_names(self):
        return [self._index_field]

    def build_index(self, decoded_rows, piece_ordinal):
        if not decoded_rows:
            return
        for row in decoded_rows:
            if self._index_field not in row:
                raise FieldNotPresentError(
                    'Field %r not present while indexing' % (self._index_field,))
            value = row[self._index_field]
            if value is not None:
                self._index_data[value].add(piece_ordinal)

    def indexed_values(self):
        return list(self._index_data.keys())

    def get_row_group_indexes(self, value_key=None):
        if value_key is None:
            out = set()
            for groups in self._index_data.values():
                out |= groups
            return out
        return self._index_data.get(value_key, set())

    def __getstate__(self):
        # defaultdict with a lambda-free factory pickles fine, but freeze to a
        # plain dict for cross-implementation stability of the footer blob.
        state = self.__dict__.copy()
        state['_index_data'] = dict(self._index_data)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._index_data = defaultdict(set, self._index_data)
