"""Dataset footer metadata: write-side generation, read-side loading.

Parity: reference ``petastorm/etl/dataset_metadata.py :: materialize_dataset,
get_schema, get_schema_from_dataset_url, infer_or_load_unischema,
load_row_groups`` and its footer key constants.  The footer key strings are
kept byte-identical to the reference's so datasets written by real petastorm
read unmodified, and datasets we write are readable by it (codec classes
unpickle via the module-rename shim below).

Write path difference (TPU-first): the reference requires a live Spark
session; ours is a pyarrow ``ParquetWriter`` wrapped by
:func:`materialize_dataset_pyarrow` / :class:`DatasetWriter`.  A
Spark-compatible ``materialize_dataset`` context manager is still provided
for hosts that do have pyspark.
"""

import json
import logging
import pickle
import posixpath
from petastorm_tpu.utils.locks import make_lock
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager, suppress
from dataclasses import dataclass

import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
from petastorm_tpu.unischema import Unischema, encode_row

logger = logging.getLogger(__name__)

# Byte-identical to the reference's keys (petastorm/etl/dataset_metadata.py)
# for on-disk compatibility.
UNISCHEMA_KEY = b'dataset-toolkit.unischema.v1'
ROW_GROUPS_PER_FILE_KEY = b'dataset-toolkit.num_row_groups_per_file.v1'
#: Our extension (not in the reference): per-file list of per-row-group ROW
#: counts, so epoch sizing never has to re-open file footers.
ROW_GROUP_ROW_COUNTS_KEY = b'petastorm-tpu.rowgroup_row_counts.v1'

_COMMON_METADATA = '_common_metadata'


@dataclass(frozen=True)
class RowGroupPiece:
    """One unit of read work: a single row group of a single file.

    Parity: the reference's pyarrow ``ParquetDatasetPiece`` usage in
    ``load_row_groups``; modern pyarrow dropped that class, so we carry our
    own (also what travels to pool workers, so it stays tiny and picklable).
    """
    path: str            # filesystem path of the parquet file
    row_group: int       # row-group ordinal within the file
    num_rows: int = -1   # row count when known from metadata (-1 = unknown)
    partition_values: tuple = ()  # ((key, value), ...) from dir partitioning


# -- legacy pickle compatibility ---------------------------------------------

_MODULE_RENAMES = {
    'petastorm.unischema': 'petastorm_tpu.unischema',
    'petastorm.codecs': 'petastorm_tpu.codecs',
}

_pyspark_stub_cache = {}


def _pyspark_stub(module, name):
    """A lightweight stand-in for a pyspark class referenced by a reference
    pickle (``ScalarCodec._spark_type`` holds DataType instances).

    Real petastorm footers are written on Spark clusters, but TPU-VM images
    ship no pyspark — without this, such datasets cannot even unpickle.  The
    stub only needs to (a) instantiate under any pickle protocol, (b) accept
    BUILD state, and (c) duck-type ``typeName`` with the pyspark class name,
    which is exactly what ``ScalarCodec.__setstate__`` -> ``_normalize``
    consumes to recover the arrow storage type.
    """
    key = (module, name)
    if key not in _pyspark_stub_cache:
        @classmethod
        def type_name(cls):
            return cls.__name__[:-4].lower() if cls.__name__.endswith('Type') \
                else cls.__name__.lower()

        _pyspark_stub_cache[key] = type(name, (object,), {
            '__module__': module,
            '__init__': lambda self, *a, **kw: None,
            'typeName': type_name,
            '__repr__': lambda self: '%s()' % type(self).__name__,
        })
    return _pyspark_stub_cache[key]


class _CompatUnpickler(pickle.Unpickler):
    """Unpickles Unischemas written by the reference implementation by
    remapping its module paths onto ours, and satisfying pyspark lookups with
    stub classes when pyspark is not installed (SURVEY.md §7 footer-compat
    risk; reference ``petastorm/codecs.py :: ScalarCodec.spark_dtype``)."""

    def find_class(self, module, name):
        if module == 'pyspark.sql.types' or module.startswith('pyspark.sql.types.'):
            try:
                return super().find_class(module, name)
            except (ImportError, AttributeError):
                return _pyspark_stub(module, name)
        return super().find_class(_MODULE_RENAMES.get(module, module), name)


def _loads_schema(blob):
    import io
    return _CompatUnpickler(io.BytesIO(blob)).load()


# -- filesystem helpers ------------------------------------------------------

def _list_parquet_files(fs, path):
    """All data files under ``path``, excluding metadata/hidden files."""
    if fs.isfile(path):
        return [path]
    files = sorted(f for f in fs.find(path)
                   if not _is_metadata_or_hidden(f))
    return files


def _is_metadata_or_hidden(path):
    base = posixpath.basename(path)
    return base.startswith('_') or base.startswith('.') or base.endswith('.crc')


def _partition_values_for(path, root):
    """Extract hive-style key=value directory partition values."""
    rel = path[len(root):].lstrip('/')
    values = []
    for part in rel.split('/')[:-1]:
        if '=' in part:
            key, _, value = part.partition('=')
            values.append((key, value))
    return tuple(values)


# -- write side --------------------------------------------------------------

class DatasetWriter(object):
    """Streaming Spark-free dataset writer.

    Encodes row dicts through the schema's codecs and writes Parquet with
    controlled row-group sizing, then stamps the petastorm-compatible footer
    metadata.  Replaces the reference's Spark
    ``dataframe.write.parquet`` + ``materialize_dataset`` pair for TPU-VM
    hosts.

    Usage::

        with DatasetWriter(url, MySchema, rowgroup_size_mb=64) as w:
            for row in rows:
                w.write(row)

    Multi-host materialization (the pod analog of the reference's
    Spark-executor parallel write): every host writes its own shard of rows
    into the SAME directory with a distinct ``part_prefix`` (e.g.
    ``'part_h%03d' % jax.process_index()``) and ``stamp_metadata=False``,
    then — after a barrier (``parallel.sync_hosts()``) — exactly one host
    stamps the footer over the whole directory with
    :func:`materialize_dataset_pyarrow` or the
    ``petastorm-tpu-generate-metadata`` CLI.  ``stamp_metadata=False`` is
    REQUIRED for concurrent writers: the stamp scans the whole directory,
    and a default per-host ``close()`` stamp would race other hosts'
    still-open part files.
    """

    def __init__(self, dataset_url, schema, rowgroup_size_mb=None,
                 rows_per_rowgroup=None, rows_per_file=None, compression='snappy',
                 storage_options=None, filesystem=None, workers=0,
                 part_prefix='part', stamp_metadata=True):
        if rowgroup_size_mb is not None and rows_per_rowgroup is not None:
            raise ValueError('Pass rowgroup_size_mb or rows_per_rowgroup, not both')
        if workers < 0:
            raise ValueError('workers must be >= 0')
        if not isinstance(part_prefix, str):
            raise ValueError('part_prefix must be a str, got %r'
                             % (type(part_prefix).__name__,))
        if '/' in part_prefix or not part_prefix:
            raise ValueError('part_prefix must be a non-empty file-name prefix')
        if part_prefix[0] in '_.':
            # The dataset file lister treats leading '_'/'.' as
            # metadata/hidden — such parts would write fine and then be
            # invisible to the footer stamp and every reader.
            raise ValueError("part_prefix must not start with '_' or '.'")
        self._schema = schema
        self._arrow_schema = schema.as_arrow_schema()
        self._rowgroup_size_mb = rowgroup_size_mb
        self._rows_per_rowgroup = rows_per_rowgroup
        self._rows_per_file = rows_per_file
        # Codec cells that are already compressed (JPEG/PNG images, zlib
        # ndarrays) gain nothing from parquet-level compression — snappy over
        # them is pure CPU burned on every read.  Per-column override: NONE
        # for those, the requested codec for everything else.
        if isinstance(compression, str):
            from petastorm_tpu.codecs import (CompressedImageCodec,
                                              CompressedNdarrayCodec)
            precompressed = [
                name for name, f in schema.fields.items()
                if isinstance(f.codec, (CompressedImageCodec,
                                        CompressedNdarrayCodec))]
            if precompressed:
                compression = dict.fromkeys(schema.fields, compression)
                for name in precompressed:
                    compression[name] = 'NONE'
        self._compression = compression
        self._part_prefix = part_prefix
        self._stamp_metadata = bool(stamp_metadata)
        self._fs, self._path = get_filesystem_and_path_or_paths(
            dataset_url, storage_options=storage_options, filesystem=filesystem)
        self._buffer = []        # encoded dicts, or Futures when workers > 0
        self._buffer_nbytes = 0  # bytes of *resolved* rows (async: a floor)
        self._accounted = 0      # prefix of self._buffer already in _buffer_nbytes
        self._file_index = 0
        self._writer = None
        self._sink = None
        self._rows_in_file = 0
        self._closed = False
        # Codec encode (cv2 JPEG/PNG, zlib) releases the GIL, so a thread
        # pool parallelizes the CPU-heavy half of materialization — the
        # TPU-host stand-in for the reference's Spark-executor write
        # parallelism (petastorm/etl/dataset_metadata.py ::
        # materialize_dataset runs the encode on Spark workers).  Parquet
        # serialization stays ordered on the caller thread.
        self._executor = None
        if workers:
            self._executor = ThreadPoolExecutor(
                workers, thread_name_prefix='pt-writer-encode')
            self._max_pending = max(8, 4 * workers)

    # -- row API -------------------------------------------------------------

    def write(self, row_dict):
        """Encode and buffer one row; may flush a row group.

        With ``workers > 0`` the codec encode runs on the writer's thread
        pool, so a bad row surfaces at the flush that includes it (or at
        ``close()``), not necessarily at this call.  The dict is shallow-
        copied at submit time (rebinding keys on a reused dict is safe),
        but array *contents* are read when the encode runs — don't mutate
        a cell's buffer in place after passing it.
        """
        if self._executor is not None:
            self._buffer.append(
                self._executor.submit(encode_row, self._schema,
                                      dict(row_dict)))
            # Backpressure: never hold more than max_pending un-encoded rows
            # (bounds memory when the producer outruns the encoders).
            if len(self._buffer) - self._accounted > self._max_pending:
                self._account_resolved(block_one=True)
            else:
                self._account_resolved()
        else:
            encoded = encode_row(self._schema, row_dict)
            self._buffer.append(encoded)
            self._buffer_nbytes += self._row_nbytes(encoded)
            self._accounted += 1
        if self._rowgroup_ready():
            # Size-triggered flushes write only the accounted prefix so the
            # group lands at the target size; row-count mode needs the whole
            # buffer (its trigger counts every buffered row).
            self._flush_rowgroup(
                only_accounted=self._rows_per_rowgroup is None)

    def write_many(self, rows):
        for row in rows:
            self.write(row)

    @staticmethod
    def _row_nbytes(encoded):
        return sum(len(v) if isinstance(v, (bytes, bytearray)) else 8
                   for v in encoded.values() if v is not None)

    def _account_resolved(self, block_one=False):
        """Fold completed futures (an in-order prefix) into the byte count."""
        while self._accounted < len(self._buffer):
            fut = self._buffer[self._accounted]
            if not (block_one or fut.done()):
                break
            self._buffer_nbytes += self._row_nbytes(fut.result())
            self._accounted += 1
            block_one = False

    def _rowgroup_ready(self):
        if self._rows_per_rowgroup is not None:
            return len(self._buffer) >= self._rows_per_rowgroup
        limit = ((self._rowgroup_size_mb if self._rowgroup_size_mb is not None
                  else 32)) * (1 << 20)
        if self._executor is not None and self._accounted:
            # Size-based flushing needs a current byte count, but blocking on
            # every pending future would serialize the pipeline.  Only when
            # the running per-row average says the limit is within reach do
            # we block-resolve until the resolved bytes actually confirm it
            # (or the estimate falls back under) — otherwise lagging
            # encoders would let the buffer overshoot the target row-group
            # size by the whole backpressure window.
            avg = self._buffer_nbytes / self._accounted
            while (self._buffer_nbytes < limit
                   and self._accounted < len(self._buffer)
                   and self._buffer_nbytes
                   + avg * (len(self._buffer) - self._accounted) >= limit):
                self._account_resolved(block_one=True)
                avg = self._buffer_nbytes / self._accounted
        return self._buffer_nbytes >= limit

    def _flush_rowgroup(self, only_accounted=False):
        """Write buffered rows as one row group.

        ``only_accounted`` (size-triggered flushes with ``workers > 0``)
        writes just the byte-accounted prefix — the still-pending tail
        stays buffered for the next group, so the written group honors the
        size target instead of swallowing the whole backpressure window.
        """
        if only_accounted and self._executor is not None:
            rows = [f.result() for f in self._buffer[:self._accounted]]
            rest = self._buffer[self._accounted:]
        elif self._executor is not None:
            rows, rest = [f.result() for f in self._buffer], []
        else:
            rows, rest = self._buffer, []
        if not rows:
            return
        columns = {name: [row.get(name) for row in rows]
                   for name in self._schema.fields}
        table = pa.table(
            {name: pa.array(columns[name], type=self._arrow_schema.field(name).type)
             for name in self._schema.fields},
            schema=self._arrow_schema)
        if self._writer is None or (self._rows_per_file is not None
                                    and self._rows_in_file >= self._rows_per_file):
            self._roll_file()
        self._writer.write_table(table)  # one write_table call == one row group
        self._rows_in_file += len(rows)
        self._buffer = rest
        self._buffer_nbytes = 0
        self._accounted = 0

    def _close_current_file(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._sink is not None:
            self._sink.close()  # flush fsspec buffers; footer must hit storage
            self._sink = None

    def _roll_file(self):
        self._close_current_file()
        self._fs.makedirs(self._path, exist_ok=True)
        name = posixpath.join(self._path, '%s_%05d.parquet'
                              % (self._part_prefix, self._file_index))
        self._file_index += 1
        self._rows_in_file = 0
        self._sink = self._fs.open(name, 'wb')
        self._writer = pq.ParquetWriter(self._sink, self._arrow_schema,
                                        compression=self._compression)

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        if self._closed:
            return
        try:
            self._flush_rowgroup()
        except BaseException:
            self._abort()
            raise
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        self._close_current_file()
        self._closed = True
        if self._stamp_metadata:
            _write_common_metadata(self._fs, self._path, self._schema)

    def _abort(self):
        """Teardown after a failed write/flush: release the pool and file
        handles, drop buffered rows, and mark the writer closed WITHOUT
        stamping footer metadata — a partially-written dataset must not
        read as valid, and a retried ``close()`` must not crash on leftover
        futures or mask the original error."""
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None
        self._buffer = []
        self._accounted = 0
        self._buffer_nbytes = 0
        try:
            # A sink failing to close (e.g. a broken remote stream) must not
            # replace the root-cause error this teardown runs under.
            with suppress(Exception):
                self._close_current_file()
        finally:
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        if exc_type is None:
            self.close()
        else:
            self._abort()


def write_dataset(schema, rows, dataset_url, **kwargs):
    """One-shot convenience over :class:`DatasetWriter`."""
    with DatasetWriter(dataset_url, schema, **kwargs) as writer:
        writer.write_many(rows)


@contextmanager
def materialize_dataset_pyarrow(dataset_url, schema, storage_options=None, filesystem=None):
    """Context manager stamping footer metadata around any pyarrow-based
    write the caller performs into ``dataset_url``."""
    yield
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem)
    _write_common_metadata(fs, path, schema)


@contextmanager
def materialize_dataset(spark, dataset_url, schema, row_group_size_mb=None,
                        use_summary_metadata=False, filesystem_factory=None,
                        storage_options=None):
    """Spark-parity context manager.

    Parity: ``petastorm/etl/dataset_metadata.py :: materialize_dataset`` —
    sets ``parquet.block.size`` on entry, stamps footer metadata on exit.
    Works with ``spark=None`` for non-Spark writers (then equivalent to
    :func:`materialize_dataset_pyarrow`).
    """
    if spark is not None and row_group_size_mb is not None:
        hadoop_conf = spark.sparkContext._jsc.hadoopConfiguration()
        hadoop_conf.setInt('parquet.block.size', row_group_size_mb << 20)
    yield
    filesystem = filesystem_factory() if filesystem_factory is not None else None
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem)
    _write_common_metadata(fs, path, schema)


def _collect_rowgroup_counts(fs, path, files=None):
    files = files if files is not None else _list_parquet_files(fs, path)

    def count(f):
        with fs.open(f, 'rb') as handle:
            md = pq.ParquetFile(handle).metadata
            return (posixpath.relpath(f, path), md.num_row_groups,
                    [md.row_group(i).num_rows for i in range(md.num_row_groups)])

    with ThreadPoolExecutor(max_workers=min(16, max(1, len(files)))) as pool:
        scanned = list(pool.map(count, files))
    return ({rel: n for rel, n, _ in scanned},
            {rel: rows for rel, _, rows in scanned})


def read_row_group_num_rows(fs, file_row_groups):
    """Total rows of ``{path: [row_group_index, ...]}`` via a threaded footer
    scan — the shared slow path behind ``Reader.num_local_rows`` (fast path:
    counts stamped in the footer at materialize time)."""

    def scan(item):
        path, row_groups = item
        with fs.open(path, 'rb') as handle:
            md = pq.ParquetFile(handle).metadata
            return sum(md.row_group(i).num_rows for i in row_groups)

    if not file_row_groups:
        return 0
    with ThreadPoolExecutor(max_workers=min(16, len(file_row_groups))) as pool:
        return sum(pool.map(scan, file_row_groups.items()))


def read_row_group_byte_sizes(fs, paths):
    """``{(path, row_group_index): total_byte_size}`` for every row group
    of the given files, via a threaded footer scan (one open per file).

    The adaptive scheduler's epoch-0 cost prior (ISSUE 9): compressed
    byte size is the one cheaply-knowable signal that separates a
    mixed-resolution JPEG row group from its neighbors before a single
    piece has been timed.
    """

    def scan(path):
        with fs.open(path, 'rb') as handle:
            md = pq.ParquetFile(handle).metadata
            return [(path, i, md.row_group(i).total_byte_size)
                    for i in range(md.num_row_groups)]

    paths = sorted(set(paths))
    if not paths:
        return {}
    with ThreadPoolExecutor(max_workers=min(16, len(paths))) as pool:
        return {(path, rg): size
                for found in pool.map(scan, paths)
                for path, rg, size in found}


def _write_common_metadata(fs, path, schema):
    """Write ``_common_metadata`` carrying the pickled Unischema and the
    per-file row-group count map (reference-compatible footer keys), plus the
    per-row-group ROW counts under our own key so readers never re-open
    footers just to size an epoch."""
    counts, row_counts = _collect_rowgroup_counts(fs, path)
    files = _list_parquet_files(fs, path)
    if files:
        with fs.open(files[0], 'rb') as handle:
            arrow_schema = pq.ParquetFile(handle).schema_arrow
    else:
        arrow_schema = schema.as_arrow_schema()
    metadata = dict(arrow_schema.metadata or {})
    metadata[UNISCHEMA_KEY] = pickle.dumps(schema, protocol=4)
    metadata[ROW_GROUPS_PER_FILE_KEY] = json.dumps(counts).encode('utf-8')
    metadata[ROW_GROUP_ROW_COUNTS_KEY] = json.dumps(row_counts).encode('utf-8')
    annotated = arrow_schema.with_metadata(metadata)
    with fs.open(posixpath.join(path, _COMMON_METADATA), 'wb') as out:
        pq.write_metadata(annotated, out)


# -- read side ---------------------------------------------------------------

def _read_common_metadata(fs, path):
    meta_path = posixpath.join(path, _COMMON_METADATA)
    if not fs.exists(meta_path):
        return None
    with fs.open(meta_path, 'rb') as handle:
        return pq.read_schema(handle)


def get_schema(fs, path):
    """Load the pickled Unischema from the dataset footer.

    Parity: ``petastorm/etl/dataset_metadata.py :: get_schema``.  Raises
    :class:`MetadataError` when absent (the reference tells users to run its
    metadata-generation CLI; so do we).
    """
    arrow_schema = _read_common_metadata(fs, path)
    if arrow_schema is None or not arrow_schema.metadata \
            or UNISCHEMA_KEY not in arrow_schema.metadata:
        raise MetadataError(
            'Dataset at %r has no petastorm metadata (missing %s footer key). '
            'If it was written without materialize_dataset, run '
            'petastorm-tpu-generate-metadata to add it.' % (path, UNISCHEMA_KEY))
    return _loads_schema(arrow_schema.metadata[UNISCHEMA_KEY])


def get_schema_from_dataset_url(dataset_url, storage_options=None, filesystem=None):
    """Parity: ``petastorm/etl/dataset_metadata.py :: get_schema_from_dataset_url``."""
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem)
    return get_schema(fs, path)


def infer_or_load_unischema(fs, path):
    """Stored Unischema when present, else inferred from the arrow schema
    (scalar columns only), as for vanilla Parquet stores.

    Parity: ``petastorm/etl/dataset_metadata.py :: infer_or_load_unischema``.
    """
    try:
        return get_schema(fs, path)
    except MetadataError:
        pass
    except Exception as e:  # legacy pickle needing pyspark, version skew, ...
        logger.warning('Failed to unpickle stored Unischema (%s); inferring from '
                       'arrow schema instead', e)
    files = _list_parquet_files(fs, path)
    if not files:
        raise MetadataError('No parquet files found under %r' % (path,))
    with fs.open(files[0], 'rb') as handle:
        arrow_schema = pq.ParquetFile(handle).schema_arrow
    return Unischema.from_arrow_schema(arrow_schema)


def load_row_groups(fs, path, fast_from_metadata=True):
    """Enumerate all row-group pieces of the dataset.

    Uses the footer's per-file row-group count map when present (no file
    footers opened — the point of the metadata); otherwise scans file footers
    in a thread pool.

    Parity: ``petastorm/etl/dataset_metadata.py :: load_row_groups`` incl.
    the fallback hierarchy (summary metadata -> per-file footers).
    """
    files = _list_parquet_files(fs, path)
    if not files:
        raise MetadataError('No parquet files found under %r' % (path,))

    counts = row_counts = None
    if fast_from_metadata:
        arrow_schema = _read_common_metadata(fs, path)
        if arrow_schema is not None and arrow_schema.metadata \
                and ROW_GROUPS_PER_FILE_KEY in arrow_schema.metadata:
            counts = json.loads(arrow_schema.metadata[ROW_GROUPS_PER_FILE_KEY].decode('utf-8'))
            if ROW_GROUP_ROW_COUNTS_KEY in arrow_schema.metadata:
                row_counts = json.loads(
                    arrow_schema.metadata[ROW_GROUP_ROW_COUNTS_KEY].decode('utf-8'))

    pieces = []
    if counts is not None:
        present = {posixpath.relpath(f, path): f for f in files}
        for rel, n in sorted(counts.items()):
            full = present.get(rel)
            if full is None:
                logger.warning('File %r in footer metadata is missing on disk; skipping', rel)
                continue
            parts = _partition_values_for(full, path)
            per_rg = (row_counts or {}).get(rel)
            per_rg = per_rg if per_rg is not None and len(per_rg) == int(n) else None
            pieces.extend(
                RowGroupPiece(full, i, per_rg[i] if per_rg else -1, parts)
                for i in range(int(n)))
        return pieces

    lock = make_lock('etl.dataset_metadata.load_row_groups.lock')

    def scan(f):
        with fs.open(f, 'rb') as handle:
            md = pq.ParquetFile(handle).metadata
            found = [RowGroupPiece(f, i, md.row_group(i).num_rows,
                                   _partition_values_for(f, path))
                     for i in range(md.num_row_groups)]
        with lock:
            pieces.extend(found)

    with ThreadPoolExecutor(max_workers=min(16, len(files))) as pool:
        list(pool.map(scan, files))
    pieces.sort(key=lambda p: (p.path, p.row_group))
    return pieces
