"""Offline inverted-index build over row groups; stored in the dataset footer.

Parity: reference ``petastorm/etl/rowgroup_indexing.py ::
build_rowgroup_index, get_row_group_indexes`` and its footer key
``dataset-toolkit.rowgroups_index.v1`` (kept byte-identical for on-disk
compatibility).  Consumed at reader init by ``petastorm_tpu/selectors.py``
to prune row groups before any data I/O.
"""

import pickle
import zlib
from concurrent.futures import ThreadPoolExecutor

import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.dataset_metadata import (_COMMON_METADATA, _read_common_metadata,
                                                get_schema, load_row_groups)
from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
from petastorm_tpu.utils import decode_row

ROWGROUPS_INDEX_KEY = b'dataset-toolkit.rowgroups_index.v1'


def build_rowgroup_index(dataset_url, spark_context=None, indexers=None,
                         storage_options=None, filesystem=None):
    """Scan the dataset once, feed every row group through ``indexers``, and
    persist the pickled index map into the footer.

    ``spark_context`` is accepted for signature parity with the reference but
    unused: the scan runs on a host thread pool (no JVM on TPU-VM hosts).
    """
    if not indexers:
        raise ValueError('indexers must be a non-empty list')
    fs, path = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem)
    schema = get_schema(fs, path)
    pieces = load_row_groups(fs, path)

    needed_fields = sorted({name for ix in indexers for name in ix.get_field_names()})
    missing = [n for n in needed_fields if n not in schema.fields]
    if missing:
        raise ValueError('Indexed fields %s not in schema' % missing)

    def scan(ordinal_piece):
        ordinal, piece = ordinal_piece
        with fs.open(piece.path, 'rb') as f:
            table = pq.ParquetFile(f).read_row_group(piece.row_group,
                                                     columns=needed_fields)
        rows = [decode_row(r, schema) for r in table.to_pylist()]
        return ordinal, rows

    with ThreadPoolExecutor(max_workers=8) as pool:
        for ordinal, rows in pool.map(scan, enumerate(pieces)):
            for indexer in indexers:
                indexer.build_index(rows, ordinal)

    index_map = {ix.index_name: ix for ix in indexers}
    _write_footer_key(fs, path, ROWGROUPS_INDEX_KEY,
                      zlib.compress(pickle.dumps(index_map, protocol=4)))
    return index_map


def get_row_group_indexes(fs, path):
    """Load the pickled ``{index_name: indexer}`` map from the footer."""
    arrow_schema = _read_common_metadata(fs, path)
    if arrow_schema is None or not arrow_schema.metadata \
            or ROWGROUPS_INDEX_KEY not in arrow_schema.metadata:
        raise MetadataError(
            'Dataset at %r has no row-group index (footer key %s); run '
            'build_rowgroup_index first' % (path, ROWGROUPS_INDEX_KEY))
    return pickle.loads(zlib.decompress(arrow_schema.metadata[ROWGROUPS_INDEX_KEY]))


def _write_footer_key(fs, path, key, value):
    arrow_schema = _read_common_metadata(fs, path)
    if arrow_schema is None:
        raise MetadataError('Dataset at %r has no _common_metadata' % (path,))
    metadata = dict(arrow_schema.metadata or {})
    metadata[key] = value
    import posixpath
    with fs.open(posixpath.join(path, _COMMON_METADATA), 'wb') as out:
        pq.write_metadata(arrow_schema.with_metadata(metadata), out)
