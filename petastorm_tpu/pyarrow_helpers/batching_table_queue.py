"""Re-batch a stream of arrow tables into fixed-size batches.

Parity: reference ``petastorm/pyarrow_helpers/batching_table_queue.py ::
BatchingTableQueue`` — feeds ``BatchedDataLoader``; slicing stays in arrow
(zero-copy) until the consumer materializes numpy/torch tensors.
"""

from collections import deque

import pyarrow as pa


class BatchingTableQueue(object):
    """``put(table)`` arrow tables in; ``get()`` fixed-``batch_size`` tables out."""

    def __init__(self, batch_size):
        if batch_size <= 0:
            raise ValueError('batch_size must be positive')
        self._batch_size = batch_size
        self._tables = deque()   # (table, start_row)
        self._available = 0

    def put(self, table):
        if table.num_rows:
            self._tables.append((table, 0))
            self._available += table.num_rows

    def empty(self):
        return self._available < self._batch_size

    def get(self):
        """Next full batch as a single arrow table; raises if not ready."""
        if self.empty():
            raise IndexError('fewer than batch_size rows buffered')
        parts = []
        need = self._batch_size
        while need > 0:
            table, start = self._tables.popleft()
            avail = table.num_rows - start
            take = min(avail, need)
            parts.append(table.slice(start, take))  # zero-copy
            if take < avail:
                self._tables.appendleft((table, start + take))
            need -= take
        self._available -= self._batch_size
        return parts[0] if len(parts) == 1 else pa.concat_tables(parts)

    def drain(self):
        """Remaining rows (< batch_size) as one table, or None."""
        if self._available == 0:
            return None
        parts = [t.slice(start) for t, start in self._tables]
        self._tables.clear()
        self._available = 0
        return parts[0] if len(parts) == 1 else pa.concat_tables(parts)
