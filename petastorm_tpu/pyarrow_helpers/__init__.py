"""pyarrow plumbing helpers."""
