"""Pallas TPU flash attention (forward + backward), MXU-tiled.

Block-wise online-softmax attention: the [seq, seq] score matrix is never
materialised — each grid step holds one ``block_q × block_k`` tile in VMEM,
folding it into running (max, denominator, output) accumulators in fp32
while the matmuls feed the MXU in the input dtype.  The backward pass is
the standard flash recomputation split into a dQ kernel (grid over Q
blocks) and a dK/dV kernel (grid over K blocks), using the saved
log-sum-exp instead of stored probabilities.

Used standalone and as the ``attn_fn`` inside
``petastorm_tpu.parallel.ulysses_attention`` (each device's local full-
sequence attention after the all-to-all) — the composition that makes long
context cheap: Ulysses moves the data, this kernel keeps HBM traffic at
O(seq · head_dim).

K and V are CHUNKED: each kernel call holds one ``kv_chunk`` (default 8k
rows) of K/V in VMEM, and chunks are folded at the XLA level with the same
normalized-(output, lse) merge the ring fold uses — so a single device
streams arbitrary ``seq_len`` (the old ~8k VMEM cliff is gone; beyond one
device's FLOPs, shard with ring/Ulysses).  The backward pass streams the
same way: dQ accumulates over K/V chunks, dK/dV over Q chunks, all against
the global lse/delta.

No reference equivalent (the reference has no compute kernels at all,
SURVEY.md §2.6).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Shared with ring attention so masked-softmax semantics never diverge.
from petastorm_tpu.parallel.ring_attention import NEG_INF


def _auto_interpret():
    return jax.default_backend() != 'tpu'


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal,
                seq_len, block_q, block_k, packed, k_start, kv_blocks):
    if packed:
        sq_ref, sk_ref, o_ref, lse_ref = refs
    else:
        o_ref, lse_ref = refs
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    d = q.shape[-1]

    # ``k_ref`` holds one K/V CHUNK starting at absolute position
    # ``k_start`` (k_start=0, kv_blocks=whole-sequence for the unchunked
    # call); all masks work in absolute positions so chunked calls fold
    # into exactly the unchunked result.
    num_kv = jnp.minimum(kv_blocks,
                         jnp.maximum(0, pl.cdiv(seq_len - k_start, block_k)))
    if causal:
        # Blocks strictly above the diagonal contribute nothing.
        num_kv = jnp.minimum(num_kv, jnp.maximum(
            0, pl.cdiv((qi + 1) * block_q - k_start, block_k)))

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        o, l, m = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = k_start + kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len  # padded keys never attend
        if causal:
            mask &= q_pos >= k_pos
        if packed:
            # Packed rows: queries only see keys of their own NONZERO
            # segment (0 marks padding in both roles).
            sq = sq_ref[0, 0]                                   # [block_q]
            sk = sk_ref[0, 0, pl.ds(kb * block_k, block_k)]     # [block_k]
            mask &= (sq[:, None] == sk[None, :]) & (sq[:, None] != 0)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - m_new))
        p = jnp.where(m_new[:, None] == NEG_INF, 0.0, jnp.exp(s - m_new[:, None]))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return o_new, l_new, m_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, num_kv, body, (o0, l0, m0))

    l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
    # lse rides as [bh, 1, seq]: a (1, 1, block_q) block keeps the last-two
    # block dims Mosaic-legal (second-to-last equals the full array dim).
    lse_ref[0, 0] = lse.astype(jnp.float32)


def _fwd(q3, k3, v3, seg3, seg3_k, scale, causal, seq_len, block_q, block_k,
         packed, heads, interpret, k_start=0):
    """One forward kernel call: full Q against the K/V chunk ``k3``/``v3``
    (absolute start ``k_start``).  ``seg3`` is the q-side segment array
    (full length), ``seg3_k`` the k-side chunk slice."""
    bh, seq_pad, d = q3.shape
    kv_pad = k3.shape[1]
    grid = (bh, seq_pad // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, kv_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, kv_pad, d), lambda i, j: (i, 0, 0)),
    ]
    args = [q3, k3, v3]
    if packed:
        # seg3 is [batch, 1, seq_pad]; every head of a batch row shares it,
        # so the index map folds the (batch*heads) grid axis back down.
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i // heads, 0, j)),
            pl.BlockSpec((1, 1, kv_pad), lambda i, j: (i // heads, 0, 0)),
        ]
        args += [seg3, seg3_k]
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_k=block_k,
                          packed=packed, k_start=k_start,
                          kv_blocks=kv_pad // block_k),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_pad, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq_pad), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


def _fold_normalized(o1, lse1, o2, lse2):
    """Merge two normalized partial attentions (softmax weight exp(lse)).

    The chunk-level analog of the ring hop fold: o = Σ o_i·exp(lse_i) /
    Σ exp(lse_i), with fully-masked (lse == NEG_INF) parts contributing
    exactly zero.  ``o*`` are [bh, seq, d] fp32, ``lse*`` [bh, 1, seq]."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m == NEG_INF, 0.0, m)
    w1 = jnp.where(lse1 == NEG_INF, 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(lse2 == NEG_INF, 0.0, jnp.exp(lse2 - m_safe))
    denom = w1 + w2
    safe = jnp.where(denom == 0.0, 1.0, denom)
    wa = jnp.swapaxes(w1 / safe, 1, 2)          # [bh, seq, 1]
    wb = jnp.swapaxes(w2 / safe, 1, 2)
    o = o1 * wa + o2 * wb
    lse = jnp.where(denom == 0.0, NEG_INF, m_safe + jnp.log(safe))
    return o, lse


def _fwd_chunked(q3, k3, v3, seg3, scale, causal, seq_len, block_q, block_k,
                 packed, heads, interpret, kv_chunk):
    """Stream K/V through the forward kernel in ``kv_chunk`` slices.

    VMEM per call is one chunk instead of the whole sequence — the piece
    that removes the single-device seq-length cliff.  Accumulation stays
    fp32 across folds; the final cast matches the unchunked kernel."""
    bh, seq_pad, d = q3.shape
    o = None
    lse = None
    for c0 in range(0, seq_pad, kv_chunk):
        c1 = min(c0 + kv_chunk, seq_pad)
        k_c = jax.lax.slice_in_dim(k3, c0, c1, axis=1)
        v_c = jax.lax.slice_in_dim(v3, c0, c1, axis=1)
        seg_k = (jax.lax.slice_in_dim(seg3, c0, c1, axis=2)
                 if packed else None)
        o_c, lse_c = _fwd(q3, k_c, v_c, seg3, seg_k, scale, causal, seq_len,
                          block_q, block_k, packed, heads, interpret,
                          k_start=c0)
        o_c = o_c.astype(jnp.float32)
        if o is None:
            o, lse = o_c, lse_c
        else:
            o, lse = _fold_normalized(o, lse, o_c, lse_c)
    return o.astype(q3.dtype), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   scale, causal, seq_len, block_q, block_k, packed,
                   k_start, kv_blocks):
    if packed:
        sq_ref, sk_ref, dq_ref = refs
    else:
        (dq_ref,) = refs
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]       # [block_q]
    delta = delta_ref[0, 0]   # [block_q]
    d = q.shape[-1]

    # Chunk-relative K/V (absolute start ``k_start``): dq contributions
    # against the GLOBAL lse/delta are additive across chunks.
    num_kv = jnp.minimum(kv_blocks,
                         jnp.maximum(0, pl.cdiv(seq_len - k_start, block_k)))
    if causal:
        num_kv = jnp.minimum(num_kv, jnp.maximum(
            0, pl.cdiv((qi + 1) * block_q - k_start, block_k)))
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = k_start + kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # Padded query rows carry lse == NEG_INF; without the q_pos guard
        # exp(s - NEG_INF) overflows to inf and poisons ds with NaNs.
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask &= q_pos >= k_pos
        if packed:
            sq = sq_ref[0, 0]
            sk = sk_ref[0, 0, pl.ds(kb * block_k, block_k)]
            mask &= (sq[:, None] == sk[None, :]) & (sq[:, None] != 0)
        # exp(s - lse) == softmax row (lse = m + log l); masked/empty rows
        # have lse == NEG_INF and p underflows to 0.
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kv, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    scale, causal, seq_len, block_q, block_k, packed,
                    q_start, k_start, q_blocks):
    if packed:
        sq_ref, sk_ref, dk_ref, dv_ref = refs
    else:
        dk_ref, dv_ref = refs
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)  # [block_k, d]
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]

    # ``q_ref``/``do_ref``/``lse_ref``/``delta_ref`` hold one Q chunk
    # (absolute start ``q_start``); k blocks are chunk-relative with
    # absolute start ``k_start``.  dk/dv contributions against the global
    # lse/delta are additive across Q chunks.
    num_q = jnp.minimum(q_blocks,
                        jnp.maximum(0, pl.cdiv(seq_len - q_start, block_q)))
    if causal:
        q_begin = jnp.clip((k_start + ki * block_k - q_start) // block_q,
                           0, num_q)
    else:
        q_begin = 0
    k_pos = k_start + ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(q_blk, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = q_start + qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask &= q_pos >= k_pos
        if packed:
            sq_blk = sq_ref[0, 0, pl.ds(qb * block_q, block_q)]
            sk = sk_ref[0, 0]
            mask &= (sq_blk[:, None] == sk[None, :]) & (sq_blk[:, None] != 0)
        p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(p, do_blk, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_blk, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_begin, num_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_call(q3, k_c, v_c, seg3, seg_k, do3, lse, delta, scale, causal,
                 seq_len, block_q, block_k, packed, heads, interpret,
                 k_start):
    """dQ contribution of one K/V chunk (full Q streamed block-by-block)."""
    bh, seq_pad, d = q3.shape
    kv_pad = k_c.shape[1]
    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, kv_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, kv_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
    ]
    dq_args = [q3, k_c, v_c, do3, lse, delta]
    if packed:
        dq_specs += [
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i // heads, 0, j)),
            pl.BlockSpec((1, 1, kv_pad), lambda i, j: (i // heads, 0, 0)),
        ]
        dq_args += [seg3, seg_k]
    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_k=block_k,
                          packed=packed, k_start=k_start,
                          kv_blocks=kv_pad // block_k),
        grid=(bh, seq_pad // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_pad, d), q3.dtype),
        interpret=interpret,
    )(*dq_args)


def _bwd_dkv_call(q_c, k_c, v_c, seg_q, seg_k, do_c, lse_c, delta_c, scale,
                  causal, seq_len, block_q, block_k, packed, heads, interpret,
                  q_start, k_start):
    """dK/dV contribution of one Q chunk against one K/V chunk."""
    bh, q_pad, d = q_c.shape
    kv_pad = k_c.shape[1]
    dkv_specs = [
        pl.BlockSpec((1, q_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, q_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, 1, q_pad), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, 1, q_pad), lambda i, j: (i, 0, 0)),
    ]
    dkv_args = [q_c, k_c, v_c, do_c, lse_c, delta_c]
    if packed:
        dkv_specs += [
            pl.BlockSpec((1, 1, q_pad), lambda i, j: (i // heads, 0, 0)),
            pl.BlockSpec((1, 1, block_k), lambda i, j: (i // heads, 0, j)),
        ]
        dkv_args += [seg_q, seg_k]
    return pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_k=block_k,
                          packed=packed, q_start=q_start, k_start=k_start,
                          q_blocks=q_pad // block_q),
        grid=(bh, kv_pad // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_pad, d), k_c.dtype),
            jax.ShapeDtypeStruct((bh, kv_pad, d), v_c.dtype),
        ],
        interpret=interpret,
    )(*dkv_args)


def _bwd(q3, k3, v3, seg3, o3, lse, do3, scale, causal, seq_len, block_q,
         block_k, packed, heads, interpret, kv_chunk=None):
    """Backward pass, K/V (and Q, for dK/dV) streamed in chunks.

    Per-chunk contributions computed against the GLOBAL lse/delta are
    plain sums — no softmax refold needed in the backward direction."""
    bh, seq_pad, d = q3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bh, 1, seq] like lse
    chunk = kv_chunk if kv_chunk is not None else seq_pad
    chunk = min(chunk, seq_pad)

    def sl(x, lo, hi, axis=1):
        return jax.lax.slice_in_dim(x, lo, hi, axis=axis)

    dq = None
    dk_parts, dv_parts = [], []
    for c0 in range(0, seq_pad, chunk):
        c1 = min(c0 + chunk, seq_pad)
        k_c, v_c = sl(k3, c0, c1), sl(v3, c0, c1)
        seg_k = sl(seg3, c0, c1, axis=2) if packed else None
        dq_c = _bwd_dq_call(q3, k_c, v_c, seg3, seg_k, do3, lse, delta,
                            scale, causal, seq_len, block_q, block_k, packed,
                            heads, interpret, k_start=c0)
        # Partials accumulate in fp32 at the XLA level (the single-call
        # path accumulates in fp32 inside the kernel; chunking must not
        # lose that).
        dq_c = dq_c.astype(jnp.float32)
        dq = dq_c if dq is None else dq + dq_c
        dk_c = None
        dv_c = None
        for r0 in range(0, seq_pad, chunk):
            r1 = min(r0 + chunk, seq_pad)
            if causal and r1 <= c0:
                continue  # whole Q chunk above the diagonal: contributes 0
            dkc, dvc = _bwd_dkv_call(
                sl(q3, r0, r1), k_c, v_c,
                sl(seg3, r0, r1, axis=2) if packed else None, seg_k,
                sl(do3, r0, r1), sl(lse, r0, r1, axis=2),
                sl(delta, r0, r1, axis=2), scale, causal, seq_len, block_q,
                block_k, packed, heads, interpret, q_start=r0, k_start=c0)
            dkc = dkc.astype(jnp.float32)
            dvc = dvc.astype(jnp.float32)
            dk_c = dkc if dk_c is None else dk_c + dkc
            dv_c = dvc if dv_c is None else dv_c + dvc
        if dk_c is None:  # every Q chunk skipped (can't happen, but safe)
            dk_c = jnp.zeros(k_c.shape, jnp.float32)
            dv_c = jnp.zeros(v_c.shape, jnp.float32)
        dk_parts.append(dk_c)
        dv_parts.append(dv_c)
    dk = dk_parts[0] if len(dk_parts) == 1 else jnp.concatenate(dk_parts, axis=1)
    dv = dv_parts[0] if len(dv_parts) == 1 else jnp.concatenate(dv_parts, axis=1)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q3, k3, v3, seg3, scale, causal, seq_len, block_q, block_k, packed,
           heads, kv_chunk):
    out, _ = _flash_fwd(q3, k3, v3, seg3, scale, causal, seq_len, block_q,
                        block_k, packed, heads, kv_chunk)
    return out


def _flash_fwd(q3, k3, v3, seg3, scale, causal, seq_len, block_q, block_k,
               packed, heads, kv_chunk):
    seq_pad = q3.shape[1]
    if kv_chunk is None or kv_chunk >= seq_pad:
        out, lse = _fwd(q3, k3, v3, seg3, seg3, scale, causal, seq_len,
                        block_q, block_k, packed, heads,
                        interpret=_auto_interpret())
    else:
        out, lse = _fwd_chunked(q3, k3, v3, seg3, scale, causal, seq_len,
                                block_q, block_k, packed, heads,
                                interpret=_auto_interpret(),
                                kv_chunk=kv_chunk)
    return out, (q3, k3, v3, seg3, out, lse)


def _flash_bwd(scale, causal, seq_len, block_q, block_k, packed, heads,
               kv_chunk, res, g):
    import numpy as _np
    q3, k3, v3, seg3, out, lse = res
    dq, dk, dv = _bwd(q3, k3, v3, seg3, out, lse, g, scale, causal, seq_len,
                      block_q, block_k, packed, heads,
                      interpret=_auto_interpret(), kv_chunk=kv_chunk)
    # Integer operands take a float0 cotangent (segment ids are labels);
    # the non-packed path carries seg3=None (empty pytree, no cotangent).
    dseg = (None if seg3 is None
            else _np.zeros(seg3.shape, dtype=jax.dtypes.float0))
    return dq, dk, dv, dseg


_flash.defvjp(_flash_fwd, _flash_bwd)


#: Above this padded length the forward/backward default to streaming K/V
#: in chunks of this many rows (fp32 d=128: ~8 MB K+V per call — well under
#: VMEM).  Explicit ``kv_chunk`` overrides.
KV_CHUNK_DEFAULT = 8192


def flash_attention(q, k, v, causal=False, scale=None, block_q=128, block_k=128,
                    segment_ids=None, kv_chunk=None):
    """Flash attention over ``[batch, seq, heads, head_dim]`` inputs.

    Drop-in for ``petastorm_tpu.parallel.full_attention`` (same signature and
    semantics, O(seq) memory).  Differentiable via the flash backward
    kernels.  Sequences are padded to the block size internally; padded keys
    are masked out, padded query rows are sliced off.

    ``segment_ids`` (``[batch, seq]`` int, 0 = padding) restricts attention
    to same-nonzero-segment pairs — the O(seq)-memory path for
    ``petastorm_tpu.jax.packing`` packed rows (same semantics as
    ``packing.packed_attention``, which is the dense oracle).

    ``kv_chunk`` streams K/V through VMEM in chunks of that many rows
    (auto-enabled above ``KV_CHUNK_DEFAULT`` padded rows; ``0`` forces the
    old whole-K/V residency), so a single
    device handles arbitrary sequence lengths instead of capping where
    whole-K/V VMEM residency ran out (~8k rows fp32).  The backward pass
    streams the same way (dQ over K/V chunks, dK/dV over Q chunks).

    Compiles to Mosaic on TPU; on CPU/GPU backends it runs the same kernels
    through the Pallas interpreter (tests, dry runs).
    """
    if q.ndim != 4:
        raise ValueError('expected [batch, seq, heads, head_dim], got %r' % (q.shape,))
    b, seq_len, h, d = q.shape
    kv_len = k.shape[1]
    if kv_len != seq_len:
        raise ValueError('flash_attention requires seq_q == seq_kv (got %d vs %d)'
                         % (seq_len, kv_len))
    packed = segment_ids is not None
    if packed and tuple(segment_ids.shape) != (b, seq_len):
        raise ValueError('segment_ids must be [batch, seq] = %r, got %r'
                         % ((b, seq_len), tuple(segment_ids.shape)))
    scale = scale if scale is not None else d ** -0.5

    import math
    block_q = min(block_q, max(seq_len, 16))
    block_k = min(block_k, max(seq_len, 16))
    if not _auto_interpret():
        # Mosaic on real TPU rejects non-tile-aligned layouts: block_q/block_k
        # appear as the minor (lane) dim of the lse/delta blocks, so round UP
        # to a 128-lane multiple.  The Pallas interpreter (CI) accepts any
        # block shape — keep the requested sizes there so small-block tests
        # still exercise multi-block grids and the lcm tail-block logic.
        block_q = -(-block_q // 128) * 128
        block_k = -(-block_k // 128) * 128
    # Pad to the lcm so BOTH grids (seq_pad // block_q, seq_pad // block_k)
    # cover the sequence exactly — padding to max() alone drops tail blocks
    # whenever the smaller block doesn't divide the larger.
    lcm = math.lcm(block_q, block_k)
    seq_pad = -(-seq_len // lcm) * lcm

    def to3(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, seq_len, d)
        if seq_pad != seq_len:
            x = jnp.pad(x, ((0, 0), (0, seq_pad - seq_len), (0, 0)))
        return x

    if packed:
        seg = jnp.asarray(segment_ids, jnp.int32)
        if seq_pad != seq_len:   # pad with 0 = "padding segment"
            seg = jnp.pad(seg, ((0, 0), (0, seq_pad - seq_len)))
        seg3 = seg[:, None, :]   # [b, 1, seq_pad]; heads share via index map
    else:
        seg3 = None

    if kv_chunk is None and seq_pad > KV_CHUNK_DEFAULT:
        kv_chunk = KV_CHUNK_DEFAULT
    if kv_chunk == 0:
        kv_chunk = None      # explicit 0: whole-K/V residency, no streaming
    elif kv_chunk is not None:
        # chunk boundaries must land on both block grids
        kv_chunk = max(lcm, (int(kv_chunk) // lcm) * lcm)
        if kv_chunk >= seq_pad:
            kv_chunk = None

    out = _flash(to3(q), to3(k), to3(v), seg3, scale, causal, seq_len,
                 block_q, block_k, packed, h, kv_chunk)
    out = out[:, :seq_len].reshape(b, h, seq_len, d)
    return jnp.moveaxis(out, 1, 2)
