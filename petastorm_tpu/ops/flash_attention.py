"""Pallas TPU flash attention (forward + backward), MXU-tiled.

Block-wise online-softmax attention: the [seq, seq] score matrix is never
materialised — each grid step holds one ``block_q × block_k`` tile in VMEM,
folding it into running (max, denominator, output) accumulators in fp32
while the matmuls feed the MXU in the input dtype.  The backward pass is
the standard flash recomputation split into a dQ kernel (grid over Q
blocks) and a dK/dV kernel (grid over K blocks), using the saved
log-sum-exp instead of stored probabilities.

Used standalone and as the ``attn_fn`` inside
``petastorm_tpu.parallel.ulysses_attention`` (each device's local full-
sequence attention after the all-to-all) — the composition that makes long
context cheap: Ulysses moves the data, this kernel keeps HBM traffic at
O(seq · head_dim).

K and V live whole in VMEM per (batch·head) grid step, so the practical
per-device sequence limit is ~8k at head_dim 128 fp32 (half the ~16 MB
VMEM); shard longer sequences with ring/Ulysses first.

No reference equivalent (the reference has no compute kernels at all,
SURVEY.md §2.6).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Shared with ring attention so masked-softmax semantics never diverge.
from petastorm_tpu.parallel.ring_attention import NEG_INF


def _auto_interpret():
    return jax.default_backend() != 'tpu'


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal,
                seq_len, block_q, block_k, packed):
    if packed:
        sq_ref, sk_ref, o_ref, lse_ref = refs
    else:
        o_ref, lse_ref = refs
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    d = q.shape[-1]

    num_kv = pl.cdiv(seq_len, block_k)
    if causal:
        # Blocks strictly above the diagonal contribute nothing.
        num_kv = jnp.minimum(num_kv, pl.cdiv((qi + 1) * block_q, block_k))

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        o, l, m = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_len  # padded keys never attend
        if causal:
            mask &= q_pos >= k_pos
        if packed:
            # Packed rows: queries only see keys of their own NONZERO
            # segment (0 marks padding in both roles).
            sq = sq_ref[0, 0]                                   # [block_q]
            sk = sk_ref[0, 0, pl.ds(kb * block_k, block_k)]     # [block_k]
            mask &= (sq[:, None] == sk[None, :]) & (sq[:, None] != 0)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - m_new))
        p = jnp.where(m_new[:, None] == NEG_INF, 0.0, jnp.exp(s - m_new[:, None]))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return o_new, l_new, m_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, num_kv, body, (o0, l0, m0))

    l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
    # lse rides as [bh, 1, seq]: a (1, 1, block_q) block keeps the last-two
    # block dims Mosaic-legal (second-to-last equals the full array dim).
    lse_ref[0, 0] = lse.astype(jnp.float32)


def _fwd(q3, k3, v3, seg3, scale, causal, seq_len, block_q, block_k, packed,
         heads, interpret):
    bh, seq_pad, d = q3.shape
    grid = (bh, seq_pad // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, seq_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, seq_pad, d), lambda i, j: (i, 0, 0)),
    ]
    args = [q3, k3, v3]
    if packed:
        # seg3 is [batch, 1, seq_pad]; every head of a batch row shares it,
        # so the index map folds the (batch*heads) grid axis back down.
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i // heads, 0, j)),
            pl.BlockSpec((1, 1, seq_pad), lambda i, j: (i // heads, 0, 0)),
        ]
        args += [seg3, seg3]
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_k=block_k,
                          packed=packed),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_pad, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq_pad), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   scale, causal, seq_len, block_q, block_k, packed):
    if packed:
        sq_ref, sk_ref, dq_ref = refs
    else:
        (dq_ref,) = refs
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]       # [block_q]
    delta = delta_ref[0, 0]   # [block_q]
    d = q.shape[-1]

    num_kv = pl.cdiv(seq_len, block_k)
    if causal:
        num_kv = jnp.minimum(num_kv, pl.cdiv((qi + 1) * block_q, block_k))
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        # Padded query rows carry lse == NEG_INF; without the q_pos guard
        # exp(s - NEG_INF) overflows to inf and poisons ds with NaNs.
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask &= q_pos >= k_pos
        if packed:
            sq = sq_ref[0, 0]
            sk = sk_ref[0, 0, pl.ds(kb * block_k, block_k)]
            mask &= (sq[:, None] == sk[None, :]) & (sq[:, None] != 0)
        # exp(s - lse) == softmax row (lse = m + log l); masked/empty rows
        # have lse == NEG_INF and p underflows to 0.
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kv, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    scale, causal, seq_len, block_q, block_k, packed):
    if packed:
        sq_ref, sk_ref, dk_ref, dv_ref = refs
    else:
        dk_ref, dv_ref = refs
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)  # [block_k, d]
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]

    num_q = pl.cdiv(seq_len, block_q)
    q_start = (ki * block_k) // block_q if causal else 0
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jax.lax.dot_general(q_blk, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        mask = (k_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask &= q_pos >= k_pos
        if packed:
            sq_blk = sq_ref[0, 0, pl.ds(qb * block_q, block_q)]
            sk = sk_ref[0, 0]
            mask &= (sq_blk[:, None] == sk[None, :]) & (sq_blk[:, None] != 0)
        p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(p, do_blk, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_blk, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_start, num_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q3, k3, v3, seg3, o3, lse, do3, scale, causal, seq_len, block_q,
         block_k, packed, heads, interpret):
    bh, seq_pad, d = q3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bh, 1, seq] like lse

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, seq_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, seq_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
    ]
    dq_args = [q3, k3, v3, do3, lse, delta]
    if packed:
        dq_specs += [
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i // heads, 0, j)),
            pl.BlockSpec((1, 1, seq_pad), lambda i, j: (i // heads, 0, 0)),
        ]
        dq_args += [seg3, seg3]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_k=block_k,
                          packed=packed),
        grid=(bh, seq_pad // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_pad, d), q3.dtype),
        interpret=interpret,
    )(*dq_args)

    dkv_specs = [
        pl.BlockSpec((1, seq_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, seq_pad, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, 1, seq_pad), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, 1, seq_pad), lambda i, j: (i, 0, 0)),
    ]
    dkv_args = [q3, k3, v3, do3, lse, delta]
    if packed:
        dkv_specs += [
            pl.BlockSpec((1, 1, seq_pad), lambda i, j: (i // heads, 0, 0)),
            pl.BlockSpec((1, 1, block_k), lambda i, j: (i // heads, 0, j)),
        ]
        dkv_args += [seg3, seg3]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_k=block_k,
                          packed=packed),
        grid=(bh, seq_pad // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_pad, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, seq_pad, d), v3.dtype),
        ],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(q3, k3, v3, seg3, scale, causal, seq_len, block_q, block_k, packed,
           heads):
    out, _ = _flash_fwd(q3, k3, v3, seg3, scale, causal, seq_len, block_q,
                        block_k, packed, heads)
    return out


def _flash_fwd(q3, k3, v3, seg3, scale, causal, seq_len, block_q, block_k,
               packed, heads):
    out, lse = _fwd(q3, k3, v3, seg3, scale, causal, seq_len, block_q,
                    block_k, packed, heads, interpret=_auto_interpret())
    return out, (q3, k3, v3, seg3, out, lse)


def _flash_bwd(scale, causal, seq_len, block_q, block_k, packed, heads, res,
               g):
    import numpy as _np
    q3, k3, v3, seg3, out, lse = res
    dq, dk, dv = _bwd(q3, k3, v3, seg3, out, lse, g, scale, causal, seq_len,
                      block_q, block_k, packed, heads,
                      interpret=_auto_interpret())
    # Integer operands take a float0 cotangent (segment ids are labels);
    # the non-packed path carries seg3=None (empty pytree, no cotangent).
    dseg = (None if seg3 is None
            else _np.zeros(seg3.shape, dtype=jax.dtypes.float0))
    return dq, dk, dv, dseg


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128, block_k=128,
                    segment_ids=None):
    """Flash attention over ``[batch, seq, heads, head_dim]`` inputs.

    Drop-in for ``petastorm_tpu.parallel.full_attention`` (same signature and
    semantics, O(seq) memory).  Differentiable via the flash backward
    kernels.  Sequences are padded to the block size internally; padded keys
    are masked out, padded query rows are sliced off.

    ``segment_ids`` (``[batch, seq]`` int, 0 = padding) restricts attention
    to same-nonzero-segment pairs — the O(seq)-memory path for
    ``petastorm_tpu.jax.packing`` packed rows (same semantics as
    ``packing.packed_attention``, which is the dense oracle).

    Compiles to Mosaic on TPU; on CPU/GPU backends it runs the same kernels
    through the Pallas interpreter (tests, dry runs).
    """
    if q.ndim != 4:
        raise ValueError('expected [batch, seq, heads, head_dim], got %r' % (q.shape,))
    b, seq_len, h, d = q.shape
    kv_len = k.shape[1]
    if kv_len != seq_len:
        raise ValueError('flash_attention requires seq_q == seq_kv (got %d vs %d)'
                         % (seq_len, kv_len))
    packed = segment_ids is not None
    if packed and tuple(segment_ids.shape) != (b, seq_len):
        raise ValueError('segment_ids must be [batch, seq] = %r, got %r'
                         % ((b, seq_len), tuple(segment_ids.shape)))
    scale = scale if scale is not None else d ** -0.5

    import math
    block_q = min(block_q, max(seq_len, 16))
    block_k = min(block_k, max(seq_len, 16))
    if not _auto_interpret():
        # Mosaic on real TPU rejects non-tile-aligned layouts: block_q/block_k
        # appear as the minor (lane) dim of the lse/delta blocks, so round UP
        # to a 128-lane multiple.  The Pallas interpreter (CI) accepts any
        # block shape — keep the requested sizes there so small-block tests
        # still exercise multi-block grids and the lcm tail-block logic.
        block_q = -(-block_q // 128) * 128
        block_k = -(-block_k // 128) * 128
    # Pad to the lcm so BOTH grids (seq_pad // block_q, seq_pad // block_k)
    # cover the sequence exactly — padding to max() alone drops tail blocks
    # whenever the smaller block doesn't divide the larger.
    lcm = math.lcm(block_q, block_k)
    seq_pad = -(-seq_len // lcm) * lcm

    def to3(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, seq_len, d)
        if seq_pad != seq_len:
            x = jnp.pad(x, ((0, 0), (0, seq_pad - seq_len), (0, 0)))
        return x

    if packed:
        seg = jnp.asarray(segment_ids, jnp.int32)
        if seq_pad != seq_len:   # pad with 0 = "padding segment"
            seg = jnp.pad(seg, ((0, 0), (0, seq_pad - seq_len)))
        seg3 = seg[:, None, :]   # [b, 1, seq_pad]; heads share via index map
    else:
        seg3 = None

    out = _flash(to3(q), to3(k), to3(v), seg3, scale, causal, seq_len,
                 block_q, block_k, packed, h)
    out = out[:, :seq_len].reshape(b, h, seq_len, d)
    return jnp.moveaxis(out, 1, 2)
