"""TPU Pallas kernels for the hot compute ops.

No reference equivalent: the reference is a data library with no first-party
native compute (SURVEY.md §2.6); these kernels serve the framework's model
zoo and the sequence-parallel attention plane (``petastorm_tpu.parallel``).
"""

from petastorm_tpu.ops.flash_attention import flash_attention  # noqa: F401
