"""Batch-path worker: one row group -> one columnar batch (arrow table).

Parity: reference ``petastorm/arrow_reader_worker.py :: ArrowReaderWorker,
ArrowReaderWorkerResultsQueueReader`` — whole-row-group arrow reads, column
predicates, pandas TransformSpec, namedtuple-of-numpy-arrays conversion.
This is the fast path: no per-row python loops; numpy columns go straight
into the JAX loader's collate.
"""

from dataclasses import dataclass, field as dataclass_field

import numpy as np
import pyarrow as pa

from petastorm_tpu.cache import NullCache
from petastorm_tpu.reader_impl.parquet_worker_base import ParquetWorkerBase


@dataclass
class BatchWorkerArgs:
    filesystem: object
    pieces: list
    schema: object
    schema_view: object
    transform_spec: object = None
    predicate: object = None
    cache: object = dataclass_field(default_factory=NullCache)
    #: Transient-I/O retries per row group before PoisonedRowGroupError
    #: (SURVEY.md §5.3 build obligation; no reference equivalent).
    read_retries: int = 2
    retry_backoff_s: float = 0.1
    #: Ingest plane (ISSUE 14): the parent reader's IngestPlane, or None
    #: (synchronous reads).  Set by Reader._start after mode resolution.
    ingest: object = None


def piece_cache_key(piece, schema_view, transform_spec):
    """Result-cache key of one batch-path row group.  ``_apply_transform``
    runs before the cache store: the payload is post-transform, so the
    key carries the transform identity.  Module-level for the same
    reason as ``py_dict_reader_worker.piece_cache_key`` — the cluster
    cache tier must reproduce it without constructing a reader."""
    cache_key = '%s:%d:batch:%s' % (piece.path, piece.row_group,
                                    ','.join(sorted(schema_view.fields)))
    token = getattr(transform_spec, 'cache_token', None) \
        if transform_spec is not None else None
    if token:
        cache_key += ':t{%s}' % token
    return cache_key


class ArrowReaderWorker(ParquetWorkerBase):

    #: TransformSpec.func runs at DataFrame level here and may drop rows —
    #: consumed by ``Reader.transform_may_change_row_count`` (epoch_steps
    #: guard).  The row worker applies func per row 1:1, so it stays False.
    DATAFRAME_TRANSFORM = True

    def process(self, piece_index, _row_drop_partition=0):
        piece = self._a.pieces[piece_index]
        cache_key = piece_cache_key(piece, self._a.schema_view,
                                    self._a.transform_spec)
        # The retry/poison classifier wraps only the I/O stage: an ArrowInvalid
        # out of a user transform (e.g. from_pandas on a mixed-type column)
        # must surface as the transform's own error, not as a corrupt file.
        # _ingest_scope releases the plane's prefetched entry on a
        # result-cache HIT (the lambda below never runs then).
        with self._ingest_scope(piece):
            table = self._a.cache.get(
                cache_key,
                lambda: self._apply_transform(
                    self._read_with_retry(piece, lambda: self._read_piece(
                        piece, lambda pf: self._load_table(pf, piece)))))
        if table is not None and table.num_rows > 0:
            self.publish_func(table)

    def _load_table(self, pf, piece):
        physical = set(pf.schema_arrow.names)
        wanted = [n for n in self._a.schema_view.fields if n in physical]
        predicate = self._a.predicate

        if predicate is not None:
            pred_fields = sorted(set(predicate.get_fields()) & physical)
            if not pred_fields:
                raise ValueError('Predicate fields %s not present in files'
                                 % sorted(predicate.get_fields()))
            pred_table = pf.read_row_group(piece.row_group, columns=pred_fields)
            cols = {n: pred_table.column(n).to_pylist() for n in pred_fields}
            mask = np.array([
                predicate.do_include({n: cols[n][i] for n in pred_fields})
                for i in range(pred_table.num_rows)], dtype=bool)
            if not mask.any():
                return None
            table = pf.read_row_group(piece.row_group, columns=wanted)
            table = table.filter(pa.array(mask))
        else:
            table = pf.read_row_group(piece.row_group, columns=wanted)

        # Inject hive partition values as constant columns when requested.
        for key, value in piece.partition_values:
            if key in self._a.schema_view.fields and key not in table.column_names:
                field = self._a.schema_view.fields[key]
                dtype = np.dtype(field.numpy_dtype)
                cast = value if dtype.kind in ('U', 'S', 'O') else dtype.type(value)
                table = table.append_column(key, pa.array([cast] * table.num_rows))

        return table

    def _apply_transform(self, table):
        spec = self._a.transform_spec
        if table is None or spec is None:
            return table
        df = table.to_pandas()
        if spec.func is not None:
            df = spec.func(df)
        for name in spec.removed_fields:
            if name in df.columns:
                df = df.drop(columns=[name])
        if spec.selected_fields is not None:
            df = df[list(spec.selected_fields)]
        return pa.Table.from_pandas(df, preserve_index=False)


class ArrowResultConverter(object):
    """arrow table -> namedtuple of numpy arrays (one batch per row group).

    Parity: ``petastorm/arrow_reader_worker.py ::
    ArrowReaderWorkerResultsQueueReader``.
    """

    def __init__(self, schema):
        self._schema = schema

    def convert(self, table):
        out = {}
        for name in self._schema.fields:
            if name not in table.column_names:
                continue
            column = table.column(name).combine_chunks()
            out[name] = _column_to_numpy(column)
        # Fields produced by a transform but absent from the schema view are
        # still surfaced (schema already includes edit_fields via
        # transform_schema, so normally nothing is dropped here).
        return self._schema.make_namedtuple_from_dict(out)


def _column_to_numpy(column):
    ctype = column.type
    if pa.types.is_list(ctype) or pa.types.is_large_list(ctype):
        # Ragged lists -> 1-D object array of numpy arrays; rectangular when
        # all lengths equal -> 2-D array (the useful case for training).
        pylist = column.to_pylist()
        arrays = [np.asarray(x) if x is not None else None for x in pylist]
        lengths = {a.shape for a in arrays if a is not None}
        if len(lengths) == 1 and None not in pylist:
            return np.stack(arrays)
        out = np.empty(len(arrays), dtype=object)
        out[:] = arrays
        return out
    if pa.types.is_string(ctype) or pa.types.is_large_string(ctype) \
            or pa.types.is_binary(ctype) or pa.types.is_large_binary(ctype):
        return np.asarray(column.to_pylist(), dtype=object)
    return column.to_numpy(zero_copy_only=False)
