"""Example model zoo for the acceptance configs (BASELINE.json):

* ``mlp`` — MNIST (config #1)
* ``resnet`` — ResNet-50 for ImageNet-Parquet (config #3, the flagship)
* ``vit`` — Vision Transformer on the same image pipeline (encoder blocks
  shared with ``transformer``, so TP/FSDP rules apply unchanged)
* ``dlrm`` — Criteo embedding tables (config #4)
* ``transformer`` — long-context LM (sequence/tensor-parallel flagship)
* ``moe`` — Switch-style expert-parallel FFN

The reference ships no models (it is a data library); these exist so the
loader can be proven against real pjit training loops, as its examples do
with TF/torch models.
"""

from petastorm_tpu.models.mlp import MLP  # noqa: F401
from petastorm_tpu.models.resnet import ResNet50  # noqa: F401
from petastorm_tpu.models.transformer import (  # noqa: F401
    TransformerLM, param_shardings, make_attn_fn)
from petastorm_tpu.models.decoding import beam_search, generate  # noqa: F401
from petastorm_tpu.models.vit import ViT  # noqa: F401
