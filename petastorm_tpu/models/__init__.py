"""Example model zoo for the acceptance configs (BASELINE.json):

* ``mlp`` — MNIST (config #1)
* ``resnet`` — ResNet-50 for ImageNet-Parquet (config #3, the flagship)
* ``dlrm`` — Criteo embedding tables (config #4)
* ``transformer`` — long-context LM (sequence/tensor-parallel flagship)

The reference ships no models (it is a data library); these exist so the
loader can be proven against real pjit training loops, as its examples do
with TF/torch models.
"""

from petastorm_tpu.models.mlp import MLP  # noqa: F401
from petastorm_tpu.models.resnet import ResNet50  # noqa: F401
from petastorm_tpu.models.transformer import (  # noqa: F401
    TransformerLM, param_shardings, make_attn_fn)
