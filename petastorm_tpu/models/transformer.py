"""Long-context Transformer LM — the sequence-parallel flagship.

No reference equivalent (the reference ships no models; SURVEY.md §2.6 —
its examples train third-party torch/TF models).  This model exists to
prove the framework's long-context plane end to end: data from
``petastorm_tpu.jax.DataLoader``, attention from
``petastorm_tpu.ops.flash_attention`` (single device) or
``petastorm_tpu.parallel.ring/ulysses`` (sequence-sharded), parameters
sharded Megatron-style over a ``model`` mesh axis.

TPU design notes:
* All matmuls run in bfloat16 on the MXU (``dtype``); accumulation and the
  softmax/norm stats stay fp32.
* ``attn_fn`` is injected, not hard-coded: the module computes q/k/v
  ``[batch, seq, heads, head_dim]`` and delegates — so one model definition
  serves dense oracle, Pallas flash, ring (seq axis over ICI ring via
  ppermute), and Ulysses (all-to-all) without touching the module.
* ``param_shardings`` maps the param pytree onto a mesh: attention/MLP
  input projections shard their *output* features over ``model``; output
  projections shard their *input* features — the Megatron sandwich, which
  leaves XLA exactly one all-reduce per block per direction.
"""

import functools
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.ops import flash_attention


def rope_cos_sin(positions, head_dim, base=10000.0):
    """RoPE rotation tables for ``positions`` [b, s]: cos/sin, each
    [b, s, 1, head_dim/2] — compute once, rotate q AND k with them."""
    if head_dim % 2:
        raise ValueError('RoPE needs an even head_dim, got %d' % head_dim)
    half = head_dim // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [b, s, half]
    return (jnp.cos(angles)[:, :, None, :],
            jnp.sin(angles)[:, :, None, :])


def rope(x, positions=None, base=10000.0, cos_sin=None):
    """Rotary position embedding (GPT-NeoX split-half convention).

    ``x``: [batch, seq, heads, head_dim]; ``positions``: [batch, seq] (or
    pass a precomputed ``cos_sin`` from :func:`rope_cos_sin`).  Rotation
    happens BEFORE the attention delegation, so every attn_fn (dense,
    flash, ring, Ulysses — packed or not) inherits it untouched; with
    ``packing`` positions that restart per document, each packed document
    is rotated as if it started at 0.
    """
    if cos_sin is None:
        cos_sin = rope_cos_sin(positions, x.shape[-1], base)
    cos, sin = cos_sin
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([x1 * cos - x2 * sin,
                               x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param('scale', nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


class Attention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    attn_fn: Callable = flash_attention
    causal: bool = True  # False for encoder use (e.g. models.vit)
    decode: bool = False  # autoregressive KV-cache mode (see models.decoding)
    max_decode_len: int = 2048
    #: Grouped-query attention: K/V projected to this many heads (must
    #: divide num_heads); each K/V head serves num_heads//num_kv_heads
    #: query heads.  The decode cache stores only the KV heads — the
    #: long-context memory win.  None = classic MHA (fused qkv projection,
    #: parameter tree unchanged).
    num_kv_heads: Any = None
    #: 'rope' rotates q/k by position before delegation (cached keys are
    #: stored rotated — standard practice); None = positions handled
    #: upstream (learned table in TransformerLM).
    pos_mode: Any = None

    @nn.compact
    def __call__(self, x, positions=None):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError('d_model %d not divisible by %d heads'
                             % (d_model, self.num_heads))
        head_dim = d_model // self.num_heads
        if self.num_kv_heads is None:
            qkv = nn.DenseGeneral((3, self.num_heads, head_dim), axis=-1,
                                  dtype=self.dtype, name='qkv')(x)
            q, k, v = jnp.moveaxis(qkv, -3, 0)  # each [b, s, h, hd]
        else:
            if self.num_heads % self.num_kv_heads:
                raise ValueError('num_heads %d not divisible by num_kv_heads %d'
                                 % (self.num_heads, self.num_kv_heads))
            q = nn.DenseGeneral((self.num_heads, head_dim), axis=-1,
                                dtype=self.dtype, name='q')(x)
            kv = nn.DenseGeneral((2, self.num_kv_heads, head_dim), axis=-1,
                                 dtype=self.dtype, name='kv')(x)
            k, v = jnp.moveaxis(kv, -3, 0)      # [b, s, h_kv, hd]
        if self.pos_mode == 'rope':
            if positions is None:
                if self.decode:
                    # arange(seq) would rotate every 1-token step at
                    # position 0 — silently wrong; demand real positions.
                    raise ValueError('decode mode with RoPE requires '
                                     'explicit positions')
                positions = jnp.broadcast_to(jnp.arange(x.shape[1]),
                                             x.shape[:2])
            cs = rope_cos_sin(positions, q.shape[-1])  # once for q AND k
            q = rope(q, cos_sin=cs)
            k = rope(k, cos_sin=cs)
        if self.decode:
            out = self._decode_step(q, k, v)
        else:
            k, v = self._expand_kv(k, v)
            out = self.attn_fn(q, k, v, causal=self.causal)
        return nn.DenseGeneral(d_model, axis=(-2, -1), dtype=self.dtype,
                               name='out')(out)

    def _expand_kv(self, k, v):
        """Broadcast KV heads to the query head count (GQA no-op for MHA)."""
        if self.num_kv_heads is None or self.num_kv_heads == self.num_heads:
            return k, v
        g = self.num_heads // self.num_kv_heads
        return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)

    def _decode_step(self, q, k, v):
        """Attention against a fixed-size KV cache (incremental decoding).

        XLA-friendly: the cache is a STATIC ``[b, max_decode_len, h, hd]``
        buffer updated in place with ``dynamic_update_slice``; one-token
        queries attend the whole buffer with future positions masked — no
        shape ever depends on the step index, so the generate loop compiles
        once (``lax.scan`` in ``models.decoding``).  A multi-token call on a
        FRESH cache (index 0) is the classic prefill: it writes the whole
        prompt's K/V and runs ordinary causal attention over just the prompt
        — one MXU-batched forward instead of L sequential steps.  On a WARM
        cache (index > 0 — chunked prefill, cache reuse) the chunk instead
        attends the full cache buffer with absolute-position causal masking,
        so cached history is honored; ``lax.cond`` picks the branch at run
        time without breaking the compile-once property.  Flax init never
        mutates the cache (``is_initializing`` guard), so a freshly
        initialized cache is all-zeros with index 0.
        """
        b, seq, h, hd = q.shape
        h_kv = k.shape[2]   # < h under GQA: the cache memory win
        cache_k = self.variable('cache', 'key', jnp.zeros,
                                (b, self.max_decode_len, h_kv, hd), self.dtype)
        cache_v = self.variable('cache', 'value', jnp.zeros,
                                (b, self.max_decode_len, h_kv, hd), self.dtype)
        index = self.variable('cache', 'index', jnp.zeros, (), jnp.int32)
        i = index.value
        if not self.is_initializing():
            cache_k.value = jax.lax.dynamic_update_slice(
                cache_k.value, k.astype(self.dtype), (0, i, 0, 0))
            cache_v.value = jax.lax.dynamic_update_slice(
                cache_v.value, v.astype(self.dtype), (0, i, 0, 0))
            index.value = i + seq
        q_pos = i + jnp.arange(seq)
        if seq > 1:
            def fresh_prefill(q, k, v):
                # fresh cache: causal attention over just the prompt —
                # cheaper than attending the (empty) full buffer
                k, v = self._expand_kv(k, v)
                return self.attn_fn(q, k, v, causal=True)

            def warm_prefill(q, k, v):
                return self._attend_cache(q, cache_k.value, cache_v.value,
                                          q_pos)
            return jax.lax.cond(i == 0, fresh_prefill, warm_prefill, q, k, v)
        return self._attend_cache(q, cache_k.value, cache_v.value, q_pos)

    def _attend_cache(self, q, ck, cv, q_pos):
        """Attend the static cache buffer at absolute query positions.

        Grouped einsum against the UNEXPANDED cache: per-step HBM reads
        stay at h_kv heads (the actual GQA bandwidth win), accumulation
        in fp32 via preferred_element_type — no repeated/casted copies.
        """
        b, seq, h, hd = q.shape
        h_kv = ck.shape[2]
        g = h // h_kv
        q_g = q.astype(jnp.float32).reshape(b, seq, h_kv, g, hd)
        scores = jnp.einsum('bqkgd,blkd->bkgql', q_g, ck,
                            preferred_element_type=jnp.float32) * hd ** -0.5
        mask = jnp.arange(self.max_decode_len)[None, :] <= q_pos[:, None]
        from petastorm_tpu.parallel.ring_attention import NEG_INF
        scores = jnp.where(mask[None, None, None, :, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bkgql,blkd->bqkgd', probs, cv,
                         preferred_element_type=jnp.float32)
        return out.reshape(b, seq, h, hd).astype(q.dtype)


class Block(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    attn_fn: Callable = flash_attention
    causal: bool = True
    decode: bool = False
    max_decode_len: int = 2048
    num_kv_heads: Any = None
    pos_mode: Any = None

    @nn.compact
    def __call__(self, x, positions=None):
        x = x + Attention(self.num_heads, self.dtype, self.attn_fn,
                          causal=self.causal, decode=self.decode,
                          max_decode_len=self.max_decode_len,
                          num_kv_heads=self.num_kv_heads,
                          pos_mode=self.pos_mode,
                          name='attn')(RMSNorm(name='ln1')(x), positions)
        h = nn.Dense(self.d_ff, dtype=self.dtype, name='ffw_in')(RMSNorm(name='ln2')(x))
        h = nn.gelu(h)
        return x + nn.Dense(x.shape[-1], dtype=self.dtype, name='ffw_out')(h)


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens [batch, seq] -> logits [batch, seq, vocab]."""

    vocab_size: int
    d_model: int = 512
    num_heads: int = 8
    num_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attn_fn: Callable = flash_attention
    remat: bool = False  # jax.checkpoint each block: FLOPs for HBM
    decode: bool = False  # KV-cache incremental mode (models.decoding)
    num_kv_heads: Any = None  # GQA: KV heads < query heads (see Attention)
    pos_embed: str = 'learned'  # 'learned' table | 'rope' rotary q/k

    @nn.compact
    def __call__(self, tokens, positions=None):
        """``positions`` overrides the default row-absolute ``arange``
        positions — pass ``packing.pack_*``'s per-segment ``positions`` so
        each packed document is embedded (or RoPE-rotated) as if it
        started at 0."""
        if self.pos_embed not in ('learned', 'rope'):
            raise ValueError("pos_embed must be 'learned' or 'rope', got %r"
                             % (self.pos_embed,))
        embed = nn.Embed(self.vocab_size, self.d_model, name='embed',
                         dtype=self.dtype)
        x = embed(tokens)
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]),
                                         tokens.shape)
        if self.pos_embed == 'learned':
            pos = nn.Embed(self.max_seq_len, self.d_model, name='pos_embed',
                           dtype=self.dtype)(positions)
            x = x + pos
        block = Block
        if self.remat:
            block = nn.remat(Block)
        rope_mode = 'rope' if self.pos_embed == 'rope' else None
        for i in range(self.num_layers):
            x = block(self.num_heads, self.d_ff, self.dtype, self.attn_fn,
                      decode=self.decode, max_decode_len=self.max_seq_len,
                      num_kv_heads=self.num_kv_heads, pos_mode=rope_mode,
                      name='block_%d' % i)(x, positions)
        x = RMSNorm(name='ln_f')(x)
        # Tied output head: attend() reuses the (vocab-sharded) embedding.
        return embed.attend(x.astype(self.dtype)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------

#: (path-suffix match, PartitionSpec factory) — Megatron TP sandwich.
def _spec_for(path, model_axis):
    names = [p.key for p in path if hasattr(p, 'key')]
    leaf = names[-1] if names else ''
    parent = names[-2] if len(names) > 1 else ''
    if parent in ('embed', 'pos_embed'):
        return P(model_axis, None)             # vocab/position sharded
    if parent == 'qkv':
        # kernel [d_model, 3, heads, head_dim] — shard heads.
        return P(None, None, model_axis, None) if leaf == 'kernel' \
            else P(None, model_axis, None)     # bias [3, heads, head_dim]
    if parent == 'q':
        # GQA query proj: kernel [d_model, heads, head_dim] — shard heads.
        return P(None, model_axis, None) if leaf == 'kernel' \
            else P(model_axis, None)
    if parent == 'kv':
        # GQA kv proj: kernel [d_model, 2, kv_heads, head_dim].  The model
        # axis size must divide kv_heads; param_shardings falls back to
        # replication per leaf when it doesn't (e.g. MQA with kv_heads=1).
        return P(None, None, model_axis, None) if leaf == 'kernel' \
            else P(None, model_axis, None)
    if parent == 'out':
        # kernel [heads, head_dim, d_model] — shard input heads.
        return P(model_axis, None, None) if leaf == 'kernel' else P(None)
    if parent == 'ffw_in':
        return P(None, model_axis) if leaf == 'kernel' else P(model_axis)
    if parent == 'ffw_out':
        return P(model_axis, None) if leaf == 'kernel' else P(None)
    return P()                                 # norms & everything else: replicated


def megatron_spec_fn(model_axis='model'):
    """Public path→PartitionSpec callable with the Megatron TP rules — the
    ``base_spec_fn`` hook for :func:`petastorm_tpu.parallel.fsdp_shardings`
    (FSDP × TP composition)."""
    return functools.partial(_spec_for, model_axis=model_axis)


def param_shardings(params, mesh, model_axis='model'):
    """NamedSharding pytree for ``TransformerLM`` params over ``mesh``.

    Tensor parallelism the XLA way: annotate the parameters, let GSPMD
    propagate through the matmuls and insert the block all-reduces —
    never hand-written collectives (scaling-book recipe).
    """
    if model_axis not in mesh.axis_names:
        return jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), params)
    axis_size = mesh.shape[model_axis]

    def leaf_sharding(path, leaf):
        spec = _spec_for(path, model_axis)
        # A dim the rule would shard must be divisible by the axis size;
        # otherwise fall back to replication for this leaf (e.g. MQA
        # kv_heads=1 under 2-way TP, or an odd vocab).
        for dim, axis in zip(leaf.shape, spec):
            if axis == model_axis and dim % axis_size:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def make_attn_fn(mesh=None, strategy='flash', seq_axis='seq',
                 batch_axis='data', head_axis='model', block_k=None,
                 segment_ids=None, causal=True):
    """Attention implementation for a (mesh, strategy) pair.

    'flash'   — Pallas kernel, no sequence sharding (or inside Ulysses).
    'ring'    — K/V rotate the ICI ring over ``seq_axis`` (longest contexts);
                ``block_k`` additionally chunks each hop's score tile (set
                it when seq_local² would not fit — see
                ``parallel.ring_attention``).
    'ulysses' — all-to-all seq<->head reshard, flash locally.
    'dense'   — O(seq²) oracle (tests only).

    ``segment_ids`` ([batch, seq], 0 = padding — see
    ``petastorm_tpu.jax.packing``) restricts attention to packed-row
    segments under every strategy; for 'ring'/'ulysses' place them with
    the sequence sharding (``P(batch_axis, seq_axis)``).
    """
    from petastorm_tpu.parallel import (full_attention, make_ring_attention,
                                        make_ulysses_attention)
    packed = segment_ids is not None
    if strategy == 'flash':
        return (functools.partial(flash_attention, segment_ids=segment_ids)
                if packed else flash_attention)
    if strategy == 'dense':
        return (functools.partial(full_attention, segment_ids=segment_ids)
                if packed else full_attention)
    if mesh is None:
        raise ValueError('strategy %r needs a mesh' % (strategy,))
    if strategy == 'ring':
        fn, _ = make_ring_attention(mesh, seq_axis=seq_axis, batch_axis=batch_axis,
                                    head_axis=head_axis, causal=causal,
                                    block_k=block_k, packed=packed)
    elif strategy == 'ulysses':
        fn, _ = make_ulysses_attention(
            mesh, seq_axis=seq_axis, batch_axis=batch_axis, head_axis=head_axis,
            causal=causal, attn_fn=flash_attention, packed=packed)
    else:
        raise ValueError('unknown attention strategy %r' % (strategy,))
    return functools.partial(_check_curried_causal, fn, segment_ids, causal)


def _check_curried_causal(fn, segment_ids, curried_causal, q, k, v,
                          causal=True):
    # shard_map-wrapped fns curried causal at construction time; a caller
    # asking for different masking (e.g. an encoder calling a causal-curried
    # wrapper) must hear about it, not silently get the curried behavior.
    if causal != curried_causal:
        raise ValueError(
            'attn_fn was built with causal=%s but called with causal=%s — '
            'pass causal=%s to make_attn_fn' % (curried_causal, causal, causal))
    if segment_ids is not None:
        return fn(q, k, v, segment_ids)
    return fn(q, k, v)
