"""DLRM (Deep Learning Recommendation Model) in flax — Criteo config (#4).

TPU notes: the dense MLPs run in bfloat16 on the MXU; embedding lookups are
gathers (bandwidth-bound, kept fp32); the pairwise-dot feature interaction
is expressed as one batched matmul so XLA tiles it onto the MXU instead of
emitting O(F^2) small ops.  For multi-chip runs the natural sharding is
model-parallel embedding tables (shard the vocab axis) + data-parallel MLPs;
see ``examples/criteo/jax_example.py``.
"""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    layer_sizes: Sequence[int]
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for i, size in enumerate(self.layer_sizes):
            x = nn.Dense(size, dtype=self.dtype)(x)
            if i < len(self.layer_sizes) - 1:
                x = nn.relu(x)
        return x


class DLRM(nn.Module):
    """num_dense continuous features + one categorical id per embedding table."""

    vocab_sizes: Sequence[int]
    embedding_dim: int = 16
    bottom_mlp: Sequence[int] = (64, 32, 16)
    top_mlp: Sequence[int] = (64, 32, 1)
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, dense_features, categorical_ids):
        """dense: (B, num_dense) float; categorical: (B, num_tables) int."""
        if self.bottom_mlp[-1] != self.embedding_dim:
            raise ValueError('bottom MLP must end at embedding_dim')
        dense_emb = MLP(self.bottom_mlp, dtype=self.dtype)(dense_features)

        tables = [
            nn.Embed(vocab, self.embedding_dim, name='table_%d' % i,
                     embedding_init=nn.initializers.normal(0.01))
            for i, vocab in enumerate(self.vocab_sizes)
        ]
        cat_embs = [table(categorical_ids[:, i]) for i, table in enumerate(tables)]

        # (B, F, D): all features, dense projection first.
        feats = jnp.stack([dense_emb.astype(jnp.float32)] +
                          [e.astype(jnp.float32) for e in cat_embs], axis=1)
        feats = feats.astype(self.dtype)
        # Pairwise dot interactions as one batched matmul (MXU-friendly).
        interactions = jnp.einsum('bfd,bgd->bfg', feats, feats)
        num_feats = len(self.vocab_sizes) + 1
        iu, ju = jnp.triu_indices(num_feats, k=1)
        pairwise = interactions[:, iu, ju]

        top_in = jnp.concatenate([dense_emb, pairwise.astype(self.dtype)], axis=1)
        logits = MLP(self.top_mlp, dtype=self.dtype)(top_in)
        return logits[:, 0].astype(jnp.float32)
