"""Small flax MLP used by examples/mnist."""

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden_sizes: tuple = (128, 64)
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32) / 255.0
        for size in self.hidden_sizes:
            x = nn.relu(nn.Dense(size)(x))
        return nn.Dense(self.num_classes)(x)
