"""ResNet-50 in flax — the flagship model for the ImageNet-Parquet config.

TPU notes: compute runs in bfloat16 (MXU native) with float32 parameters and
batch statistics; convolutions are NHWC (XLA's preferred TPU layout).
"""

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    projection: bool = False
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                       epsilon=1e-5, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    num_classes: int = 1000
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), use_bias=False, dtype=self.dtype,
                    padding=[(3, 3), (3, 3)])(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 epsilon=1e-5, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, (filters, blocks) in enumerate([(64, 3), (128, 4), (256, 6), (512, 3)]):
            for j in range(blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(filters, strides=strides, projection=(j == 0),
                                    dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        # Final classifier in float32 for numerically stable logits/softmax.
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
