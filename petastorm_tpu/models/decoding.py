"""Autoregressive generation for ``TransformerLM`` — compiled, static-shape.

The XLA way to decode (no reference analog; the reference ships no
models): the per-layer KV cache is a fixed ``[b, max_seq_len, h, hd]``
buffer (``Attention._decode_step``), prefill and generation are both
``lax.scan`` loops over it, and every step runs the same executable —
no data-dependent Python control flow, one compile for any prompt.

    tokens = decoding.generate(model, params, prompt, max_new_tokens=64)

Greedy by default; pass ``temperature > 0`` with ``rng`` to sample.
"""

import jax
import jax.numpy as jnp

__all__ = ['generate', 'beam_search', 'speculative_generate']


def _decode_variant(model):
    """The same architecture flipped into KV-cache mode."""
    return model.clone(decode=True)


def _prefill(dec, params, prompt):
    """Fresh zero cache + ONE batched causal forward over the prompt.

    Returns ``(cache, last_logits)``.  The single place that encodes the
    fresh-cache contract with ``Attention._decode_step`` (zeros + index 0,
    broadcast positions) — greedy and beam decoding share it so they can
    never drift apart.
    """
    b, prompt_len = prompt.shape
    cache_shapes = jax.eval_shape(
        lambda: dec.init(jax.random.PRNGKey(0), prompt[:, :1],
                         positions=jnp.zeros((b, 1), jnp.int32)))['cache']
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)
    logits, mutated = dec.apply(
        {'params': params, 'cache': cache}, prompt,
        positions=jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32),
                                   (b, prompt_len)),
        mutable=['cache'])
    return mutated['cache'], logits[:, -1]


def _truncate_logits(logits, top_k, top_p):
    """Mask ``[b, vocab]`` logits to the top-k set and/or top-p nucleus.

    Index-based (selection by SORT POSITION, scattered back), so tied
    logits at the threshold are resolved by sort order instead of all
    being kept — ``top_k=1`` stays one token even on a flat distribution.
    Cost is one ``lax.top_k`` of size k (k = vocab only when nucleus-only),
    not a full-vocab sort per knob.
    """
    b, vocab = logits.shape
    if ((top_k is None or top_k >= vocab)
            and (top_p is None or top_p >= 1.0)):
        return logits   # no-op knobs: skip the sort+scatter entirely
    neg_inf = jnp.finfo(logits.dtype).min
    k = top_k if (top_k is not None and top_k < vocab) else vocab
    vals, idx = jax.lax.top_k(logits, k)        # descending, [b, k]
    keep = jnp.ones(vals.shape, bool)
    if top_p is not None and top_p < 1.0:
        # After top-k masking, softmax over the kept slice equals softmax
        # of the masked full vector — the nucleus is computed on exactly
        # the distribution sampling would see.
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep sorted position j iff cumulative mass BEFORE j < top_p
        # (position 0 always kept).
        keep = (cum - probs) < top_p
    masked = jnp.full_like(logits, neg_inf)
    return masked.at[jnp.arange(b)[:, None], idx].set(
        jnp.where(keep, vals, neg_inf))


def generate(model, params, prompt, max_new_tokens, temperature=0.0,
             rng=None, top_k=None, top_p=None, eos_id=None, pad_id=0):
    """Generate ``max_new_tokens`` continuations of ``prompt`` ``[b, L]``.

    Returns ``[b, max_new_tokens]`` int32 tokens.  ``temperature=0`` is
    greedy argmax; ``temperature>0`` samples with ``rng`` (required),
    optionally truncated to the ``top_k`` highest logits and/or the
    ``top_p`` nucleus (smallest probability mass >= top_p).  With
    ``eos_id`` set, rows that emit it keep emitting ``pad_id`` for the
    remaining steps (shapes stay static — no early exit).
    ``L + max_new_tokens`` must fit ``model.max_seq_len`` (the static
    cache size).  Wrap in ``jax.jit`` with ``static_argnums`` for
    ``max_new_tokens`` — everything inside is scan-compiled already.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2:
        raise ValueError('prompt must be [batch, len], got %r'
                         % (prompt.shape,))
    b, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError('prompt+new = %d exceeds max_seq_len %d'
                         % (total, model.max_seq_len))
    if temperature > 0 and rng is None:
        raise ValueError('temperature > 0 needs an rng key')
    if (top_k is not None or top_p is not None) and temperature <= 0:
        raise ValueError('top_k/top_p only apply when temperature > 0')
    if top_k is not None and top_k < 1:
        raise ValueError('top_k must be >= 1')
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError('top_p must be in (0, 1]')

    dec = _decode_variant(model)
    cache, last_logits = _prefill(dec, params, prompt)

    def step(cache, token, position):
        logits, mutated = dec.apply(
            {'params': params, 'cache': cache}, token[:, None],
            positions=position[:, None], mutable=['cache'])
        return mutated['cache'], logits[:, 0]  # [b, vocab]

    def pick(logits, key):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1)
        logits = _truncate_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(key, logits, axis=-1)

    key0 = rng if rng is not None else jax.random.PRNGKey(0)
    done0 = jnp.zeros((b,), bool)

    def gen_body(carry, t):
        cache, logits, key, done = carry
        key, sub = jax.random.split(key)
        token = pick(logits, sub).astype(jnp.int32)
        if eos_id is not None:
            token = jnp.where(done, jnp.int32(pad_id), token)
            done = done | (token == eos_id)
        cache, next_logits = step(cache, token, jnp.full((b,), t, jnp.int32))
        return (cache, next_logits, key, done), token

    steps = prompt_len + jnp.arange(max_new_tokens, dtype=jnp.int32)
    _, tokens = jax.lax.scan(
        gen_body, (cache, last_logits, key0, done0), steps)
    return tokens.T  # [b, max_new_tokens]


def _set_cache_index(cache, value):
    """Roll every layer's cache write index to ``value`` (tree surgery).

    Entries beyond the index become stale; they are harmless because
    ``Attention._attend_cache`` masks ``l <= q_pos`` with absolute
    positions, and subsequent writes overwrite them in place — the
    rollback primitive speculative decoding relies on.
    """
    def set_leaf(path, leaf):
        last = path[-1] if path else None
        if getattr(last, 'key', None) == 'index':
            return jnp.full_like(leaf, value)
        return leaf
    return jax.tree_util.tree_map_with_path(set_leaf, cache)


def speculative_generate(model, params, draft_model, draft_params, prompt,
                         max_new_tokens, draft_len=4, temperature=0.0,
                         rng=None):
    """Speculative decoding: a cheap draft proposes ``draft_len`` tokens
    per round, the target model verifies them all in ONE batched forward,
    and the accepted prefix plus a correction token are emitted.

    * ``temperature=0`` (default): greedy.  Output is token-identical to
      greedy ``generate(model, params, prompt, max_new_tokens)`` up to
      floating-point argmax tie-breaks — the verify forward is a
      differently-ordered reduction than per-step decode, so logits agree
      only to numerical noise (~1e-5 fp32); a near-exact top-2 tie can
      resolve differently.  The tests assert identity on fp32 models;
      treat bf16 reproducibility against step-wise decode as approximate.
    * ``temperature>0`` (``rng`` required): standard speculative
      SAMPLING (Leviathan et al.) — drafts are sampled from the draft
      model, each is accepted with probability ``min(1, p_t/p_d)``, and
      the first rejection resamples from the normalized residual
      ``max(p_t - p_d, 0)``.  The output distribution is exactly the
      target model's temperature-``T`` sampling distribution, whatever
      the draft proposes (a bad draft costs speed, never correctness).

    Either way the target model runs ``~max_new/(accepted+1)`` forwards
    instead of ``max_new``.

    The verify step is ``Attention._decode_step``'s warm-cache multi-token
    path (chunked prefill): ``draft_len + 1`` tokens attend the cache
    prefix with absolute-position causal masking in one MXU-batched call.
    Rejection rolls the static cache's write index back (stale entries are
    masked and later overwritten), so shapes never depend on how many
    tokens were accepted — the whole loop is one compiled
    ``lax.while_loop``.

    Acceptance is the batch-min prefix: each round emits
    ``min_over_rows(accepted) + 1`` tokens.  Rows that accepted more emit
    their (already-accepted) draft at the cut position, so per-row
    outputs remain greedy-exact / distribution-exact; batch-min only
    costs speed on mixed batches.

    Requires ``prompt_len + max_new_tokens + draft_len <= max_seq_len``
    on both models (verify writes up to ``draft_len`` positions past the
    accepted point before rolling back).  ``eos_id`` early stopping is
    not supported — use :func:`generate` for that.
    Returns ``[b, max_new_tokens]`` int32 tokens.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2:
        raise ValueError('prompt must be [batch, len], got %r'
                         % (prompt.shape,))
    if draft_len < 1:
        raise ValueError('draft_len must be >= 1')
    if temperature > 0 and rng is None:
        raise ValueError('temperature > 0 needs an rng key')
    sampled = temperature > 0
    b, prompt_len = prompt.shape
    k = int(draft_len)
    for name, m in (('model', model), ('draft_model', draft_model)):
        if prompt_len + max_new_tokens + k > m.max_seq_len:
            raise ValueError(
                '%s: prompt+new+draft_len = %d exceeds max_seq_len %d'
                % (name, prompt_len + max_new_tokens + k, m.max_seq_len))

    dec = _decode_variant(model)
    dft = _decode_variant(draft_model)
    t_cache, t_logits = _prefill(dec, params, prompt)
    d_cache, _ = _prefill(dft, draft_params, prompt)
    key0 = rng if rng is not None else jax.random.PRNGKey(0)
    if sampled:
        key0, sub = jax.random.split(key0)
        c0 = jax.random.categorical(
            sub, t_logits / temperature, axis=-1).astype(jnp.int32)
    else:
        c0 = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # first token

    buf = jnp.zeros((b, max_new_tokens + k + 1), jnp.int32)
    buf = buf.at[:, 0].set(c0)

    def draft_step(cache, token, position, key):
        logits, mutated = dft.apply(
            {'params': draft_params, 'cache': cache}, token[:, None],
            positions=jnp.full((b, 1), position, jnp.int32),
            mutable=['cache'])
        logits = logits[:, 0]
        if sampled:
            nxt = jax.random.categorical(key, logits / temperature, axis=-1)
            probs = jax.nn.softmax(logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, -1)
            probs = jnp.zeros_like(logits)   # unused in the greedy path
        return mutated['cache'], nxt.astype(jnp.int32), probs

    def round_body(carry):
        buf, g, c, t_cache, d_cache, key = carry
        pos = prompt_len + g - 1          # absolute position c is consumed at
        key, k_draft, k_accept, k_resample = jax.random.split(key, 4)

        # 1. draft k+1 steps (the extra step fills the cache entry for the
        #    last proposal; its own output is discarded)
        def scan_body(state, xs):
            j, subkey = xs
            d_cache, token = state
            d_cache, nxt, probs = draft_step(d_cache, token, pos + j, subkey)
            return (d_cache, nxt), (nxt, probs)
        (d_cache, _), (proposals, q_probs) = jax.lax.scan(
            scan_body, (d_cache, c),
            (jnp.arange(k + 1, dtype=jnp.int32),
             jax.random.split(k_draft, k + 1)))
        drafts = proposals[:k].T                       # [b, k]

        # 2. verify [c, d1..dk] in one warm-cache multi-token forward
        chunk = jnp.concatenate([c[:, None], drafts], axis=1)   # [b, k+1]
        positions = pos + jnp.broadcast_to(
            jnp.arange(k + 1, dtype=jnp.int32), (b, k + 1))
        logits, mutated = dec.apply(
            {'params': params, 'cache': t_cache}, chunk,
            positions=positions, mutable=['cache'])
        t_cache = mutated['cache']

        # 3. accepted prefix (per row), batch-min cut, correction token
        j = jnp.arange(k + 1)
        padded = jnp.concatenate([drafts, jnp.zeros((b, 1), jnp.int32)], 1)
        if sampled:
            p_t = jax.nn.softmax(logits / temperature, axis=-1)  # [b,k+1,V]
            # q_probs[j] is the dist d_{j+1} was drawn from; p_t[:, j] is
            # the target dist for the same slot.
            q = jnp.moveaxis(q_probs[:k], 0, 1)                  # [b, k, V]
            p_at_d = jnp.take_along_axis(
                p_t[:, :k], drafts[:, :, None], axis=2)[:, :, 0]
            q_at_d = jnp.take_along_axis(
                q, drafts[:, :, None], axis=2)[:, :, 0]
            u = jax.random.uniform(k_accept, (b, k))
            accept = u * q_at_d < p_at_d                         # [b, k]
            a_r = jnp.argmin(jnp.concatenate(
                [accept.astype(jnp.int32),
                 jnp.zeros((b, 1), jnp.int32)], axis=1), axis=1)  # [b]
            a = jnp.min(a_r)
            # Residual at the cut: max(p_t - q, 0) normalized; with a == k
            # there is no draft there (q row is zero) and this reduces to
            # sampling p_t directly — the all-accepted bonus token.
            q_pad = jnp.concatenate(
                [q, jnp.zeros((b, 1, q.shape[-1]))], axis=1)      # [b,k+1,V]
            p_t_a = jnp.take_along_axis(
                p_t, jnp.full((b, 1, 1), a).astype(jnp.int32),
                axis=1)[:, 0]                                     # [b, V]
            q_a = jnp.take_along_axis(
                q_pad, jnp.full((b, 1, 1), a).astype(jnp.int32),
                axis=1)[:, 0]
            res = jnp.maximum(p_t_a - q_a, 0.0)
            res = jnp.where(res.sum(-1, keepdims=True) > 0, res, p_t_a)
            resampled = jax.random.categorical(
                k_resample, jnp.log(res + 1e-30), axis=-1).astype(jnp.int32)
            # Rows that accepted beyond the cut emit their accepted draft.
            correction = jnp.where(a_r > a, jnp.take(padded, a, axis=1),
                                   resampled)
        else:
            preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [b,k+1]
            match = jnp.all(preds[:, :k] == drafts, axis=0)        # [k]
            a = jnp.argmin(jnp.concatenate(
                [match.astype(jnp.int32), jnp.zeros((1,), jnp.int32)]))
            correction = jnp.take_along_axis(
                preds, jnp.full((b, 1), a), axis=1)[:, 0]          # [b]

        # 4. emit d1..d_a then the correction (garbage beyond is
        #    overwritten by later rounds and sliced off at the end)
        emit = jnp.where(j[None, :] < a, padded,
                         jnp.where(j[None, :] == a, correction[:, None], 0))
        buf = jax.lax.dynamic_update_slice(buf, emit, (0, g))

        # 5. roll both caches back to the accepted position
        new_index = pos + a + 1
        t_cache = _set_cache_index(t_cache, new_index)
        d_cache = _set_cache_index(d_cache, new_index)
        return buf, g + a + 1, correction, t_cache, d_cache, key

    def cond(carry):
        return carry[1] < max_new_tokens

    g0 = jnp.int32(1)
    buf, _, _, _, _, _ = jax.lax.while_loop(
        cond, round_body, (buf, g0, c0, t_cache, d_cache, key0))
    return buf[:, :max_new_tokens]


def beam_search(model, params, prompt, max_new_tokens, num_beams=4,
                eos_id=None, pad_id=0, length_penalty=1.0):
    """Beam-search decoding: the ``num_beams`` highest-likelihood
    continuations, returning the best.

    Returns ``(tokens [b, max_new_tokens], scores [b])`` where ``scores``
    is the best beam's sum of token log-probs divided by
    ``length**length_penalty`` (>1 favors longer sequences).  Static
    shapes throughout: beams fold into the batch axis (``b*num_beams``
    rows through the model), each scan step re-orders every layer's KV
    cache by the surviving beams' parents with one batched gather.
    ``eos_id`` freezes a finished beam: it keeps emitting ``pad_id`` at
    zero additional log-prob.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2:
        raise ValueError('prompt must be [batch, len], got %r'
                         % (prompt.shape,))
    if num_beams < 1:
        raise ValueError('num_beams must be >= 1')
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > model.max_seq_len:
        raise ValueError('prompt+new = %d exceeds max_seq_len %d'
                         % (prompt_len + max_new_tokens, model.max_seq_len))
    k = num_beams
    neg_inf = jnp.float32(jnp.finfo(jnp.float32).min)

    dec = _decode_variant(model)
    # Prefill ONCE at batch b (all beams share the prompt), then fold beams
    # into the batch axis by repeating the cache rows — 1/k the prompt
    # compute of prefilling the tiled batch.
    cache_b, last_logits_b = _prefill(dec, params, prompt)
    cache = jax.tree_util.tree_map(
        lambda v: (jnp.repeat(v, k, axis=0)
                   if v.ndim >= 1 and v.shape[0] == b else v), cache_b)
    log_probs = jnp.repeat(
        jax.nn.log_softmax(last_logits_b.astype(jnp.float32), axis=-1),
        k, axis=0)                                          # [b*k, V]
    vocab = log_probs.shape[-1]

    # Only beam 0 is live initially (all beams hold the same prompt —
    # without this the top-k would pick k copies of the same token).
    beam_mask = jnp.where(jnp.arange(k) == 0, 0.0, neg_inf)  # [k]
    scores0 = jnp.broadcast_to(beam_mask, (b, k))

    def step_fn(carry, t):
        cache, scores, done, lengths, last_lp = carry
        # candidate scores over [b, k, V]; finished beams may only emit pad
        # at zero cost.
        cand = last_lp.reshape(b, k, vocab) + scores[:, :, None]
        if eos_id is not None:
            pad_only = jnp.full((vocab,), neg_inf).at[pad_id].set(0.0)
            cand = jnp.where(done[:, :, None],
                             scores[:, :, None] + pad_only[None, None, :],
                             cand)
        flat = cand.reshape(b, k * vocab)
        top_scores, top_idx = jax.lax.top_k(flat, k)       # [b, k]
        parent = top_idx // vocab                          # [b, k]
        token = (top_idx % vocab).astype(jnp.int32)        # [b, k]
        if eos_id is not None:
            parent_done = jnp.take_along_axis(done, parent, axis=1)
            done = parent_done | (token == eos_id)
            token = jnp.where(parent_done, jnp.int32(pad_id), token)
            # a beam's length counts its real tokens (incl. its eos)
            lengths = (jnp.take_along_axis(lengths, parent, axis=1)
                       + (~parent_done).astype(jnp.int32))
        else:
            lengths = lengths + 1
        # Re-order every layer's cache rows to the surviving parents.
        flat_parent = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
        cache = jax.tree_util.tree_map(
            lambda v: (jnp.take(v, flat_parent, axis=0)
                       if v.ndim >= 1 and v.shape[0] == b * k else v),
            cache)
        next_logits, mutated = dec.apply(
            {'params': params, 'cache': cache}, token.reshape(b * k, 1),
            positions=jnp.full((b * k, 1), t, jnp.int32), mutable=['cache'])
        last_lp = jax.nn.log_softmax(
            next_logits[:, 0].astype(jnp.float32), axis=-1)
        return ((mutated['cache'], top_scores, done, lengths, last_lp),
                (token, parent))

    done0 = jnp.zeros((b, k), bool)
    lengths0 = jnp.zeros((b, k), jnp.int32)
    steps = prompt_len + jnp.arange(max_new_tokens, dtype=jnp.int32)
    (cache, scores, done, lengths, _), (tokens, parents) = jax.lax.scan(
        step_fn, (cache, scores0, done0, lengths0, log_probs), steps)
    # tokens/parents: [T, b, k].  Walk parents backwards to reconstruct
    # each beam's token path (the cache was re-ordered in place, the
    # recorded tokens were not).
    def backtrace(carry, xs):
        beam = carry                       # [b, k] current beam index
        token_t, parent_t = xs
        tok = jnp.take_along_axis(token_t, beam, axis=1)
        beam = jnp.take_along_axis(parent_t, beam, axis=1)
        return beam, tok

    init_beam = jnp.broadcast_to(jnp.arange(k), (b, k))
    _, path = jax.lax.scan(backtrace, init_beam, (tokens, parents),
                           reverse=True)
    path = jnp.moveaxis(path, 0, 2)        # [b, k, T]
    # Per-BEAM length normalization (early-finishing beams divide by their
    # real emitted length), so length_penalty genuinely trades short
    # high-density hypotheses against longer ones.
    norm = jnp.maximum(1, lengths).astype(jnp.float32) ** length_penalty
    final = scores / norm
    best = jnp.argmax(final, axis=1)       # [b]
    best_tokens = jnp.take_along_axis(
        path, best[:, None, None], axis=1)[:, 0]
    return best_tokens, jnp.take_along_axis(final, best[:, None], 1)[:, 0]
