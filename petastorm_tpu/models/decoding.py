"""Autoregressive generation for ``TransformerLM`` — compiled, static-shape.

The XLA way to decode (no reference analog; the reference ships no
models): the per-layer KV cache is a fixed ``[b, max_seq_len, h, hd]``
buffer (``Attention._decode_step``), prefill and generation are both
``lax.scan`` loops over it, and every step runs the same executable —
no data-dependent Python control flow, one compile for any prompt.

    tokens = decoding.generate(model, params, prompt, max_new_tokens=64)

Greedy by default; pass ``temperature > 0`` with ``rng`` to sample.
"""

import jax
import jax.numpy as jnp

__all__ = ['generate']


def _decode_variant(model):
    """The same architecture flipped into KV-cache mode."""
    return model.clone(decode=True)


def _truncate_logits(logits, top_k, top_p):
    """Mask ``[b, vocab]`` logits to the top-k set and/or top-p nucleus.

    Index-based (selection by SORT POSITION, scattered back), so tied
    logits at the threshold are resolved by sort order instead of all
    being kept — ``top_k=1`` stays one token even on a flat distribution.
    Cost is one ``lax.top_k`` of size k (k = vocab only when nucleus-only),
    not a full-vocab sort per knob.
    """
    b, vocab = logits.shape
    if ((top_k is None or top_k >= vocab)
            and (top_p is None or top_p >= 1.0)):
        return logits   # no-op knobs: skip the sort+scatter entirely
    neg_inf = jnp.finfo(logits.dtype).min
    k = top_k if (top_k is not None and top_k < vocab) else vocab
    vals, idx = jax.lax.top_k(logits, k)        # descending, [b, k]
    keep = jnp.ones(vals.shape, bool)
    if top_p is not None and top_p < 1.0:
        # After top-k masking, softmax over the kept slice equals softmax
        # of the masked full vector — the nucleus is computed on exactly
        # the distribution sampling would see.
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep sorted position j iff cumulative mass BEFORE j < top_p
        # (position 0 always kept).
        keep = (cum - probs) < top_p
    masked = jnp.full_like(logits, neg_inf)
    return masked.at[jnp.arange(b)[:, None], idx].set(
        jnp.where(keep, vals, neg_inf))


def generate(model, params, prompt, max_new_tokens, temperature=0.0,
             rng=None, top_k=None, top_p=None, eos_id=None, pad_id=0):
    """Generate ``max_new_tokens`` continuations of ``prompt`` ``[b, L]``.

    Returns ``[b, max_new_tokens]`` int32 tokens.  ``temperature=0`` is
    greedy argmax; ``temperature>0`` samples with ``rng`` (required),
    optionally truncated to the ``top_k`` highest logits and/or the
    ``top_p`` nucleus (smallest probability mass >= top_p).  With
    ``eos_id`` set, rows that emit it keep emitting ``pad_id`` for the
    remaining steps (shapes stay static — no early exit).
    ``L + max_new_tokens`` must fit ``model.max_seq_len`` (the static
    cache size).  Wrap in ``jax.jit`` with ``static_argnums`` for
    ``max_new_tokens`` — everything inside is scan-compiled already.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2:
        raise ValueError('prompt must be [batch, len], got %r'
                         % (prompt.shape,))
    b, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError('prompt+new = %d exceeds max_seq_len %d'
                         % (total, model.max_seq_len))
    if temperature > 0 and rng is None:
        raise ValueError('temperature > 0 needs an rng key')
    if (top_k is not None or top_p is not None) and temperature <= 0:
        raise ValueError('top_k/top_p only apply when temperature > 0')
    if top_k is not None and top_k < 1:
        raise ValueError('top_k must be >= 1')
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError('top_p must be in (0, 1]')

    dec = _decode_variant(model)
    # Cache SHAPES only — eval_shape runs no compute and no param init;
    # a fresh cache is zeros with index 0 (init never mutates it).
    cache_shapes = jax.eval_shape(
        lambda: dec.init(jax.random.PRNGKey(0), prompt[:, :1],
                         positions=jnp.zeros((b, 1), jnp.int32)))['cache']
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    def step(cache, token, position):
        logits, mutated = dec.apply(
            {'params': params, 'cache': cache}, token[:, None],
            positions=position[:, None], mutable=['cache'])
        return mutated['cache'], logits[:, 0]  # [b, vocab]

    # Prefill: ONE batched causal forward over the whole prompt fills every
    # layer's cache (seq>1 path of Attention._decode_step) — MXU-efficient,
    # not L sequential steps.  Its last logits predict the first new token.
    prefill_logits, mutated = dec.apply(
        {'params': params, 'cache': cache}, prompt,
        positions=jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32),
                                   (b, prompt_len)),
        mutable=['cache'])
    cache = mutated['cache']
    last_logits = prefill_logits[:, -1]

    def pick(logits, key):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1)
        logits = _truncate_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(key, logits, axis=-1)

    key0 = rng if rng is not None else jax.random.PRNGKey(0)
    done0 = jnp.zeros((b,), bool)

    def gen_body(carry, t):
        cache, logits, key, done = carry
        key, sub = jax.random.split(key)
        token = pick(logits, sub).astype(jnp.int32)
        if eos_id is not None:
            token = jnp.where(done, jnp.int32(pad_id), token)
            done = done | (token == eos_id)
        cache, next_logits = step(cache, token, jnp.full((b,), t, jnp.int32))
        return (cache, next_logits, key, done), token

    steps = prompt_len + jnp.arange(max_new_tokens, dtype=jnp.int32)
    _, tokens = jax.lax.scan(
        gen_body, (cache, last_logits, key0, done0), steps)
    return tokens.T  # [b, max_new_tokens]
