"""Mixture-of-Experts FFN with all-to-all expert parallelism.

No reference equivalent (the reference is a data library — SURVEY.md §2.6);
this is the transformer-side EP obligation, the GShard/Switch pattern done
the XLA way:

* **Routing** — Switch top-1: a replicated router picks one expert per
  token; the gate probability scales the expert output (so router gradients
  flow through the gate).
* **Capacity** — each expert accepts ``capacity`` token slots per device
  per step (``capacity_factor`` × fair share); overflow tokens are dropped
  (contribute zero), the standard fixed-shape trick that keeps everything
  static for XLA.
* **Dispatch** — one-hot dispatch/combine tensors turn routing into
  einsums (MXU work, no gathers), and two ``lax.all_to_all``s move token
  slots to the devices that own the experts and back — ICI traffic only,
  inside ``jax.shard_map``.

``moe_apply`` is the single-device oracle (all experts everywhere);
``make_expert_parallel_moe`` returns the sharded twin + param shardings.
Tested equal to the oracle (forward and gradients) on the CPU mesh.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _a2a(x, axis_name, split_axis, concat_axis):
    """``lax.all_to_all`` with a hand-written transpose.

    The stock transpose rule in this jax version returns the cotangent with
    the split/concat dims swapped (verified: a [El, ep, ...] cotangent comes
    back [ep, El, ...] and lowering fails); an all_to_all's transpose is
    simply the reverse all_to_all, written out here.
    """
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis)


def _a2a_fwd(x, axis_name, split_axis, concat_axis):
    return _a2a(x, axis_name, split_axis, concat_axis), None


def _a2a_bwd(axis_name, split_axis, concat_axis, _, g):
    return (_a2a(g, axis_name, concat_axis, split_axis),)


_a2a.defvjp(_a2a_fwd, _a2a_bwd)


def moe_init(rng, d_model, d_ff, num_experts, dtype=jnp.float32):
    """{'router': [d, E], 'w1': [E, d, f], 'w2': [E, f, d]}."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale1 = 1.0 / np.sqrt(d_model)
    scale2 = 1.0 / np.sqrt(d_ff)
    return {
        'router': (jax.random.normal(k1, (d_model, num_experts)) * scale1).astype(dtype),
        'w1': (jax.random.normal(k2, (num_experts, d_model, d_ff)) * scale1).astype(dtype),
        'w2': (jax.random.normal(k3, (num_experts, d_ff, d_model)) * scale2).astype(dtype),
    }


def _route(params, x, capacity):
    """Switch top-1 dispatch/combine tensors for local tokens ``x [T, d]``.

    Returns (dispatch [T, E, C] one-hot slots, combine = dispatch * gate).
    Tokens beyond an expert's capacity get all-zero rows (dropped).
    """
    logits = x @ params['router']                     # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]  # [T]
    onehot = jax.nn.one_hot(expert, params['router'].shape[1],
                            dtype=jnp.float32)        # [T, E]
    # Slot index of each token within its expert (arrival order).
    slot = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # [T, E]
    kept = onehot * (slot < capacity)
    dispatch = kept[:, :, None] * jax.nn.one_hot(
        slot.astype(jnp.int32), capacity, dtype=jnp.float32)  # [T, E, C]
    combine = dispatch * gate[:, None, None].astype(jnp.float32)
    return dispatch, combine


def _expert_ffn(w1, w2, xs):
    """Per-expert FFN over slot buffers ``xs [E?, C?, d]`` (vmapped over E)."""
    return jax.vmap(lambda a, b, x: jax.nn.relu(x @ a) @ b)(w1, w2, xs)


def moe_apply(params, x, capacity_factor=2.0):
    """Single-device oracle: dense dispatch to every expert, no collectives.

    ``x``: [T, d] tokens; returns [T, d].
    """
    num_experts = params['router'].shape[1]
    capacity = _capacity(x.shape[0], num_experts, capacity_factor)
    dispatch, combine = _route(params, x, capacity)
    xs = jnp.einsum('tec,td->ecd', dispatch, x.astype(jnp.float32))
    ys = _expert_ffn(params['w1'].astype(jnp.float32),
                     params['w2'].astype(jnp.float32), xs)
    return jnp.einsum('tec,ecd->td', combine, ys).astype(x.dtype)


def _capacity(tokens, num_experts, capacity_factor):
    return max(1, int(np.ceil(tokens * capacity_factor / num_experts)))


def make_expert_parallel_moe(mesh, num_experts, expert_axis='expert',
                             batch_axis='data', capacity_factor=2.0):
    """shard_map-wrapped MoE over ``mesh``: experts sharded over
    ``expert_axis`` (leading E axis of w1/w2), tokens over ``batch_axis``.

    Tokens shard over BOTH axes (the expert axis does double duty as extra
    data parallelism — the standard GShard layout, so no device routes a
    token twice); experts shard over ``expert_axis`` alone, the router is
    replicated.

    Returns ``(fn, param_shardings_fn, token_sharding)``: ``fn(params, x)``
    on global ``x [T, d]`` placed with ``token_sharding``;
    ``param_shardings_fn(params)`` places the params.  ``num_experts`` must
    be divisible by the expert-axis size.
    """
    ep = mesh.shape[expert_axis] if expert_axis in mesh.axis_names else 1
    if num_experts % max(ep, 1):
        raise ValueError('num_experts=%d not divisible by %r axis size %d'
                         % (num_experts, expert_axis, ep))
    experts_local = num_experts // ep

    def inner(params, x):
        # x: [T_local, d]; every device routes its own tokens.
        capacity = _capacity(x.shape[0], num_experts, capacity_factor)
        dispatch, combine = _route(params, x, capacity)
        xs = jnp.einsum('tec,td->ecd', dispatch,
                        x.astype(jnp.float32))        # [E, C, d]
        d = xs.shape[-1]
        if ep > 1:
            # Send each expert block to its owner; receive my experts' slot
            # buffers from every peer: [E, C, d] -> [El, ep, C, d] (dim 1
            # indexes the source peer) -> [El, ep*C, d].
            xs = _a2a(xs.reshape(ep, experts_local, capacity, d),
                      expert_axis, 0, 1)
            xs = xs.reshape(experts_local, ep * capacity, d)
        ys = _expert_ffn(params['w1'].astype(jnp.float32),
                         params['w2'].astype(jnp.float32), xs)
        if ep > 1:
            # Route results back to the tokens' home devices:
            # [El, ep*C, d] -> [ep, El, C, d] -> [E, C, d], the same
            # expert-major order the forward reshape used.
            ys = _a2a(ys.reshape(experts_local, ep, capacity, d),
                      expert_axis, 1, 0)
            ys = ys.reshape(num_experts, capacity, d)
        return jnp.einsum('tec,ecd->td', combine, ys).astype(x.dtype)

    expert_spec = expert_axis if expert_axis in mesh.axis_names else None
    token_axes = tuple(ax for ax in (batch_axis, expert_axis)
                       if ax in mesh.axis_names)
    token_spec = P(token_axes) if token_axes else P()
    fn = jax.shard_map(
        inner, mesh=mesh,
        in_specs=({'router': P(), 'w1': P(expert_spec), 'w2': P(expert_spec)},
                  token_spec),
        out_specs=token_spec)

    def param_shardings(params):
        return {
            'router': NamedSharding(mesh, P()),
            'w1': NamedSharding(mesh, P(expert_spec)),
            'w2': NamedSharding(mesh, P(expert_spec)),
        }

    return fn, param_shardings, NamedSharding(mesh, token_spec)
