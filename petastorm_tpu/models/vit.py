"""Vision Transformer — the second vision flagship next to ResNet-50.

No reference equivalent (the reference ships no models, SURVEY.md §2.6);
this exists to prove the image pipeline end to end on a transformer
backbone: uint8 batches from ``petastorm_tpu.jax.DataLoader``, on-device
``petastorm_tpu.jax.augment``, encoder blocks shared with
``models.transformer`` (same ``Block``/``Attention`` modules with
``causal=False``), so the Megatron TP rules and FSDP composition apply
unchanged.

TPU design notes:
* Patchify is a stride-``patch`` conv — one big MXU matmul per image, no
  gather/reshape shuffle on the VPU.
* Everything runs bf16 on the MXU (``dtype``); norms/softmax stats fp32.
* ``pool='mean'`` (default) global-average-pools patch tokens — no class
  token means the sequence length stays a multiple of the patch grid,
  which keeps flash-attention block tiling clean.
"""

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from petastorm_tpu.models.transformer import (Block, RMSNorm,
                                              megatron_spec_fn,
                                              param_shardings)
from petastorm_tpu.ops import flash_attention

__all__ = ['ViT', 'param_shardings', 'megatron_spec_fn']


class ViT(nn.Module):
    """images [batch, H, W, C] float/bf16 -> logits [batch, num_classes]."""

    num_classes: int
    patch_size: int = 16
    d_model: int = 384
    num_heads: int = 6
    num_layers: int = 12
    d_ff: int = 1536
    dtype: Any = jnp.bfloat16
    attn_fn: Callable = flash_attention
    pool: str = 'mean'            # 'mean' | 'cls'
    remat: bool = False

    @nn.compact
    def __call__(self, images):
        if images.ndim != 4:
            raise ValueError('expected [batch, H, W, C], got %r'
                             % (images.shape,))
        h, w = images.shape[1], images.shape[2]
        if h % self.patch_size or w % self.patch_size:
            raise ValueError('image %dx%d not divisible by patch_size %d'
                             % (h, w, self.patch_size))
        if self.pool not in ('mean', 'cls'):
            raise ValueError("pool must be 'mean' or 'cls', got %r"
                             % (self.pool,))

        x = nn.Conv(self.d_model, (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    dtype=self.dtype, name='patch_embed')(
                        images.astype(self.dtype))
        b = x.shape[0]
        x = x.reshape(b, -1, self.d_model)      # [b, n_patches, d]
        n = x.shape[1]

        if self.pool == 'cls':
            cls = self.param('cls_token', nn.initializers.zeros,
                             (1, 1, self.d_model))
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.d_model)).astype(x.dtype),
                 x], axis=1)
            n += 1
        pos = self.param('pos_embed',
                         nn.initializers.normal(stddev=0.02),
                         (1, n, self.d_model))
        x = x + pos.astype(x.dtype)

        block = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            x = block(self.num_heads, self.d_ff, self.dtype, self.attn_fn,
                      causal=False, name='block_%d' % i)(x)
        x = RMSNorm(name='ln_f')(x)
        x = x[:, 0] if self.pool == 'cls' else x.mean(axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name='head')(x.astype(jnp.float32))
