"""Materialization job controller (ISSUE 18a): warm datasets ahead of demand.

The controller owns one dataset's warming job end to end:

* **decode identity** — :class:`service.cluster.ClusterCacheIdentity`
  resolves the job's pieces, plane context, and per-piece digests
  WITHOUT constructing a reader; the warmer then instantiates the exact
  reader-worker class consumers run (``PyDictReaderWorker`` /
  ``ArrowReaderWorker``) standalone, with a capturing result cache, so
  a warmed entry is byte-identical to what a consumer's miss would have
  published — the same single-source-of-truth key formats, the same
  post-transform values, the same ``encode_entry`` bytes.
* **lease protocol** — the dispatcher's split-lease semantics over
  piece-granular work: ``lease`` grants with a TTL and burns an attempt,
  expiry requeues, ``max_piece_attempts`` poisons a piece to ``failed``.
  The protocol is what lets autoscaler scale-in victims
  (:meth:`offer_drain_candidate`) and the controller's own run loop
  share one work queue without double-warming a piece.
* **durable progress** — the PR 15 snapshot+journal ledger under
  ``kind='materialize_ledger'``: ``complete`` appends one O(1)
  write-ahead line BEFORE the in-memory transition, so a SIGKILLed
  controller restarts attempt-intact with every finished piece still
  finished (the chaos scenario asserts exactly this).  Restores are
  gated on the plane-context fingerprint — a ledger written under a
  different dataset/spec identity cold-starts instead of lying.
* **eviction-aware admission** — every publish asks
  ``CachePlane.admit_publish`` first: a publish whose LRU victims
  include any entry accessed within ``hot_window_s`` is refused
  (counted, piece left pending attempt-intact for a later, cooler run).

Candidates come from the provenance journal (:func:`derive_candidates`):
sealed records that paid a cold decode name the dataset roots worth
warming, with per-tenant attribution riding along.
"""

import hashlib
import logging
import os
import threading
import time

from petastorm_tpu.telemetry import decisions as _decisions
from petastorm_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)

__all__ = ['MaterializeController', 'MATERIALIZE_LEDGER_KIND',
           'derive_candidates', 'wire_digests']

MATERIALIZE_LEDGER_KIND = 'materialize_ledger'

#: Ledger snapshot cadence: one full snapshot per this many completes
#: (the write-ahead journal covers the gap — same cost model as the
#: dispatcher's ledger).
_SAVE_EVERY = 16

_PENDING, _LEASED, _DONE, _FAILED = 'p', 'l', 'd', 'f'


class _CaptureCache(object):
    """Result-cache stand-in for the standalone warmer workers: always
    fills, and records key -> post-transform value — exactly what the
    consumer path would have handed ``encode_entry``."""

    def __init__(self):
        self.values = {}

    def get(self, key, fill_func):
        value = fill_func()
        self.values[key] = value
        return value

    def cleanup(self):
        self.values.clear()


class MaterializeController(object):  # ptlint: disable=pickle-unsafe-attrs — owns a lock, threads and an flock'd ledger; runs in one process, never pickled
    """One dataset's pre-publish warming job.

    Args mirror the service job dict (``ClusterCacheIdentity.build``
    consumes them verbatim): ``dataset_url`` + ``reader_kwargs`` pin the
    decode identity, ``cache_plane_dir`` is the shared plane the fleet
    reads.  ``ledger_path=None`` runs without durability (tests, one-shot
    tools); ``throttle_s`` stretches the decode->publish window (the
    chaos harness's kill target).  Construction never raises on an
    unsupported job — ``identity`` stays None and :meth:`run` reports
    why.
    """

    def __init__(self, dataset_url, cache_plane_dir, reader_kwargs=None,
                 ledger_path=None, cache_plane_disk_bytes=None,
                 cache_plane_ram_bytes=None, reader_factory='auto',
                 wire_policy='auto', hot_window_s=300.0, lease_ttl_s=30.0,
                 max_piece_attempts=3, throttle_s=0.0):
        from petastorm_tpu.service.cluster import ClusterCacheIdentity
        self.dataset_url = dataset_url
        self._job = {'dataset_url': dataset_url,
                     'reader_kwargs': dict(reader_kwargs or {}),
                     'reader_factory': reader_factory,
                     'cache_plane_dir': cache_plane_dir,
                     'cache_plane_disk_bytes': cache_plane_disk_bytes,
                     'cache_plane_ram_bytes': cache_plane_ram_bytes}
        self._wire_policy = wire_policy
        self._hot_window_s = float(hot_window_s)
        self._lease_ttl_s = float(lease_ttl_s)
        self._max_piece_attempts = int(max_piece_attempts)
        self.throttle_s = float(throttle_s)
        self._lock = make_lock(
            'materialize.controller.MaterializeController._lock')
        self._init_metrics()
        self.identity = ClusterCacheIdentity.build(self._job)
        self._piece_state = []       # piece index -> [state_code, attempt]
        self._leases = {}            # piece index -> (worker_id, expires)
        self._drain_passes = {}      # worker id -> warming-pass thread
        self.resumed_pieces = 0
        self._completes_since_save = 0
        self._ledger = None
        if self.identity is not None:
            self._piece_state = [[_PENDING, 0]
                                 for _ in range(self.identity.num_pieces)]
            self._context_token = hashlib.blake2b(
                self.identity.plane.context.encode('utf-8', 'replace'),
                digest_size=8).hexdigest()
            if ledger_path:
                self._attach_ledger(ledger_path)

    # -- telemetry -----------------------------------------------------------

    def _init_metrics(self):
        from petastorm_tpu.telemetry import MetricsRegistry
        self.metrics = MetricsRegistry('materialize')
        self._m_runs = self.metrics.counter('materialize_runs')
        self._m_warmed = self.metrics.counter('materialize_pieces_warmed')
        self._m_resumed = self.metrics.counter('materialize_pieces_resumed')
        self._m_failed = self.metrics.counter('materialize_pieces_failed')
        self._m_refused = self.metrics.counter(
            'materialize_admission_refused')
        self._m_bytes = self.metrics.counter('materialize_published_bytes')
        self._m_wire = self.metrics.counter('materialize_wire_published')
        self._m_wire_skipped = self.metrics.counter(
            'materialize_wire_skipped')
        self._m_drain_passes = self.metrics.counter(
            'materialize_drain_passes')

    # -- durable ledger ------------------------------------------------------

    def _attach_ledger(self, path):
        """Acquire + restore-or-cold-start.  A held ledger (another live
        controller on the same path) disables durability for THIS
        instance rather than raising — warming is an optimization."""
        from petastorm_tpu.service.ledger import (DispatcherLedger,
                                                  LedgerHeldError)
        ledger = DispatcherLedger(path, kind=MATERIALIZE_LEDGER_KIND)
        try:
            ledger.acquire()
        except LedgerHeldError:
            logger.warning('materialize: ledger %s held by a live '
                           'controller; running without durability', path)
            return
        self._ledger = ledger
        state = ledger.load()
        if not state:
            self._save_ledger()
            return
        if state.get('context') != self._context_token \
                or not isinstance(state.get('splits'), list) \
                or len(state['splits']) != len(self._piece_state):
            logger.warning('materialize: ledger %s was written under a '
                           'different decode identity/geometry; cold start',
                           path)
            self._save_ledger()
            return
        try:
            from petastorm_tpu.service.ledger import decode_splits
            decoded = decode_splits(state['splits'])
        except (ValueError, KeyError, TypeError):
            logger.warning('materialize: ledger %s splits undecodable; '
                           'cold start', path)
            self._save_ledger()
            return
        for i, (restored_state, attempt) in enumerate(decoded):
            if restored_state == 'done':
                self._piece_state[i] = [_DONE, attempt]
                self.resumed_pieces += 1
                self._m_resumed.inc()
            elif restored_state == 'failed':
                self._piece_state[i] = [_FAILED, attempt]
            else:
                # pending AND leased both requeue attempt-intact: the
                # controller's death was not the piece's failure.
                self._piece_state[i] = [_PENDING, attempt]
        logger.info('materialize: ledger %s restored %d/%d pieces done',
                    path, self.resumed_pieces, len(self._piece_state))

    def _save_ledger(self):
        if self._ledger is None:
            return
        with self._lock:
            splits = [list(rec) for rec in self._piece_state]
        self._ledger.save({'context': self._context_token,
                           'dataset_url': self.dataset_url,
                           'splits': splits})
        self._completes_since_save = 0

    # -- lease protocol ------------------------------------------------------

    def _expire_leases_locked(self, now):
        for index, (_, expires) in list(self._leases.items()):
            if expires < now:
                del self._leases[index]
                # Attempt stays burned (the grant consumed it): the
                # poison ceiling below is what bounds a crashing piece.
                self._piece_state[index][0] = _PENDING

    def lease(self, worker_id, n=1, skip=()):
        """Grant up to ``n`` pending piece indices to ``worker_id`` with
        a TTL; burns one attempt per grant.  Pieces at the attempt
        ceiling poison to ``failed`` instead of granting."""
        from petastorm_tpu import materialize
        if materialize.killed():
            return []
        now = time.monotonic()
        granted = []
        with self._lock:
            self._expire_leases_locked(now)
            for index, rec in enumerate(self._piece_state):
                if len(granted) >= n:
                    break
                if rec[0] != _PENDING or index in skip:
                    continue
                if rec[1] >= self._max_piece_attempts:
                    rec[0] = _FAILED
                    self._m_failed.inc()
                    _decisions.record_decision(
                        'materialize', 'poison_piece',
                        'max_piece_attempts',
                        {'attempts': rec[1],
                         'max_attempts': self._max_piece_attempts},
                        piece=index)
                    continue
                rec[0] = _LEASED
                rec[1] += 1
                self._leases[index] = (worker_id, now + self._lease_ttl_s)
                granted.append(index)
        return granted

    def complete(self, worker_id, index):
        """Retire one warmed piece — write-ahead journal line FIRST
        (the durable record exists before the in-memory transition), so
        a kill between the two re-runs nothing."""
        if self._ledger is not None:
            self._ledger.append({'op': 'done', 'split': int(index)})
        with self._lock:
            self._piece_state[index][0] = _DONE
            self._leases.pop(index, None)
        self._m_warmed.inc()
        self._completes_since_save += 1
        if self._completes_since_save >= _SAVE_EVERY:
            self._save_ledger()

    def release(self, worker_id, index, burn_attempt=True):
        """Return a lease unfinished.  ``burn_attempt=False`` refunds the
        grant's attempt — used when the piece itself was fine but the
        environment refused it (admission), so a later run retries from
        a clean count."""
        with self._lock:
            rec = self._piece_state[index]
            if rec[0] == _LEASED:
                rec[0] = _PENDING
                if not burn_attempt:
                    rec[1] = max(0, rec[1] - 1)
            self._leases.pop(index, None)

    def fail(self, worker_id, index):
        """Decode failure: requeue for retry (the attempt ceiling in
        ``lease`` poisons a piece that keeps failing)."""
        self.release(worker_id, index, burn_attempt=True)
        self._m_failed.inc()

    def pending_count(self):
        with self._lock:
            return sum(1 for rec in self._piece_state
                       if rec[0] == _PENDING
                       and rec[1] < self._max_piece_attempts)

    # -- warming -------------------------------------------------------------

    def _make_worker(self):
        """One standalone reader-worker (the EXACT consumer decode path)
        + its capturing cache.  Per-pass, not per-controller: passes run
        concurrently (run loop + drain passes) and the parquet handle
        cache inside the worker is single-threaded state."""
        identity = self.identity
        capture = _CaptureCache()
        if identity.kind == 'columns':
            from petastorm_tpu.py_dict_reader_worker import (
                PyDictReaderWorker, RowWorkerArgs)
            args = RowWorkerArgs(
                filesystem=identity.fs, pieces=identity.pieces,
                schema=identity.stored_schema,
                schema_view=identity.schema_view,
                transform_spec=identity.transform_spec,
                predicate=identity.predicate, cache=capture,
                shuffle_row_drop_partitions=identity.drop_partitions,
                columnar_output=True)
            worker = PyDictReaderWorker(0, lambda _result: None, args)
        else:
            from petastorm_tpu.arrow_reader_worker import (ArrowReaderWorker,
                                                           BatchWorkerArgs)
            args = BatchWorkerArgs(
                filesystem=identity.fs, pieces=identity.pieces,
                schema=identity.stored_schema,
                schema_view=identity.schema_view,
                transform_spec=identity.transform_spec,
                predicate=identity.predicate, cache=capture)
            worker = ArrowReaderWorker(0, lambda _result: None, args)
        return worker, capture

    def _decode_piece(self, index, worker, capture):
        """Decode one piece via the consumer code path; returns
        ``[(digest, cache_key, value), ...]`` (one per row-drop
        partition)."""
        identity = self.identity
        digests = identity.piece_digests(index)
        capture.values.clear()
        items = []
        if identity.kind == 'columns':
            from petastorm_tpu.py_dict_reader_worker import piece_cache_key
            for part in range(identity.drop_partitions):
                key = piece_cache_key(identity.pieces[index],
                                      identity.schema_view,
                                      identity.transform_spec, part) + ':c'
                worker.process(index, part)
                items.append((digests[part], key, capture.values[key]))
        else:
            from petastorm_tpu.arrow_reader_worker import piece_cache_key
            key = piece_cache_key(identity.pieces[index],
                                  identity.schema_view,
                                  identity.transform_spec)
            worker.process(index)
            items.append((digests[0], key, capture.values[key]))
        return items

    def _publish(self, digest, blob):
        """Admission-gated publish: 'published' | 'present' | 'refused'
        | 'degraded'."""
        plane = self.identity.plane
        if plane.has_digest(digest):
            return 'present'
        admitted, estimate = plane.admit_publish(len(blob),
                                                 self._hot_window_s)
        # Decision journal (ISSUE 20): the admission verdict with the
        # eviction-estimate inputs it read — "why was this publish
        # refused" resolves to hot victims, not a bare counter.
        inputs = {'nbytes': len(blob),
                  'hot_window_s': self._hot_window_s,
                  'admitted': admitted,
                  'fits': estimate.get('fits') if estimate else None,
                  'victim_newest_age_s':
                      estimate.get('victim_newest_age_s')
                      if estimate else None}
        if not admitted:
            self._m_refused.inc()
            _decisions.record_decision(
                'materialize', 'refuse_publish', 'hot_window_s', inputs,
                suppressed=True, digest=digest)
            return 'refused'
        if not plane.publish_blob(digest, blob):
            return 'degraded'
        self._m_bytes.inc(len(blob))
        _decisions.record_decision(
            'materialize', 'published', 'hot_window_s', inputs,
            digest=digest)
        return 'published'

    def _warm_piece(self, index, worker, capture):
        """Decode + publish one piece (raw entry per partition, then the
        wire-format sibling).  Returns 'done' | 'refused' | 'failed'."""
        from petastorm_tpu.cache_plane.plane import encode_entry
        from petastorm_tpu.materialize.transcode import (
            verify_wire_identity, wire_entry, wire_key)
        try:
            items = self._decode_piece(index, worker, capture)
        except Exception as e:  # noqa: BLE001 — a bad piece must not kill the job
            logger.warning('materialize: decode of piece %d failed (%s: %s)',
                           index, type(e).__name__, e)
            return 'failed'
        if self.throttle_s:
            time.sleep(self.throttle_s)  # chaos kill window: decoded, unpublished
        for digest, key, value in items:
            try:
                blob = encode_entry(value)
            except Exception as e:  # noqa: BLE001 — unencodable: skip the piece
                logger.warning('materialize: cannot encode piece %d (%s)',
                               index, e)
                return 'failed'
            outcome = self._publish(digest, blob)
            if outcome == 'refused':
                return 'refused'
            if outcome == 'degraded':
                return 'failed'
            # Wire-format sibling (ISSUE 18b): columnar pieces only;
            # skipped entries are covered by the raw entry (degrade).
            if self._wire_policy and self.identity.kind == 'columns' \
                    and isinstance(value, dict) and value:
                entry = wire_entry(value, self._wire_policy)
                if entry is None \
                        or not verify_wire_identity(value, entry,
                                                    self._wire_policy):
                    self._m_wire_skipped.inc()
                    continue
                wdigest = self.identity.plane.digest(
                    wire_key(key, self._wire_policy))
                try:
                    wblob = encode_entry(entry)
                except Exception:  # noqa: BLE001 — wire copy is optional
                    self._m_wire_skipped.inc()
                    continue
                if self._publish(wdigest, wblob) == 'published':
                    self._m_wire.inc()
                else:
                    self._m_wire_skipped.inc()
        return 'done'

    def _warm_loop(self, worker_id, deadline=None, max_pieces=None):
        """Lease/warm/complete until dry, deadline, or max_pieces; the
        shared engine under ``run`` and drain passes."""
        worker, capture = self._make_worker()
        warmed = failed = refused_count = 0
        refused = set()
        try:
            while max_pieces is None or warmed + failed < max_pieces:
                if deadline is not None and time.monotonic() >= deadline:
                    break
                granted = self.lease(worker_id, 1, skip=refused)
                if not granted:
                    break
                index = granted[0]
                outcome = self._warm_piece(index, worker, capture)
                if outcome == 'done':
                    self.complete(worker_id, index)
                    warmed += 1
                elif outcome == 'refused':
                    # Plane is hotter than this job: leave the piece
                    # pending attempt-intact for a cooler run, skip it
                    # for the rest of THIS pass.
                    self.release(worker_id, index, burn_attempt=False)
                    refused.add(index)
                    refused_count += 1
                else:
                    self.fail(worker_id, index)
                    failed += 1
        finally:
            try:
                worker.shutdown()
            except Exception:  # noqa: BLE001 — handle-cache teardown only
                pass
        return {'warmed': warmed, 'failed': failed,
                'refused': refused_count}

    def run(self, max_pieces=None, worker_id='controller'):
        """Warm the whole dataset (or up to ``max_pieces``) in the
        calling thread.  Returns the job summary; never raises for
        per-piece failures."""
        from petastorm_tpu import materialize
        if materialize.killed():
            return {'ok': False, 'reason': 'kill_switch'}
        if self.identity is None:
            return {'ok': False, 'reason': 'identity_unavailable'}
        self._m_runs.inc()
        t0 = time.monotonic()
        pass_stats = self._warm_loop(worker_id, max_pieces=max_pieces)
        self._save_ledger()
        summary = self.summary()
        summary.update(pass_stats)
        summary['elapsed_s'] = round(time.monotonic() - t0, 3)
        self.last_summary = summary
        return summary

    def summary(self):
        with self._lock:
            states = [rec[0] for rec in self._piece_state]
        return {'ok': True,
                'total_pieces': len(states),
                'done': states.count(_DONE),
                'pending': states.count(_PENDING),
                'failed_pieces': states.count(_FAILED),
                'resumed': self.resumed_pieces,
                'wire_published': self._m_wire.value,
                'admission_refused': self._m_refused.value,
                'published_bytes': self._m_bytes.value}

    # -- autoscaler hand-off (scale-in candidates warm before they drain) ----

    def offer_drain_candidate(self, worker_id, deadline_s=30.0):
        """A scale-in victim's capacity, offered for ONE bounded warming
        pass before its drain proceeds.  Returns True when a pass was
        started (or is already running) — the dispatcher then defers the
        drain until :meth:`drain_ready`; False (no pending work, kill
        switch, unsupported job) means drain immediately."""
        from petastorm_tpu import materialize
        if materialize.killed() or self.identity is None \
                or not self.pending_count():
            return False
        with self._lock:
            thread = self._drain_passes.get(worker_id)
            if thread is not None and thread.is_alive():
                return True
            deadline = time.monotonic() + float(deadline_s)
            thread = threading.Thread(
                target=self._drain_pass, args=(worker_id, deadline),
                daemon=True, name='materialize-drain-%s' % worker_id)
            self._drain_passes[worker_id] = thread
        thread.start()
        return True

    def drain_ready(self, worker_id):
        """True when the worker's warming pass (if any) has finished —
        the dispatcher's gate for proceeding with the deferred drain."""
        thread = self._drain_passes.get(worker_id)
        return thread is None or not thread.is_alive()

    def _drain_pass(self, worker_id, deadline):
        self._m_drain_passes.inc()
        try:
            self._warm_loop(worker_id, deadline=deadline)
            self._save_ledger()
        except Exception:  # noqa: BLE001 — a pass failure must still release the drain
            logger.warning('materialize: drain warming pass for %s died',
                           worker_id, exc_info=True)

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        for thread in list(self._drain_passes.values()):
            thread.join(timeout=5.0)
        self._save_ledger()
        if self._ledger is not None:
            self._ledger.release()
            self._ledger = None

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()


def wire_digests(identity, index, policy='auto'):
    """Full plane digests of one piece's wire-format entries (empty for
    batch-kind jobs — wire siblings are columnar-only).  Mirrors
    ``ClusterCacheIdentity.piece_digests`` for the ``:w{policy}``
    namespace; the doctor's skip-stage probe reads through this."""
    from petastorm_tpu.materialize.transcode import wire_key
    if identity is None or identity.kind != 'columns':
        return []
    from petastorm_tpu.py_dict_reader_worker import piece_cache_key
    return [identity.plane.digest(wire_key(
                piece_cache_key(identity.pieces[index],
                                identity.schema_view,
                                identity.transform_spec, part) + ':c',
                policy))
            for part in range(identity.drop_partitions)]


def derive_candidates(journals=None, top_k=4):
    """Warming candidates from observed access patterns: dataset roots
    named by sealed provenance records, ranked by how much cold decoding
    consumers paid there (``cache`` outcome ``decode``/``degraded``),
    with per-tenant attribution.  ``journals=None`` reads every live
    journal in this process.

    Returns ``[{'root', 'records', 'cold', 'pieces', 'tenants'}, ...]``
    hottest-coldest first — the controller's admission queue; roots with
    zero cold records are dropped (nothing to save there).
    """
    from petastorm_tpu.telemetry import provenance
    if journals is None:
        journals = provenance.journals()
    by_root = {}
    for journal in journals:
        try:
            records = journal.records()
        except Exception as exc:  # noqa: BLE001 — candidates are advisory
            logger.warning('materialize: skipping unreadable provenance '
                           'journal (%s: %s)', type(exc).__name__, exc)
            continue
        for record in records:
            if not isinstance(record, dict):
                continue
            roots = {os.path.dirname(str(piece.get('path')))
                     for piece in (record.get('pieces') or [])
                     if isinstance(piece, dict) and piece.get('path')}
            cold = record.get('cache') in ('decode', 'degraded')
            tenant = record.get('tenant')
            for root in roots:
                agg = by_root.setdefault(root, {
                    'root': root, 'records': 0, 'cold': 0,
                    'pieces': set(), 'tenants': {}})
                agg['records'] += 1
                agg['cold'] += int(cold)
                agg['pieces'].update(
                    (piece.get('path'), piece.get('row_group'))
                    for piece in (record.get('pieces') or [])
                    if isinstance(piece, dict)
                    and os.path.dirname(str(piece.get('path'))) == root)
                if tenant:
                    agg['tenants'][tenant] = agg['tenants'].get(tenant,
                                                                0) + 1
    out = []
    for agg in by_root.values():
        if not agg['cold']:
            continue
        agg['pieces'] = len(agg['pieces'])
        out.append(agg)
    out.sort(key=lambda a: (-a['cold'], -a['records'], a['root']))
    return out[:top_k]
