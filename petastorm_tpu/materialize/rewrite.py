"""Layout rewrite job (ISSUE 18c): re-shard hot datasets to zero-waste.

The ingest plane's coalesced range plans (PR 14) fetch ``waste_bytes``
when a dataset's layout interleaves unselected columns between selected
ones, or when row groups are sized against the split geometry — the
planner's gap/waste stats measure exactly this.  ``rewrite_layout``
streams a dataset through the reader/writer pair into a NEW dataset
whose row groups match the requested geometry and whose files carry
ONLY the selected columns (contiguous by construction — parquet lays a
row group's column chunks back to back, so dropping the unselected ones
removes the interleaving the merge-gap had to ride over).
``layout_stats`` is the before/after evidence and the trigger signal:
rewrite when waste_pct says the fleet is paying for bytes it never
decodes.

``write_rows`` is THE row sink — shared verbatim with
``tools/pack_dataset.py`` — so offline CLI packing and fleet rewrite
jobs produce byte-identical layouts (one code path, one test).
"""

import logging

logger = logging.getLogger(__name__)

__all__ = ['write_rows', 'layout_stats', 'rewrite_layout']


def write_rows(output_url, schema, rows, rows_per_rowgroup=None,
               rowgroup_size_mb=None, rows_per_file=None,
               storage_options=None, filesystem=None,
               compression='snappy'):
    """Stream ``rows`` (an iterable of row dicts) into a fresh dataset.

    The single writer path for every offline materialization in the
    repo (pack, rewrite, future pre-tokenize jobs): one
    ``DatasetWriter`` configuration surface, so two jobs given the same
    rows and geometry produce byte-identical layouts.  Returns the row
    count written.
    """
    from petastorm_tpu.etl.dataset_metadata import DatasetWriter
    count = 0

    def counted():
        nonlocal count
        for row in rows:
            count += 1
            yield row

    kwargs = {}
    if rows_per_rowgroup is not None:
        kwargs['rows_per_rowgroup'] = rows_per_rowgroup
    elif rowgroup_size_mb is not None:
        kwargs['rowgroup_size_mb'] = rowgroup_size_mb
    with DatasetWriter(output_url, schema, rows_per_file=rows_per_file,
                       compression=compression,
                       storage_options=storage_options,
                       filesystem=filesystem, **kwargs) as writer:
        writer.write_many(counted())
    return count


def layout_stats(dataset_url, columns=None, storage_options=None,
                 filesystem=None, merge_gap=None, max_range_bytes=None):
    """Gap/waste accounting of a dataset's CURRENT layout, as the ingest
    plane would plan it: per row group, the raw column-chunk ranges of
    the selected ``columns`` vs the coalesced GETs — summed dataset-wide
    through :func:`ingest.planner.plan_stats` (the same arithmetic the
    live plane's telemetry gauges run).

    Returns ``{'files', 'row_groups', 'rows', 'needed_bytes',
    'fetched_bytes', 'waste_bytes', 'waste_pct', 'requests',
    'rows_per_row_group'}`` — the rewrite trigger signal and the
    before/after evidence in one shape.
    """
    import pyarrow.parquet as pq

    from petastorm_tpu.etl.dataset_metadata import load_row_groups
    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
    from petastorm_tpu.ingest import planner

    merge_gap = planner.DEFAULT_MERGE_GAP if merge_gap is None \
        else int(merge_gap)
    max_range_bytes = planner.DEFAULT_MAX_RANGE_BYTES \
        if max_range_bytes is None else int(max_range_bytes)
    columns = set(columns) if columns is not None else None

    fs, path_or_paths = get_filesystem_and_path_or_paths(
        dataset_url, storage_options=storage_options, filesystem=filesystem)
    paths = (path_or_paths if isinstance(path_or_paths, list)
             else [path_or_paths])
    files = []
    for p in paths:
        files.extend(sorted({piece.path for piece in load_row_groups(fs, p)}))

    totals = {'files': 0, 'row_groups': 0, 'rows': 0, 'needed_bytes': 0,
              'fetched_bytes': 0, 'waste_bytes': 0, 'requests': 0}
    group_rows = []
    for path in files:
        handle = fs.open(path, 'rb')
        try:
            metadata = pq.ParquetFile(handle).metadata
        finally:
            try:
                handle.close()
            except OSError:  # best-effort teardown
                pass
        totals['files'] += 1
        for rg in range(metadata.num_row_groups):
            raw = planner.column_chunk_ranges(metadata, rg, columns)
            plan = planner.plan_stats(
                raw, planner.coalesce(raw, merge_gap, max_range_bytes))
            totals['row_groups'] += 1
            rows = metadata.row_group(rg).num_rows
            totals['rows'] += rows
            group_rows.append(rows)
            for key in ('needed_bytes', 'fetched_bytes', 'waste_bytes',
                        'requests'):
                totals[key] += plan[key]
    totals['waste_pct'] = (
        round(100.0 * totals['waste_bytes'] / totals['fetched_bytes'], 2)
        if totals['fetched_bytes'] else 0.0)
    totals['rows_per_row_group'] = {
        'min': min(group_rows) if group_rows else 0,
        'max': max(group_rows) if group_rows else 0,
        'mean': (round(float(sum(group_rows)) / len(group_rows), 1)
                 if group_rows else 0.0)}
    return totals


def rewrite_layout(source_url, output_url, rows_per_rowgroup,
                   columns=None, predicate=None, overwrite=False,
                   storage_options=None, reader_kwargs=None):
    """Re-shard ``source_url`` into ``output_url`` with row groups of
    ``rows_per_rowgroup`` rows, keeping only ``columns`` (None = all) —
    the materialize plane's layout job.

    Streams through the reader (decode identity preserved: codecs,
    nullability, schema all ride the stored Unischema) and writes
    through :func:`write_rows`, the sink ``tools/pack_dataset.py``
    shares.  Returns a summary with before/after :func:`layout_stats`
    over the SELECTED columns — ``after['waste_bytes']`` trending to
    zero is the job's whole point.
    """
    from petastorm_tpu.etl.dataset_metadata import get_schema_from_dataset_url
    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.unischema import Unischema

    fs, target_path = get_filesystem_and_path_or_paths(
        output_url, storage_options=storage_options)
    if fs.exists(target_path) and fs.ls(target_path):
        if not overwrite:
            raise ValueError('target %r exists; pass overwrite=True'
                             % (output_url,))
        fs.rm(target_path, recursive=True)

    stored_schema = get_schema_from_dataset_url(
        source_url, storage_options=storage_options)
    if columns is not None:
        schema = stored_schema.create_schema_view(list(columns))
    else:
        schema = stored_schema
    schema = Unischema(stored_schema.name, list(schema.fields.values()))
    selected = list(schema.fields)

    before = layout_stats(source_url, columns=selected,
                          storage_options=storage_options)

    reader_kwargs = dict(reader_kwargs or {})
    reader_kwargs.setdefault('shuffle_row_groups', False)
    reader_kwargs.setdefault('num_epochs', 1)
    reader_kwargs['schema_fields'] = selected
    reader_kwargs['predicate'] = predicate
    reader_kwargs['storage_options'] = storage_options
    with make_reader(source_url, **reader_kwargs) as reader:
        rows = write_rows(output_url, schema,
                          (row._asdict() for row in reader),
                          rows_per_rowgroup=int(rows_per_rowgroup),
                          storage_options=storage_options)

    after = layout_stats(output_url, columns=selected,
                         storage_options=storage_options)
    summary = {'rows': rows, 'rows_per_rowgroup': int(rows_per_rowgroup),
               'columns': selected, 'output_url': output_url,
               'before': before, 'after': after,
               'waste_bytes_saved': before['waste_bytes']
               - after['waste_bytes']}
    logger.info('rewrite_layout: %d rows -> %s; waste %d -> %d bytes '
                '(%.1f%% -> %.1f%%)', rows, output_url,
                before['waste_bytes'], after['waste_bytes'],
                before['waste_pct'], after['waste_pct'])
    return summary
