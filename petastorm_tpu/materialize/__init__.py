"""Proactive materialization plane: decode once, serve forever (ISSUE 18).

Every cache tier so far is reactive — decoded entries, wire-shaped
slabs, and coalesced range plans exist only after some consumer paid the
cold path, so a new tenant's first epoch still runs 2-3.5x slower than a
warm fleet (ROADMAP item 4).  This package inverts that, per the tf.data
service paper's snapshot/"ingestion-as-a-service" direction and
MinatoLoader's pay-once preprocessing argument (PAPERS.md): background
jobs warm datasets AHEAD of demand using capacity the autoscaler would
otherwise drain away.

Three job kinds, one controller:

* **pre-publish** (:class:`MaterializeController`) — decode every piece
  of a dataset through the EXACT reader-worker code path consumers run
  (``PyDictReaderWorker`` / ``ArrowReaderWorker``, instantiated
  standalone with a capturing result cache) and publish the entries into
  the cluster cache plane under the digests
  :class:`service.cluster.ClusterCacheIdentity` computes — so a later
  consumer's first epoch is all HITs, bit-identical to the decode path
  by construction.  Piece-granular progress persists through the PR 15
  snapshot+journal ledger (``kind='materialize_ledger'``): a killed
  controller resumes attempt-intact.  Admission is eviction-aware:
  every publish consults the plane's eviction estimator
  (``CachePlane.admit_publish``) and is refused when it would evict an
  entry hotter than the configured window — warming never evicts
  traffic hotter than what it brings.
* **pre-transcode to wire format** (``transcode``) — columnar entries
  are additionally published bf16/uint8-narrowed per the public
  ``jax/transfer.py :: wire_dtype_for`` policy, under a distinct
  ``:w{policy}`` key suffix, so a warm serve can skip decode AND collate
  AND narrowing; digest identity against the streamed path is asserted
  at publish time (the same ``widen(narrow(rows))`` contract PR 17
  pinned — bf16->f32 widening is exact).
* **rewrite layout** (``rewrite``) — re-shard a hot dataset into
  row-group sizes matched to split geometry and repack selected columns
  contiguously, driven by the ingest planner's gap/waste stats, so the
  PR 14 coalesced range plans fetch zero waste bytes.  The row sink
  (``write_rows``) is shared with ``tools/pack_dataset.py`` — offline
  CLI packing and fleet rewrite jobs produce byte-identical layouts.

Warming candidates come from the provenance journal's observed access
patterns (``derive_candidates``): records that paid a cold decode name
the dataset roots worth warming, with per-tenant attribution for free.

Kill switch: ``PETASTORM_TPU_NO_MATERIALIZE=1`` disables every job kind
(the controller constructs but refuses to run); degrade everywhere —
admission refusals, unencodable entries, unsupported reader kwargs, and
wire-plan-ineligible datasets all skip work rather than raise.
"""

import os

KILL_SWITCH = 'PETASTORM_TPU_NO_MATERIALIZE'


def killed():
    """The materialization plane's kill switch (env beats everything)."""
    return bool(os.environ.get(KILL_SWITCH))


from petastorm_tpu.materialize.controller import (  # noqa: E402,F401
    MATERIALIZE_LEDGER_KIND, MaterializeController, derive_candidates)
from petastorm_tpu.materialize.rewrite import (  # noqa: E402,F401
    layout_stats, rewrite_layout, write_rows)
from petastorm_tpu.materialize.transcode import (  # noqa: E402,F401
    is_wire_entry, widen_entry, wire_entry, wire_key)

__all__ = ['KILL_SWITCH', 'killed', 'MaterializeController',
           'MATERIALIZE_LEDGER_KIND', 'derive_candidates', 'layout_stats',
           'rewrite_layout', 'write_rows', 'wire_entry', 'widen_entry',
           'wire_key', 'is_wire_entry']
