"""Wire-format pre-transcode: publish entries already narrowed (ISSUE 18b).

A raw materialized entry saves the decode; a consumer's transfer plane
still collates and narrows it per the wire policy every epoch.  This
module publishes a SECOND entry per piece — the stacked columns already
cast to their wire dtypes via the public ``jax/transfer.py ::
wire_dtype_for`` — under a distinct ``:w{policy}`` key suffix, so a
wire-aware serve skips decode AND collate AND narrowing.

The correctness contract is PR 17's: resident and streamed paths both
deliver ``widen(narrow(rows))``, bit-identical, because bf16->f32 (and
every exact wire) widens losslessly.  ``verify_wire_identity`` asserts
exactly that at publish time — the host-side widen of the entry equals
the jitted :class:`jax.residency.WirePlan` widen of the same narrow —
so a wire entry can never drift from what the streamed path delivers.

Wire entries are self-describing (policy token + per-column output
dtypes ride the entry), so a serve needs no side channel to widen.
Datasets whose columns fall outside the transfer plane's dtype support
matrix, or that a policy leaves unnarrowed, publish no wire entry — the
raw entry already covers them (degrade, never raise).
"""

import hashlib
import logging

import numpy as np

logger = logging.getLogger(__name__)

__all__ = ['policy_token', 'wire_key', 'wire_entry', 'widen_entry',
           'is_wire_entry', 'verify_wire_identity']

#: Wire entries live beside raw entries in the same plane, distinguished
#: by this key suffix (the plane digest then mixes in the policy too —
#: two policies never collide).
_WIRE_SUFFIX = ':w{%s}'


def policy_token(policy):
    """Stable string identity of a wire policy ('auto' or a per-field
    dtype map) — part of the cache key, so policy changes re-publish."""
    if not policy:
        return 'none'
    if isinstance(policy, str):
        return policy
    if isinstance(policy, dict):
        body = ','.join('%s=%s' % (k, np.dtype(v).str)
                        for k, v in sorted(policy.items()))
        return hashlib.blake2b(body.encode('utf-8'),
                               digest_size=6).hexdigest()
    return hashlib.blake2b(repr(policy).encode('utf-8'),
                           digest_size=6).hexdigest()


def wire_key(cache_key, policy):
    """The plane key of a piece's wire-format entry: the piece's raw
    result-cache key (the reader workers' single-source-of-truth format)
    plus the policy suffix."""
    return cache_key + _WIRE_SUFFIX % policy_token(policy)


def wire_entry(columns, policy='auto'):
    """Build the wire-format entry value for one piece's stacked columns,
    or None when the piece cannot ride (unsupported dtype, empty, or the
    policy narrows nothing — a wire copy identical to the raw entry
    would only burn plane capacity).

    The value is a plain dict (pickled by ``encode_entry``): narrowed
    columns + the output dtypes ``widen_entry`` needs to restore them.
    """
    from petastorm_tpu.jax.residency import wire_plan
    if not isinstance(columns, dict) or not columns:
        return None
    if not all(isinstance(v, np.ndarray) for v in columns.values()):
        return None
    plan = wire_plan(columns, policy)
    if plan is None or not plan.narrowed:
        return None
    return {'__wire__': 1,
            'policy': policy_token(policy),
            'columns': plan.narrow(columns),
            'out': {name: f.out.str for name, f in plan.fields.items()}}


def is_wire_entry(value):
    return isinstance(value, dict) and value.get('__wire__') == 1


def widen_entry(entry):
    """Host-side inverse of the narrow: cast every column back to its
    canonical output dtype (exact for bf16->f32 and all exact wires —
    the delivered batch is bit-identical to the streamed path's
    ``widen(narrow(rows))``)."""
    return {name: np.asarray(col).astype(np.dtype(entry['out'][name]),
                                         copy=False)
            for name, col in entry['columns'].items()}


def verify_wire_identity(columns, entry, policy='auto'):
    """Assert the PR 17 contract on a freshly built wire entry: the
    host widen of the entry is bit-identical to the jitted
    ``WirePlan.widen`` of the same narrow (what the streamed transfer
    plane delivers).  Returns True/False; never raises (a verify
    failure refuses the publish, it must not kill the controller)."""
    try:
        import jax.numpy as jnp
        from petastorm_tpu.jax.residency import wire_plan
        plan = wire_plan(columns, policy)
        if plan is None:
            return False
        host = widen_entry(entry)
        device = plan.widen({name: jnp.asarray(col)
                             for name, col in entry['columns'].items()})
        for name in columns:
            if not np.array_equal(host[name], np.asarray(device[name])):
                return False
        return True
    except Exception:  # noqa: BLE001 — verify failure degrades, never raises
        logger.warning('materialize: wire identity verify failed',
                       exc_info=True)
        return False
