"""Synthetic reader for testing adapters without a dataset on disk.

Parity: reference ``petastorm/test_util/reader_mock.py :: ReaderMock``.
Generates rows straight from a :class:`~petastorm_tpu.unischema.Unischema`
(deterministic per row index), walks and quacks like a
:class:`~petastorm_tpu.reader.Reader` (iterator protocol, ``schema``,
``ngram``, ``batched_output``, ``stop/join/reset``, context manager), and
plugs into every adapter (``make_petastorm_dataset``, torch loaders,
``petastorm_tpu.jax.DataLoader``).
"""

import numpy as np


def schema_data_generator(schema, index, rng=None):
    """One deterministic row dict for ``schema`` at row ``index``."""
    rng = rng or np.random.default_rng(index)
    row = {}
    for name, field in schema.fields.items():
        dtype = np.dtype(field.numpy_dtype)
        shape = tuple(d if d is not None else 4
                      for d in (field.shape or ()))
        if dtype.kind in ('U', 'S', 'O'):
            row[name] = '%s_%d' % (name, index)
        elif dtype.kind == 'f':
            row[name] = (np.full(shape, index, dtype) if shape
                         else dtype.type(index))
        elif dtype.kind in ('i', 'u'):
            row[name] = (rng.integers(0, 127, shape).astype(dtype) if shape
                         else dtype.type(index))
        elif dtype.kind == 'b':
            row[name] = (np.full(shape, index % 2, dtype) if shape
                         else dtype.type(index % 2))
        elif dtype.kind == 'M':
            row[name] = np.datetime64('2020-01-01') + np.timedelta64(index, 'D')
        else:
            row[name] = dtype.type(index)
    return row


class ReaderMock(object):
    """Iterator of synthetic schema rows.

    ``num_rows=None`` streams forever (the reference mock's behavior);
    bounded mocks raise ``StopIteration`` after ``num_rows`` and support
    ``reset()``.
    """

    def __init__(self, schema, data_generator=schema_data_generator,
                 num_rows=None):
        self.schema = schema
        self.ngram = None
        self.batched_output = False
        self.last_row_consumed = False
        self._generator = data_generator
        self._num_rows = num_rows
        self._index = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._num_rows is not None and self._index >= self._num_rows:
            self.last_row_consumed = True
            raise StopIteration
        row = self._generator(self.schema, self._index)
        self._index += 1
        return self.schema.make_namedtuple_from_dict(row)

    def next(self):
        return self.__next__()

    def reset(self):
        if not self.last_row_consumed:
            # Mirror the real Reader's guard: a mock that permitted
            # mid-iteration reset would green-light adapter code that
            # crashes on the genuine article.
            raise NotImplementedError(
                'reset() mid-iteration is not supported (matches Reader)')
        self._index = 0
        self.last_row_consumed = False

    def stop(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()
