"""Replay a protocol-model counterexample against a real Dispatcher.

The model checker (``petastorm-tpu-model``) proves properties of the
*model*; this harness closes the loop to the *code*: it takes the
bridge spec a violated invariant renders (``analysis/protocol/bridge.py``
→ ``protocol.steps``, the shortest counterexample's action labels) and
drives a real in-process :class:`~petastorm_tpu.service.Dispatcher`
through the same schedule — real ledger file, real ``_op_*`` handlers,
real crash/restart via the release-and-reacquire idiom the control-plane
tests use.  The protocol invariants are asserted on the REAL object
after every step, so a model counterexample that the code actually
shares becomes a failing real-process assertion
(:class:`ProtocolReplayError`), and one the code does NOT share (a
model-only artifact) replays clean.

Only split-lease traces are replayable today: that model's alphabet maps
one-to-one onto dispatcher operations.  Drain and piece-lease traces
carry enough in the spec to replay, but no harness binding exists yet —
:func:`replay` refuses them loudly rather than pretending.
"""

import re

__all__ = ['ProtocolReplayError', 'replay']


class ProtocolReplayError(AssertionError):
    """A protocol invariant broke on the real dispatcher during replay."""


_STEP = re.compile(r'^(?P<action>\w+)\((?P<args>[^)]*)\)$')

#: Model actions with no dispatcher-side effect (data plane / worker
#: internals): replayed as no-ops.
_NO_OP_ACTIONS = frozenset(['stream', 'worker_crash'])


def _parse(label):
    match = _STEP.match(label)
    if match is None:
        return label, ()
    args = tuple(a.strip() for a in match.group('args').split(',')
                 if a.strip())
    return match.group('action'), args


class _SplitLeaseReplay(object):
    """One split-lease replay session: model worker/split names map to
    real worker ids / split ids as the trace grants them."""

    def __init__(self, config_factory):
        from petastorm_tpu.service import Dispatcher
        self._dispatcher_cls = Dispatcher
        self._config_factory = config_factory
        self.dispatcher = Dispatcher(config_factory())
        self.workers = {}          # model worker -> real worker_id
        self.splits = {}           # model split -> real split_id
        self.done_seen = set()     # real split ids observed DONE
        self.failed_seen = set()   # real split ids observed FAILED
        self.pre_crash_attempts = None

    # -- step handlers --------------------------------------------------------

    def register(self, w):
        reply = self.dispatcher._op_register_worker(
            {'data_addr': 'tcp://replay:%d' % (len(self.workers) + 1)})
        self.workers[w] = reply['worker_id']

    worker_restart = register

    def lease(self, w, s):
        if w not in self.workers:
            self.register(w)
        reply = self.dispatcher._op_lease({'worker_id': self.workers[w]})
        split = reply.get('split')
        if split is None:
            raise ProtocolReplayError(
                'replay step lease(%s,%s): the real dispatcher granted '
                'nothing (reply %r) where the model granted a lease'
                % (w, s, reply))
        self.splits[s] = split['split_id']

    def complete(self, w, s):
        self.dispatcher._op_complete({'worker_id': self.workers[w],
                                      'split_id': self.splits[s]})

    complete_forget = complete

    def complete_crash_prereply(self, w, s):
        # The durable DONE record lands before the reply; the crash eats
        # only the reply — complete, then die.
        self.complete(w, s)
        self.dispatcher_crash()

    def complete_crash_prejournal(self, w, s):
        # The crash lands before the write-ahead: durably the split is
        # still a lease — die without completing.
        self.dispatcher_crash()

    def adopt(self, w, s):
        if w not in self.workers:
            self.register(w)
        self.dispatcher._op_heartbeat({'worker_id': self.workers[w],
                                       'held': [self.splits[s]]})

    def expire(self, s):
        self._lapse(self.splits[s])

    def orphan_requeue(self, s):
        self._lapse(self.splits[s])

    def dispatcher_crash(self):
        d = self.dispatcher
        with d._lock:
            self.pre_crash_attempts = {sp.split_id: sp.attempt
                                       for sp in d._splits}
        d._ledger_save(force=True)
        d._ledger.release()  # the flock dies with the pid

    def dispatcher_restart(self):
        self.dispatcher = self._dispatcher_cls(self._config_factory())
        self.workers = {}  # registration does not survive a restart
        if self.pre_crash_attempts is not None:
            with self.dispatcher._lock:
                after = {sp.split_id: sp.attempt
                         for sp in self.dispatcher._splits}
            for split_id, attempt in self.pre_crash_attempts.items():
                if after.get(split_id, attempt) != attempt:
                    raise ProtocolReplayError(
                        'restart-never-burns violated on the real '
                        'dispatcher: split %d attempt %d -> %d across '
                        'crash/restart (ledger restore burned an '
                        'attempt)' % (split_id, attempt,
                                      after[split_id]))
            self.pre_crash_attempts = None

    # -- plumbing -------------------------------------------------------------

    def _lapse(self, split_id):
        d = self.dispatcher
        with d._lock:
            d._splits[split_id].lease_expires = 0.0
        d._expire_leases()

    def check_invariants(self, label):
        from petastorm_tpu.service.dispatcher import _DONE, _FAILED
        with self.dispatcher._lock:
            states = {sp.split_id: sp.state
                      for sp in self.dispatcher._splits}
        for split_id in self.done_seen:
            if states.get(split_id) != _DONE:
                raise ProtocolReplayError(
                    'exactly-once violated on the real dispatcher after '
                    '%r: split %d was DONE and is now %r — completed '
                    'work resurrected' % (label, split_id,
                                          states.get(split_id)))
        for split_id in self.failed_seen:
            if states.get(split_id) != _FAILED:
                raise ProtocolReplayError(
                    'poison-sticky violated on the real dispatcher '
                    'after %r: split %d was FAILED and is now %r'
                    % (label, split_id, states.get(split_id)))
        for split_id, state in states.items():
            if state == _DONE:
                self.done_seen.add(split_id)
            elif state == _FAILED:
                self.failed_seen.add(split_id)

    def run(self, labels):
        executed = []
        try:
            for label in labels:
                action, args = _parse(label)
                if action in _NO_OP_ACTIONS:
                    executed.append(label)
                    continue
                handler = getattr(self, action, None)
                if handler is None:
                    raise ValueError(
                        'replay has no binding for model action %r — '
                        'extend _SplitLeaseReplay alongside the model'
                        % label)
                handler(*args)
                self.check_invariants(label)
                executed.append(label)
        finally:
            try:
                self.dispatcher._ledger.release()
            except Exception:  # noqa: BLE001 — teardown after the verdict
                pass
        return executed


def replay(spec, config_factory):
    """Drive a real dispatcher through ``spec['protocol']['steps']``.

    ``spec`` is a bridge/--spec-json dict; ``config_factory`` returns a
    fresh ``ServiceConfig`` for the SAME ledger path on every call (each
    dispatcher restart constructs a new one against the survivor file).

    Returns ``{'ok': True, 'steps': [...]}`` when the real code upholds
    the protocol invariants through the whole schedule; raises
    :class:`ProtocolReplayError` when it shares the model's violation.
    """
    protocol = spec.get('protocol') or {}
    model = protocol.get('model')
    if model != 'split-lease':
        raise ValueError('only split-lease traces are replayable, got %r'
                         % (model,))
    steps = list(protocol.get('steps') or [])
    if not steps:
        raise ValueError('spec carries no protocol.steps to replay')
    session = _SplitLeaseReplay(config_factory)
    executed = session.run(steps)
    return {'ok': True, 'model': model, 'steps': executed,
            'invariant': protocol.get('invariant')}
