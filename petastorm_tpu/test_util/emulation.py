"""Deterministic storage emulation for tests and benches (ISSUE 14).

:class:`BandwidthLimitedFilesystem` emulates cold-object-store storage
over any fsspec filesystem: every binary read streams chunk by chunk
paying ``bytes/bps`` of GIL-released sleep, and files at or above
``cold_threshold`` bytes additionally pay ``cold_latency`` once per open
handle before their first read — a cold-tier GET/recall round trip.

Promoted out of ``benchmark/hostplane`` (which re-exports it): it is the
correctness harness for the ingest plane and the skew-scheduling leg,
so it needs direct unit tests (``tests/test_emulation_fs.py``) instead
of being exercised only by running the bench.
"""

import time

__all__ = ['BandwidthLimitedFilesystem']


#: Emulated reads stream in 256 KiB chunks, each followed by its share
#: of the bandwidth sleep — like a real remote filesystem.  One giant
#: read-then-sleep would be wrong twice over: no cold store returns
#: 10 MB in a single burst, and the undivided Python-level read of that
#: burst holds the GIL long enough to starve every other worker thread
#: (measured: a 10.7 MB single read cost 0.84 s of real time on this
#: sandbox before its sleep even began).
_BW_CHUNK = 262144


class _BandwidthLimitedFile(object):
    """Delegating file handle whose reads stream chunk by chunk, each
    chunk paying ``len(chunk)/bps`` of sleep — a GIL-released wait,
    exactly like a real network/cold-storage read.  ``cold_latency``
    is paid once, before the handle's first read: the cold-tier
    GET/recall round trip."""

    def __init__(self, inner, bps, cold_latency=0.0):
        self._f = inner
        self._bps = bps
        self._pending_latency = cold_latency

    def read(self, n=-1):
        if self._pending_latency:
            latency, self._pending_latency = self._pending_latency, 0.0
            time.sleep(latency)
        out = []
        remaining = n
        while remaining != 0:
            take = _BW_CHUNK if remaining < 0 else min(_BW_CHUNK, remaining)
            data = self._f.read(take)
            if not data:
                break
            out.append(data)
            time.sleep(len(data) / self._bps)
            if remaining > 0:
                remaining -= len(data)
        return b''.join(out)

    def __getattr__(self, name):
        if name == '_f':  # mid-unpickle: not yet restored
            raise AttributeError(name)
        return getattr(self._f, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()


class BandwidthLimitedFilesystem(object):
    """Delegating fsspec wrapper emulating cold-storage bandwidth: every
    binary read sleeps ``bytes/bps``.  The skew-scheduling and
    object-store-ingest bench legs use it to make row groups
    *fetch-dominated* — the latency parallelizes across worker/fetch
    threads like a real remote filesystem, independent of host core
    count (the cold-filesystem skew source from the adaptive scheduler's
    motivation, reproduced deterministically).

    ``cold_latency``: additionally, files of at least ``cold_threshold``
    bytes pay this many seconds once per open handle before their first
    read — a cold-object GET/recall round trip.  Size-gated so only the
    heavy objects read as cold-tier residents (small hot files stay
    bandwidth-limited only), which is how object stores actually tier.
    """

    def __init__(self, inner, bps, cold_latency=0.0, cold_threshold=1 << 20):
        self._inner = inner
        self._bps = float(bps)
        self._cold_latency = float(cold_latency)
        self._cold_threshold = int(cold_threshold)

    def open(self, path, mode='rb', **kwargs):
        handle = self._inner.open(path, mode, **kwargs)
        if 'r' in mode and 'b' in mode:
            latency = 0.0
            if self._cold_latency:
                try:
                    if self._inner.size(path) >= self._cold_threshold:
                        latency = self._cold_latency
                except Exception:  # noqa: BLE001 — emulation is best-effort
                    pass
            return _BandwidthLimitedFile(handle, self._bps, latency)
        return handle

    def __getattr__(self, name):
        if name == '_inner':  # mid-unpickle: not yet restored
            raise AttributeError(name)
        return getattr(self._inner, name)
