"""Fleet chaos harness: named injection seams, seeded scenarios, and
the ``petastorm-tpu-chaos`` matrix runner (ISSUE 15).

Before this module the repo's fault inventory was two flaky-filesystem
wrappers and a handful of one-off SIGKILL tests (PRs 1/3/10) — each a
bespoke subprocess dance proving one failure mode once.  This module
turns that into a *plane*:

* **Seams** — named injection points threaded through the service at
  the places faults actually enter (the seam registry below is the
  contract).  Inert seams are one ``is None`` check (measured
  nanoseconds); activation is process-local
  (:func:`activate`/:func:`deactivate`) or via the
  ``PETASTORM_TPU_CHAOS`` env var (a JSON fault spec — how faults reach
  subprocess workers), seeded so a scenario replays deterministically.
* **Scenarios** — a seeded spec naming faults (seam, action,
  probability, budget), process kills at named *phases* of an epoch
  (observed via the dispatcher's ``stats`` RPC, not wall-clock sleeps),
  and config overrides (tiny shm arena = ENOSPC, tiny plane tiers =
  full plane, the PR 14 emulation filesystem = cold-store latency
  spikes).
* **The matrix runner** — executes one epoch of a real service (real
  dispatcher, real subprocess workers, real ``ServiceDataLoader``)
  under each scenario and asserts the three invariants every scenario
  must preserve: the **delivery digest** equals the direct-read ground
  truth (bit-identical rows, order-independent), **exactly-once** (every
  row id delivered exactly once), and **zero residue** (no shm
  segments, no ledger/plane tmp files left behind).

Seam registry (the names are API — scenarios and instrumentation agree
on them here):

========================  =======================  ======================
seam                      fired from               actions
========================  =======================  ======================
``rpc.request``           ``_Rpc.call`` (worker +  ``drop`` (surfaces as
                          client control RPCs)     a timeout on a
                                                   recycled socket),
                                                   ``delay``
``dispatcher.rpc``        dispatcher serve loop,   ``delay`` (REP may
                          before dispatch          never drop a reply:
                                                   the socket would
                                                   wedge — lost messages
                                                   inject at the REQ
                                                   side)
``worker.chunk``          data-plane chunk send    ``drop``, ``dup``,
                          (byte-path frames only:  ``delay``
                          a duplicated shm
                          descriptor would
                          double-release its slab)
``worker.decode``         decode loop, per leased  ``delay``, ``error``
                          split                    (decode failure ->
                                                   lease expiry path)
``fs.open`` / ``fs.read`` the promoted flaky       raise ``OSError``
                          filesystems below        (transient-retry
                                                   plane)
========================  =======================  ======================

The flaky filesystems (:class:`FlakyOpenFilesystem`,
:class:`FlakyReadFilesystem`) were promoted here out of
``test_util/fault_injection.py`` (which keeps back-compat re-exports) —
the ``BandwidthLimitedFilesystem`` promotion precedent from PR 14: they
are correctness harnesses for the retry/poisoning plane and belong in
the seam registry with direct unit tests, not in a side module
exercised only transitively.

Module imports stay stdlib-only (the service imports this at module
import time; numpy/pyarrow/jax load lazily inside the runner).
"""

import json
import logging
import os
import random
import time

from petastorm_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)

__all__ = ['activate', 'deactivate', 'active', 'inject', 'ChaosState',
           'SEAMS', 'SCENARIOS', 'SMOKE_SCENARIOS', 'FILESYSTEM_FAULTS',
           'DeliveryDigest', 'run_scenario', 'run_matrix', 'main',
           'is_data_file', 'FlakyOpenFilesystem', 'FlakyReadFilesystem']

#: The seam names instrumentation points fire (see the module
#: docstring's registry table).  ``inject`` warns once on a spec naming
#: a seam outside this set — a typo'd seam silently injecting nothing
#: is the least debuggable chaos of all.
SEAMS = ('rpc.request', 'dispatcher.rpc', 'worker.chunk', 'worker.decode',
         'fs.open', 'fs.read')

_ACTIONS = ('drop', 'dup', 'delay', 'error')

#: Seams whose instrumentation point sits inside an error handler that
#: models the fault (decode failure -> lease expiry; fs failure -> the
#: transient-retry plane).  ``action: error`` elsewhere would unwind a
#: loop with no handler — e.g. the dispatcher serve loop would die
#: without sending its REP reply, the exact outage the seam contract
#: forbids — so the spec is rejected at construction.
_ERROR_SEAMS = ('worker.decode', 'fs.open', 'fs.read')

#: Env var carrying a JSON fault spec into subprocess workers; the
#: per-role salt decorrelates their RNG streams while staying
#: deterministic for a fixed (seed, salt) pair.
CHAOS_ENV = 'PETASTORM_TPU_CHAOS'
CHAOS_SALT_ENV = 'PETASTORM_TPU_CHAOS_SALT'
#: Path PREFIX under which an env-armed process dumps its injection
#: counts at clean exit (``<prefix>.<pid>.json``) — how the matrix
#: runner's report aggregates what actually fired across subprocess
#: workers (a SIGKILLed victim's counts die with it, by design).
CHAOS_COUNTS_ENV = 'PETASTORM_TPU_CHAOS_COUNTS'


class ChaosInjectedError(OSError):
    """The injected failure for ``action: error`` faults."""


class ChaosState(object):  # ptlint: disable=pickle-unsafe-attrs — process-local by design; fault specs cross process boundaries as JSON via PETASTORM_TPU_CHAOS, never by pickling the state
    """One activated fault spec: seeded RNG + per-fault budgets/counts.

    ``spec``: ``{'seed': int, 'faults': [{'seam', 'action', 'p',
    'delay_s', 'max', 'ops'}, ...]}`` — ``p`` the per-call probability
    (default 1), ``max`` the injection budget (default unbounded),
    ``ops`` an optional allowlist matched against the seam context's
    ``op``/``split`` field.
    """

    def __init__(self, spec, salt=0):
        self.spec = dict(spec or {})
        self.seed = int(self.spec.get('seed', 0))
        self.rng = random.Random((self.seed, int(salt)).__repr__())
        self.counts = {}
        self._lock = make_lock('test_util.chaos.ChaosState._lock')
        self._by_seam = {}
        for fault in self.spec.get('faults') or ():
            seam = fault.get('seam')
            action = fault.get('action')
            if seam not in SEAMS:
                logger.warning('chaos fault names unknown seam %r '
                               '(known: %s); it will never fire', seam,
                               ', '.join(SEAMS))
            if action not in _ACTIONS:
                raise ValueError('chaos fault action must be one of %s, '
                                 'got %r' % (_ACTIONS, action))
            if action == 'error' and seam not in _ERROR_SEAMS:
                raise ValueError(
                    "action 'error' is only injectable at %s (seams "
                    'whose caller models the failure); %r has no '
                    'handler and the raise would kill the process, not '
                    'fault it' % (_ERROR_SEAMS, seam))
            self._by_seam.setdefault(seam, []).append(dict(fault))

    def fire(self, seam, ctx):
        """First matching fault's action for one seam hit (None = no
        injection).  ``delay`` sleeps here and returns ``'delay'``;
        ``error`` raises :class:`ChaosInjectedError`; ``drop``/``dup``
        return the string for the instrumentation point to act on."""
        faults = self._by_seam.get(seam)
        if not faults:
            return None
        for fault in faults:
            ops = fault.get('ops')
            if ops is not None and ctx.get('op') not in ops:
                continue
            with self._lock:
                budget = fault.get('max')
                key = (seam, fault.get('action'))
                if budget is not None \
                        and self.counts.get(key, 0) >= int(budget):
                    continue
                if self.rng.random() >= float(fault.get('p', 1.0)):
                    continue
                self.counts[key] = self.counts.get(key, 0) + 1
            action = fault['action']
            if action == 'delay':
                time.sleep(float(fault.get('delay_s', 0.05)))
                return 'delay'
            if action == 'error':
                raise ChaosInjectedError(
                    'chaos: injected error at seam %r (%r)' % (seam, ctx))
            return action
        return None

    def fired(self):
        """Total injections across every fault (the 'did the scenario
        actually exercise anything' assert)."""
        with self._lock:
            return sum(self.counts.values())

    def dump_counts(self, prefix):
        """Best-effort ``<prefix>.<pid>.json`` dump of the counts —
        registered atexit by env arming so the matrix runner can
        aggregate injections across subprocess workers."""
        from petastorm_tpu.telemetry.provenance import atomic_json_dump
        with self._lock:
            counts = {'%s/%s' % key: n for key, n in self.counts.items()}
        atomic_json_dump('%s.%d.json' % (prefix, os.getpid()), counts)


_ACTIVE = None


def activate(spec, salt=None):
    """Arm the process-local chaos state (replacing any previous one).
    Returns the :class:`ChaosState` so callers can read counts."""
    global _ACTIVE
    if salt is None:
        salt = int(os.environ.get(CHAOS_SALT_ENV, '0') or 0)
    _ACTIVE = ChaosState(spec, salt=salt)
    return _ACTIVE


def deactivate():
    global _ACTIVE
    _ACTIVE = None


def active():
    """The armed :class:`ChaosState`, or None."""
    return _ACTIVE


def inject(seam, **ctx):
    """THE instrumentation-point call.  Inert (None) unless a spec is
    armed — one global read + ``is None`` check on the hot path."""
    state = _ACTIVE
    if state is None:
        return None
    return state.fire(seam, ctx)


def _arm_from_env():
    """Arm from ``PETASTORM_TPU_CHAOS`` at import — how a fault spec
    reaches subprocess workers/dispatchers the runner spawns."""
    raw = os.environ.get(CHAOS_ENV)
    if not raw:
        return
    try:
        state = activate(json.loads(raw))
    except (ValueError, TypeError) as e:
        logger.warning('ignoring unparseable %s (%s)', CHAOS_ENV, e)
        return
    prefix = os.environ.get(CHAOS_COUNTS_ENV)
    if prefix:
        import atexit
        atexit.register(state.dump_counts, prefix)


_arm_from_env()


# -- promoted fault-injection filesystems (were test_util/fault_injection) ----

def is_data_file(path):
    """True for row-group data files (``*.parquet`` not ``_``-prefixed).
    Only data files are failed: footer/metadata reads happen at reader
    construction, which deliberately has no retry layer."""
    name = path.rsplit('/', 1)[-1]
    return name.endswith('.parquet') and not name.startswith('_')


class FlakyOpenFilesystem(object):
    """Delegating fs whose first ``fail_times`` opens of each data file
    raise OSError — the ``fs.open`` seam of the registry, wrappable
    around any fsspec filesystem and passed as
    ``make_reader(..., filesystem=...)`` to simulate GCS flakes
    deterministically."""

    def __init__(self, real_fs, fail_times):
        self._real = real_fs
        self._fail_times = fail_times
        self._counts = {}
        self._lock = make_lock(
            'test_util.chaos.FlakyOpenFilesystem._lock')

    # Documented to ride ``make_reader(..., filesystem=...)``, which the
    # ProcessPool pickles into worker args — the lock (and the injection
    # counts, which are per-process bookkeeping) must stay behind.
    def __getstate__(self):
        state = self.__dict__.copy()
        del state['_lock']
        # Counts consumed in the parent (e.g. the construction-time
        # footer read) must not eat a worker's injection budget.
        del state['_counts']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._counts = {}
        self._lock = make_lock(
            'test_util.chaos.FlakyOpenFilesystem._lock')

    def open(self, path, *args, **kwargs):
        if is_data_file(path):
            with self._lock:
                n = self._counts.get(path, 0)
                self._counts[path] = n + 1
            if n < self._fail_times:
                inject('fs.open', path=path)
                raise OSError('injected transient open failure #%d on %s'
                              % (n, path))
        return self._real.open(path, *args, **kwargs)

    def __getattr__(self, name):
        if name == '_real':  # mid-unpickle: not yet restored
            raise AttributeError(name)
        return getattr(self._real, name)


class FlakyReadFilesystem(FlakyOpenFilesystem):
    """First open of each data file succeeds but the handle dies on
    first read (the ``fs.read`` seam) — exercises eviction of a wedged
    cached handle."""

    def open(self, path, *args, **kwargs):
        handle = self._real.open(path, *args, **kwargs)
        if is_data_file(path):
            with self._lock:
                n = self._counts.get(path, 0)
                self._counts[path] = n + 1
            if n < self._fail_times:
                return _DyingFile(handle)
        return handle


class _DyingFile(object):
    def __init__(self, inner):
        self._inner = inner

    def read(self, *args, **kwargs):
        inject('fs.read')
        raise OSError('injected read failure')

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _bandwidth_limited(*args, **kwargs):
    from petastorm_tpu.test_util.emulation import BandwidthLimitedFilesystem
    return BandwidthLimitedFilesystem(*args, **kwargs)


#: The filesystem half of the seam registry: every deterministic
#: storage-fault wrapper the plane owns, by name (the scenario spec's
#: ``filesystem`` key indexes it).
FILESYSTEM_FAULTS = {
    'flaky_open': FlakyOpenFilesystem,
    'flaky_read': FlakyReadFilesystem,
    'bandwidth_limited': _bandwidth_limited,
}


# -- delivery digest ----------------------------------------------------------

class DeliveryDigest(object):
    """Order-independent, bit-exact digest of a delivered row stream.

    Per row: blake2b over every column's name + raw bytes; rows combine
    by modular sum (order-independent — unordered service delivery and
    the direct-read ground truth digest identically), and the row count
    rides in the final digest so a duplicated row can NEVER cancel a
    missing one.  This is the assertion surface of every chaos
    scenario: content exactness AND exactly-once in one comparison.
    """

    def __init__(self):
        self._sum = 0
        self.rows = 0

    def update(self, chunk):
        import hashlib

        import numpy as np
        names = sorted(chunk)
        cols = [np.asarray(chunk[name]) for name in names]
        for i in range(len(cols[0])):
            h = hashlib.blake2b(digest_size=16)
            for name, col in zip(names, cols):
                h.update(name.encode())
                h.update(np.ascontiguousarray(col[i]).tobytes())
            self._sum = (self._sum
                         + int.from_bytes(h.digest(), 'little')) % (1 << 128)
            self.rows += 1

    def hexdigest(self):
        return '%032x:%d' % (self._sum, self.rows)


def direct_read_digest(dataset_url, reader_kwargs=None):
    """Ground-truth digest: the dataset read directly (no service, no
    faults) through the same batch-reader surface the workers use."""
    from petastorm_tpu.reader import make_batch_reader
    digest = DeliveryDigest()
    kwargs = dict(reader_kwargs or {})
    kwargs.setdefault('workers_count', 1)
    with make_batch_reader(dataset_url, num_epochs=1,
                           shuffle_row_groups=False, **kwargs) as reader:
        for item in reader:
            chunk = (item._asdict() if hasattr(item, '_asdict')
                     else dict(item))
            digest.update(chunk)
    return digest.hexdigest()


# -- scenario catalogue -------------------------------------------------------

#: Epoch phases a kill can target, observed from the dispatcher's
#: ``stats`` RPC (never wall-clock sleeps): ``registered`` = the fleet
#: is up, ``leases`` = work is in flight, ``mid_epoch`` = some work
#: done AND some remaining (the interesting window), ``tail`` = nothing
#: pending.
PHASES = ('registered', 'leases', 'mid_epoch', 'tail')

#: The scenario matrix (>= 6 distinct fault classes per the ISSUE 15
#: acceptance bar).  Every scenario runs one epoch and must preserve
#: digest + exactly-once + zero residue under its fixed seed.
SCENARIOS = {
    'dispatcher_kill': {
        'summary': 'SIGKILL the dispatcher mid-epoch; restart it on the '
                   'same port + ledger — the epoch completes with no '
                   're-decode of done splits',
        'kills': [{'role': 'dispatcher', 'phase': 'mid_epoch',
                   'signal': 'kill', 'restart': True}],
        'dispatcher_subprocess': True,
    },
    'worker_kill': {
        'summary': 'SIGKILL one decode worker mid-epoch; the lease '
                   'expires and the survivor re-decodes',
        'kills': [{'role': 'worker', 'phase': 'mid_epoch',
                   'signal': 'kill', 'restart': False}],
    },
    'worker_drain': {
        'summary': 'SIGTERM one decode worker mid-epoch; it drains '
                   'gracefully — finishes or hands back, zero residue',
        'kills': [{'role': 'worker', 'phase': 'mid_epoch',
                   'signal': 'term', 'restart': False}],
    },
    'message_drop': {
        'summary': 'drop data-plane chunks and control RPCs; resend + '
                   'retry/backoff recover',
        'faults': [
            {'seam': 'worker.chunk', 'action': 'drop', 'p': 0.15,
             'max': 30},
            {'seam': 'rpc.request', 'action': 'drop', 'p': 0.1,
             'max': 15, 'ops': ['heartbeat', 'workers', 'lease']},
        ],
        'config': {'shm': False},
    },
    'message_delay': {
        'summary': 'delay data-plane chunks and dispatcher RPC '
                   'handling; nothing times out into wrongness',
        'faults': [
            {'seam': 'worker.chunk', 'action': 'delay', 'p': 0.3,
             'delay_s': 0.03, 'max': 60},
            {'seam': 'dispatcher.rpc', 'action': 'delay', 'p': 0.3,
             'delay_s': 0.03, 'max': 60},
        ],
        'config': {'shm': False},
    },
    'message_dup': {
        'summary': 'duplicate data-plane chunks; seq-keyed reassembly '
                   'dedupes',
        'faults': [{'seam': 'worker.chunk', 'action': 'dup', 'p': 0.25,
                    'max': 40}],
        'config': {'shm': False},
    },
    'fetch_latency_spike': {
        'summary': 'cold-object-store GETs via the PR 14 emulation '
                   'filesystem under every per-split reader',
        'filesystem': {'kind': 'bandwidth_limited', 'bps': 20e6,
                       'cold_latency': 0.25, 'cold_threshold': 1},
    },
    'shm_enospc': {
        'summary': 'shm arena with no headroom: every descriptor '
                   'publish refuses and degrades to the byte path',
        'config': {'shm_capacity_bytes': 1},
    },
    'plane_enospc': {
        'summary': 'cache plane with full tiers: every publish refuses '
                   '(cache_degraded) and decodes direct',
        'cache_plane': True,
        'config': {'cache_plane_ram_bytes': 1,
                   'cache_plane_disk_bytes': 1},
    },
    # -- ISSUE 16: scale-storm + multi-tenant scenarios ---------------------
    'autoscale_storm': {
        'summary': 'one-worker fleet under the closed-loop autoscaler: '
                   'lease starvation scales out mid-epoch, hysteresis '
                   'keeps the action count inside the damping bound, '
                   'and delivery stays exactly-once',
        'n_workers': 1,
        'config': {'autoscale': True, 'autoscale_min_workers': 1,
                   'autoscale_max_workers': 3, 'autoscale_step': 1,
                   'autoscale_cooldown_s': 1.0, 'autoscale_starve_s': 0.3,
                   'autoscale_idle_s': 3600.0},
        'max_autoscale_actions': 6,
    },
    'autoscale_worker_kill': {
        'summary': 'autoscaled fleet loses a worker to SIGKILL '
                   'mid-epoch: the lease expires, the controller '
                   'backfills capacity, exactly-once holds through the '
                   'churn and the damping bound still holds',
        'n_workers': 2,
        'config': {'autoscale': True, 'autoscale_min_workers': 1,
                   'autoscale_max_workers': 3, 'autoscale_step': 1,
                   'autoscale_cooldown_s': 1.0, 'autoscale_starve_s': 0.3,
                   'autoscale_idle_s': 3600.0},
        'kills': [{'role': 'worker', 'phase': 'mid_epoch',
                   'signal': 'kill', 'restart': False}],
        'max_autoscale_actions': 6,
    },
    'tenant_fair_share': {
        'summary': 'two tenants (weights 1:3) share one fleet over the '
                   'same dataset under WDRR lease scheduling; BOTH '
                   'delivery digests equal the ground truth',
        'tenants': [{'tenant': 'burst', 'weight': 3.0}],
    },
    'tenant_worker_kill': {
        'summary': 'two tenants share the fleet and one worker dies to '
                   'SIGKILL mid-epoch: both tenants stay exactly-once '
                   'through the lease churn',
        'tenants': [{'tenant': 'burst', 'weight': 3.0}],
        'kills': [{'role': 'worker', 'phase': 'mid_epoch',
                   'signal': 'kill', 'restart': False}],
    },
    # -- ISSUE 20: control-plane decision journal ----------------------------
    'decision_journal_kill': {
        'summary': 'SIGKILL the dispatcher mid-scale-storm; the restart '
                   'restores the decision journal from the ledger '
                   'attempt-intact, so petastorm-tpu-why still explains '
                   'the PRE-kill scale-out (rule + inputs, replay-clean) '
                   'and delivery stays exactly-once with zero residue',
        'n_workers': 1,
        'dispatcher_subprocess': True,
        # One lease slot on one worker + single-rowgroup splits: the
        # lone worker is saturated (free_slots 0) for essentially the
        # whole throttled epoch, so the starve window genuinely ripens
        # across the autoscaler's 1 Hz ticks — a guaranteed storm, not
        # a race against epoch completion.
        'config': {'autoscale': True, 'autoscale_min_workers': 1,
                   'autoscale_max_workers': 2, 'autoscale_step': 1,
                   'autoscale_cooldown_s': 1.0, 'autoscale_starve_s': 0.3,
                   'autoscale_idle_s': 3600.0,
                   'max_inflight_splits': 1, 'rowgroups_per_split': 1},
        'kills': [{'role': 'dispatcher', 'phase': 'mid_epoch',
                   'signal': 'kill', 'restart': True}],
        'max_autoscale_actions': 6,
        'check_decision_journal': True,
    },
    # -- ISSUE 18: proactive materialization plane ---------------------------
    'materialize_kill': {
        'summary': 'SIGKILL the materialize controller + its warming '
                   'worker mid-publish: no torn cache entries, the '
                   'restarted controller resumes from the ledger '
                   'attempt-intact, and a plane-cached consumer still '
                   'delivers the ground-truth digest',
        'runner': 'materialize',
        'throttle_s': 0.25,
        'min_entries_before_kill': 3,
    },
}

#: The fast CI smoke: one kill, one drain, one message-fault class, and
#: one ISSUE-16 scale-storm.
SMOKE_SCENARIOS = ('worker_kill', 'worker_drain', 'message_drop',
                   'autoscale_storm')

#: Every key a scenario dict (catalogue or --spec-json) may carry.
#: ``name``/``summary`` label the run; ``protocol`` is the
#: model-checker counterexample payload the bridge attaches
#: (analysis/protocol/bridge.py) — carried through to the report,
#: consumed by test_util/protocol_replay.py, ignored by the runner.
_SPEC_KEYS = frozenset([
    'name', 'summary', 'protocol', 'kills', 'faults', 'config',
    'filesystem', 'cache_plane', 'n_workers', 'dispatcher_subprocess',
    'runner', 'tenants', 'max_autoscale_actions', 'throttle_s',
    'min_entries_before_kill', 'check_decision_journal'])

_KILL_ROLES = ('dispatcher', 'worker', 'materialize')
_KILL_SIGNALS = ('kill', 'term')


def load_spec_json(path):
    """Load + validate a ``--spec-json`` scenario file (ISSUE 19: the
    model-checker counterexample bridge emits these).  Returns
    ``(name, scenario)`` for :func:`run_scenario`; raises ``ValueError``
    on an invalid spec so a typo'd seam/action/phase fails loudly
    instead of silently never firing."""
    with open(path, 'rb') as f:
        spec = json.loads(f.read().decode('utf-8'))
    if not isinstance(spec, dict):
        raise ValueError('spec must be a JSON object, got %s'
                         % type(spec).__name__)
    unknown = sorted(set(spec) - _SPEC_KEYS)
    if unknown:
        raise ValueError('unknown spec key(s) %s (known: %s)'
                         % (', '.join(unknown), ', '.join(sorted(_SPEC_KEYS))))
    for kill in spec.get('kills') or ():
        if not isinstance(kill, dict):
            raise ValueError('each kill must be an object, got %r' % (kill,))
        if kill.get('role') not in _KILL_ROLES:
            raise ValueError('kill role must be one of %s, got %r'
                             % (_KILL_ROLES, kill.get('role')))
        if kill.get('phase') not in PHASES:
            raise ValueError('kill phase must be one of %s, got %r'
                             % (PHASES, kill.get('phase')))
        if kill.get('signal', 'kill') not in _KILL_SIGNALS:
            raise ValueError('kill signal must be one of %s, got %r'
                             % (_KILL_SIGNALS, kill.get('signal')))
    # Fault validation is ChaosState's constructor: unknown actions and
    # unhandleable error seams raise there, unknown seams warn.
    ChaosState({'seed': 0, 'faults': spec.get('faults') or []})
    runner = spec.get('runner')
    if runner not in (None, 'materialize'):
        raise ValueError("runner must be 'materialize' when set, got %r"
                         % (runner,))
    name = str(spec.get('name')
               or 'spec:%s' % os.path.splitext(os.path.basename(path))[0])
    scenario = {key: value for key, value in spec.items() if key != 'name'}
    scenario.setdefault('summary', 'replayed --spec-json scenario')
    return name, scenario


# -- runner -------------------------------------------------------------------

_WORKER_CHILD = r"""
import os, sys
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, sys.argv[2])
from petastorm_tpu.service.worker import Worker
w = Worker(sys.argv[1])
w.install_signal_handlers()
w.run()
"""

_DISPATCHER_CHILD = r"""
import json, os, sys, time
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, sys.argv[3])
from petastorm_tpu.service import Dispatcher, ServiceConfig
spec = json.loads(sys.argv[2])
with Dispatcher(ServiceConfig(**spec), bind=sys.argv[1]) as d:
    while d._thread.is_alive():
        time.sleep(0.2)
"""

_MATERIALIZE_CHILD = r"""
import json, os, sys
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
sys.path.insert(0, sys.argv[2])
from petastorm_tpu.materialize import MaterializeController
spec = json.loads(sys.argv[1])
summary_path = spec.pop('summary_path')
with MaterializeController(**spec) as controller:
    summary = controller.run()
tmp = summary_path + '.part'
with open(tmp, 'w') as f:
    json.dump(summary, f)
os.replace(tmp, summary_path)
"""


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _spawn(child_src, args, spec_env=None):
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PYTHONPATH', None)
    if spec_env:
        env.update(spec_env)
    return subprocess.Popen([sys.executable, '-c', child_src] + list(args),
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _shm_residue(prefix=None):
    from petastorm_tpu.workers_pool import shm_plane
    prefix = prefix or shm_plane.PREFIX
    try:
        return {f for f in os.listdir(shm_plane.SHM_DIR)
                if f.startswith(prefix)}
    except OSError:
        return set()


def make_chaos_dataset(directory, rows=96, row_group_size=4,
                       payload_bytes=2048, seed=0):
    """Tiny plain-parquet dataset for self-contained runs (the CI smoke
    has no fixture tree): ``id`` int64 + a seeded fixed-width payload
    column, sized so an epoch takes long enough to land mid-epoch
    kills."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(directory, exist_ok=True)
    rng = np.random.default_rng(seed)
    payload = rng.integers(0, 255, (rows, payload_bytes), dtype=np.uint8)
    pq.write_table(
        pa.table({'id': np.arange(rows, dtype=np.int64),
                  'payload': list(payload)}),
        os.path.join(directory, 'data.parquet'),
        row_group_size=row_group_size)
    return 'file://' + os.path.abspath(directory), rows


class _Stats(object):  # ptlint: disable=pickle-unsafe-attrs — owned by the runner thread; never crosses a process boundary
    """Best-effort stats poller over the dispatcher RPC (tolerates a
    dead/restarting dispatcher by returning None)."""

    def __init__(self, addr):
        import zmq
        from petastorm_tpu.service.worker import _Rpc
        self._context = zmq.Context()
        self._addr = addr
        self._rpc_cls = _Rpc

    def poll(self):
        return self.call({'op': 'stats'})

    def call(self, request, timeout_s=2.0):
        from petastorm_tpu.errors import ServiceError
        rpc = self._rpc_cls(self._context, self._addr, timeout_s=timeout_s)
        try:
            return rpc.call(request)
        except ServiceError:
            return None
        finally:
            rpc.close()

    def close(self):
        self._context.term()


def _phase_reached(stats, phase, n_workers):
    if stats is None:
        return False
    if phase == 'registered':
        return len(stats.get('workers') or {}) >= n_workers
    if phase == 'leases':
        return stats.get('leased', 0) >= 1
    if phase == 'mid_epoch':
        return stats.get('done', 0) >= 1 and (
            stats.get('pending', 0) + stats.get('leased', 0)) >= 1
    if phase == 'tail':
        return stats.get('pending', 0) == 0
    raise ValueError('unknown phase %r (known: %s)' % (phase, PHASES))


def _run_materialize_scenario(name, dataset_url, rows, workdir, seed=7,
                              expected_digest=None, timeout_s=240.0):
    """The ISSUE 18 crash drill: SIGKILL the materialize controller (a
    single process that is both scheduler and warming worker) while
    publishes are in flight, then assert the three invariants —
    (1) zero torn ``.cpe`` entries (publish is tmp+rename atomic),
    (2) the ledger carries the progress and a restarted controller
    resumes it instead of re-warming, (3) a consumer reading through
    the half-then-fully warmed plane delivers the ground-truth digest
    with zero decode misses.  Same contract as :func:`run_scenario`:
    returns a report, never raises."""
    import signal as _signal

    import numpy as np

    from petastorm_tpu.cache_plane.plane import ENTRY_SUFFIX, decode_entry
    from petastorm_tpu.materialize import MATERIALIZE_LEDGER_KIND
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.service.ledger import DispatcherLedger, decode_splits

    scenario = SCENARIOS[name]
    report = {'scenario': name, 'seed': int(seed), 'ok': False,
              'checks': {}, 'injections': {}}
    plane_dir = os.path.join(workdir, 'mat_plane_%s' % name)
    ledger_path = os.path.join(workdir, 'mat_ledger_%s.json' % name)
    summary_path = os.path.join(workdir, 'mat_summary_%s.json' % name)
    # Disk-only plane (ram tier off): every publish is an inspectable
    # ``.cpe`` file, and the drill leaves nothing in /dev/shm.
    spec = {'dataset_url': dataset_url, 'cache_plane_dir': plane_dir,
            'ledger_path': ledger_path, 'cache_plane_ram_bytes': 0,
            'throttle_s': float(scenario.get('throttle_s', 0.25)),
            'summary_path': summary_path}
    shm_before = _shm_residue()
    proc = None

    def _entries():
        try:
            return sorted(n for n in os.listdir(plane_dir)
                          if n.endswith(ENTRY_SUFFIX))
        except OSError:
            return []

    try:
        # -- phase 1: warm under throttle, SIGKILL mid-publish ---------------
        proc = _spawn(_MATERIALIZE_CHILD,
                      [json.dumps(spec), _repo_root()])
        deadline = time.monotonic() + timeout_s
        want = int(scenario.get('min_entries_before_kill', 3))
        while len(_entries()) < want:
            if proc.poll() is not None or time.monotonic() > deadline:
                report['checks']['kill_controller'] = (
                    'controller finished (%d entr(ies)) before the kill '
                    'window' % len(_entries()))
                return report
            time.sleep(0.02)
        proc.send_signal(_signal.SIGKILL)
        proc.wait(timeout=30)
        report['checks']['kill_controller'] = (
            'SIGKILL pid %d with %d entr(ies) published'
            % (proc.pid, len(_entries())))

        # -- invariant 1: zero torn entries ----------------------------------
        torn = []
        for entry_name in _entries():
            try:
                with open(os.path.join(plane_dir, entry_name), 'rb') as f:
                    decode_entry(f.read())
            except Exception as e:  # noqa: BLE001 — any failure IS the finding
                torn.append('%s: %r' % (entry_name, e))
        report['checks']['zero_torn_entries'] = (
            'ok (%d entr(ies) decode cleanly)' % len(_entries())
            if not torn else '; '.join(torn[:4]))

        # -- invariant 2a: the kill left durable progress in the ledger ------
        state = DispatcherLedger(ledger_path,
                                 kind=MATERIALIZE_LEDGER_KIND).load()
        done_before = 0
        if state and isinstance(state.get('splits'), list):
            try:
                done_before = sum(
                    1 for st, _ in decode_splits(state['splits'])
                    if st == 'done')
            except (ValueError, KeyError, TypeError):
                pass
        report['checks']['ledger_progress'] = (
            'ok (%d piece(s) durably done)' % done_before
            if done_before >= 1 else
            'ledger shows no completed piece after the kill')

        # -- invariant 2b: restart resumes instead of re-warming -------------
        proc = _spawn(_MATERIALIZE_CHILD,
                      [json.dumps(dict(spec, throttle_s=0.0)),
                       _repo_root()])
        proc.wait(timeout=max(1.0, deadline - time.monotonic()))
        try:
            with open(summary_path) as f:
                summary = json.load(f)
        except (OSError, ValueError) as e:
            report['checks']['resume'] = 'no restart summary: %r' % e
            return report
        resumed = int(summary.get('resumed', 0) or 0)
        resumed_ok = (resumed >= max(1, done_before)
                      and summary.get('done') == summary.get('total_pieces')
                      and not summary.get('failed_pieces'))
        report['checks']['resume'] = (
            'ok (resumed %d from the ledger, warmed the remaining %d of %d)'
            % (resumed, int(summary.get('done', 0)) - resumed,
               summary.get('total_pieces', 0)) if resumed_ok
            else 'summary %r' % summary)

        # -- invariant 3: consumer delivery digest + zero decode misses ------
        if expected_digest is None:
            expected_digest = direct_read_digest(dataset_url)
        digest = DeliveryDigest()
        with make_batch_reader(
                dataset_url, num_epochs=1, shuffle_row_groups=False,
                workers_count=1, cache_type='plane',
                cache_location=plane_dir,
                cache_extra_settings={'ram_bytes': 0}) as reader:
            for item in reader:
                digest.update({k: np.asarray(v)
                               for k, v in item._asdict().items()})
            diag = reader.diagnostics
        digest_ok = digest.hexdigest() == expected_digest
        report['checks']['digest'] = (
            'ok' if digest_ok else
            '%s != expected %s' % (digest.hexdigest(), expected_digest))
        report['digest'] = digest.hexdigest()
        misses = int(diag.get('cache_misses', -1))
        served_warm = misses == 0 and int(diag.get('cache_hits', 0)) >= 1
        report['checks']['served_from_plane'] = (
            'ok (%d hit(s), 0 misses)' % int(diag.get('cache_hits', 0))
            if served_warm else
            'consumer decoded: hits=%s misses=%s'
            % (diag.get('cache_hits'), diag.get('cache_misses')))
        report['ok'] = bool(not torn and done_before >= 1 and resumed_ok
                            and digest_ok and served_warm)
        return report
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=20)
            except Exception:  # noqa: BLE001 — never hang the matrix
                pass
        shm_left = _shm_residue() - shm_before
        tmp_left = _ledger_tmp_residue(ledger_path)
        # Publish residue (a SIGKILL mid-write leaves ``.tmp.<pid>.*``
        # next to the entries) must be swept by the restart, not linger.
        plane_tmp = [n for n in (os.listdir(plane_dir)
                                 if os.path.isdir(plane_dir) else [])
                     if n.startswith('.tmp.')]
        report['checks']['zero_residue'] = (
            'ok' if not shm_left and not tmp_left and not plane_tmp else
            'shm=%s tmp=%s plane_tmp=%s'
            % (sorted(shm_left)[:4], tmp_left[:4], plane_tmp[:4]))
        if report.get('ok'):
            report['ok'] = (not shm_left and not tmp_left
                            and not plane_tmp)


def run_scenario(name, dataset_url, rows, workdir, seed=7, n_workers=2,
                 expected_digest=None, timeout_s=240.0, scenario=None):
    """One scenario end to end; returns a report dict (``ok`` plus the
    per-invariant verdicts and the injection counts).  Raises nothing:
    every failure lands in the report — the matrix must finish.

    ``scenario`` overrides the catalogue lookup with an ad-hoc scenario
    dict (a validated ``--spec-json`` load); ``name`` then only labels
    the report."""
    import threading

    import numpy as np

    from petastorm_tpu.errors import ServiceError  # noqa: F401
    from petastorm_tpu.service import (Dispatcher, ServiceConfig,
                                       ServiceDataLoader)
    from petastorm_tpu.workers_pool import shm_plane

    if scenario is None:
        scenario = SCENARIOS[name]
    if scenario.get('runner') == 'materialize':
        # The materialization drill runs no service fleet: one
        # controller process, killed and restarted, then a direct
        # plane-cached consumer read.
        return _run_materialize_scenario(
            name, dataset_url, rows, workdir, seed=seed,
            expected_digest=expected_digest, timeout_s=timeout_s)
    n_workers = int(scenario.get('n_workers', n_workers))
    spec = {'seed': int(seed), 'faults': scenario.get('faults') or []}
    ledger_path = os.path.join(workdir, 'ledger_%s.json' % name)
    overrides = dict(scenario.get('config') or {})
    reader_kwargs = {'workers_count': 1}
    fs_spec = scenario.get('filesystem')
    if fs_spec is not None:
        reader_kwargs['filesystem'] = _build_fault_fs(fs_spec)
    config_kwargs = dict(
        dataset_url=dataset_url, num_consumers=1, rowgroups_per_split=2,
        lease_ttl_s=2.0, reader_kwargs=reader_kwargs,
        ledger_path=ledger_path, drain_timeout_s=20.0)
    if scenario.get('cache_plane'):
        plane_dir = os.path.join(workdir, 'plane_%s' % name)
        config_kwargs.update(cache_plane=True, cache_plane_dir=plane_dir)
    config_kwargs.update(overrides)
    config = ServiceConfig(**config_kwargs)

    report = {'scenario': name, 'seed': int(seed), 'ok': False,
              'checks': {}, 'injections': {}}
    shm_before = _shm_residue()
    counts_prefix = os.path.join(workdir, 'chaos_counts_%s' % name)
    spec_env = ({CHAOS_ENV: json.dumps(spec),
                 CHAOS_COUNTS_ENV: counts_prefix}
                if spec['faults'] else None)
    state = activate(spec) if spec['faults'] else None

    dispatcher = None
    dispatcher_proc = None
    dispatcher_addr = None
    workers = []
    stats = None
    try:
        use_subproc = bool(scenario.get('dispatcher_subprocess'))
        if use_subproc:
            port = _free_port()
            dispatcher_addr = 'tcp://127.0.0.1:%d' % port
            # reader_kwargs re-set bare: JSON can't carry a filesystem
            # wrapper into the child (none of the subprocess-dispatcher
            # scenarios use one).
            child_spec = dict(config_kwargs,
                              reader_kwargs={'workers_count': 1})
            dispatcher_proc = _spawn(
                _DISPATCHER_CHILD,
                [dispatcher_addr, json.dumps(child_spec), _repo_root()],
                spec_env=spec_env)
        else:
            dispatcher = Dispatcher(config).start()
            dispatcher_addr = dispatcher.addr
        stats = _Stats(dispatcher_addr)
        salt = 1
        for _ in range(n_workers):
            env = dict(spec_env or {})
            env[CHAOS_SALT_ENV] = str(salt)
            salt += 1
            workers.append(_spawn(_WORKER_CHILD,
                                  [dispatcher_addr, _repo_root()],
                                  spec_env=env or None))
        deadline = time.monotonic() + timeout_s
        while not _phase_reached(stats.poll(), 'registered', n_workers):
            if time.monotonic() > deadline:
                report['checks']['fleet_up'] = 'workers never registered'
                return report
            time.sleep(0.1)

        # Co-tenant jobs (ISSUE 16): register every scenario tenant on
        # the SAME dataset over the same fleet before consumption
        # starts, so the whole epoch runs under fair-share scheduling.
        for entry in scenario.get('tenants') or ():
            from petastorm_tpu.service.client import register_tenant_job
            try:
                register_tenant_job(
                    dispatcher_addr, entry['tenant'], dict(
                        dataset_url=dataset_url, num_consumers=1,
                        rowgroups_per_split=2, lease_ttl_s=2.0,
                        reader_kwargs={'workers_count': 1}),
                    weight=entry.get('weight', 1.0))
            except Exception as e:  # noqa: BLE001 — reported, matrix continues
                report['checks']['register_%s' % entry['tenant']] = \
                    'failed: %r' % e
                return report

        # One consuming stream per tenant (the default job first), each
        # with its own digest + id list: the invariants must hold PER
        # TENANT — an aggregate digest could hide one tenant's loss
        # behind another's duplicate.
        streams = [{'tenant': None, 'digest': DeliveryDigest(),
                    'ids': [], 'errors': []}]
        streams += [{'tenant': entry['tenant'], 'digest': DeliveryDigest(),
                     'ids': [], 'errors': []}
                    for entry in scenario.get('tenants') or ()]

        def consume(stream):
            try:
                loader = ServiceDataLoader(
                    dispatcher_addr, batch_size=8, consumer=0,
                    drop_last=False, queue_splits=1, credits=2,
                    tenant=stream['tenant'])
                with loader:
                    for batch in loader.iter_host_batches():
                        chunk = {k: np.asarray(v) for k, v in batch.items()}
                        stream['digest'].update(chunk)
                        stream['ids'].extend(chunk['id'].tolist())
                        # Throttled consumption keeps splits in flight
                        # long enough for phase-targeted kills to land
                        # mid-epoch by construction — sized so the
                        # mid_epoch window survives a loaded host where
                        # each stats poll can take seconds.
                        time.sleep(0.1)
            except Exception as e:  # noqa: BLE001 — reported, matrix continues
                stream['errors'].append(e)

        consumers = [threading.Thread(target=consume, args=(stream,),
                                      daemon=True) for stream in streams]
        for thread in consumers:
            thread.start()

        # -- kill controller (in this thread: phases are ordered) ------------
        for kill in scenario.get('kills') or ():
            while not _phase_reached(stats.poll(), kill['phase'],
                                     n_workers):
                if time.monotonic() > deadline \
                        or not any(t.is_alive() for t in consumers):
                    break
                time.sleep(0.05)
            if not any(t.is_alive() for t in consumers):
                report['checks'].setdefault(
                    'kill_%s' % kill['role'],
                    'epoch finished before phase %r' % kill['phase'])
                continue
            import signal as _signal
            signum = (_signal.SIGKILL if kill['signal'] == 'kill'
                      else _signal.SIGTERM)
            if kill['role'] == 'dispatcher':
                if dispatcher_proc is None:
                    report['checks']['kill_dispatcher'] = \
                        'scenario did not run a dispatcher subprocess'
                    continue
                if scenario.get('check_decision_journal'):
                    # "mid-SCALE-STORM": the kill must land after the
                    # autoscaler actually acted (the record under test)
                    # AND after the serve loop's next ledger tick
                    # persisted it — otherwise the scenario measures a
                    # race, not journal survival.
                    while time.monotonic() < deadline \
                            and any(t.is_alive() for t in consumers):
                        auto = (stats.poll() or {}).get('autoscale') or {}
                        if int(auto.get('scale_outs', 0) or 0) >= 1:
                            break
                        time.sleep(0.1)
                    time.sleep(0.6)  # > one 100 ms serve-loop turn
                dispatcher_proc.send_signal(signum)
                dispatcher_proc.wait(timeout=30)
                report['checks']['kill_dispatcher'] = 'killed'
                # Wall-clock kill stamp: the decision-journal check
                # below separates pre-kill records (must survive the
                # ledger restore) from post-restart ones.
                report['kill_unix'] = time.time()
                if kill.get('restart'):
                    child_spec = dict(config_kwargs,
                                      reader_kwargs={'workers_count': 1})
                    dispatcher_proc = _spawn(
                        _DISPATCHER_CHILD,
                        [dispatcher_addr, json.dumps(child_spec),
                         _repo_root()],
                        spec_env=spec_env)
                    report['checks']['restart_dispatcher'] = 'restarted'
            else:
                victim = workers[0]
                victim.send_signal(signum)
                victim.wait(timeout=30)
                report['checks']['kill_worker'] = (
                    'sig%s pid %d, exit %r'
                    % (kill['signal'], victim.pid, victim.returncode))
                if kill.get('restart'):
                    workers[0] = _spawn(_WORKER_CHILD,
                                        [dispatcher_addr, _repo_root()],
                                        spec_env=spec_env)

        for thread in consumers:
            thread.join(max(1.0, deadline - time.monotonic()))
        if any(t.is_alive() for t in consumers):
            report['checks']['liveness'] = (
                'epoch wedged (> %.0fs); %s rows delivered'
                % (timeout_s, [s['digest'].rows for s in streams]))
            return report
        errors = [e for s in streams for e in s['errors']]
        if errors:
            report['checks']['consumer'] = 'raised: %r' % errors[0]
            return report

        # -- the three invariants, PER TENANT STREAM -------------------------
        want_ids = list(range(rows))
        if expected_digest is None:
            expected_digest = direct_read_digest(dataset_url)
        all_ok = True
        for stream in streams:
            suffix = '' if stream['tenant'] is None \
                else '_%s' % stream['tenant']
            ids = stream['ids']
            exactly_once = sorted(ids) == want_ids
            report['checks']['exactly_once%s' % suffix] = (
                'ok' if exactly_once else
                'lost=%s dup=%s' % (
                    sorted(set(want_ids) - set(ids))[:8],
                    sorted(i for i in set(ids) if ids.count(i) > 1)[:8]))
            digest_ok = stream['digest'].hexdigest() == expected_digest
            report['checks']['digest%s' % suffix] = (
                'ok' if digest_ok else '%s != expected %s'
                % (stream['digest'].hexdigest(), expected_digest))
            all_ok = all_ok and exactly_once and digest_ok
        report['digest'] = streams[0]['digest'].hexdigest()

        # -- autoscaler damping bound (ISSUE 16) -----------------------------
        bound = scenario.get('max_autoscale_actions')
        if bound is not None:
            final = stats.poll() or {}
            auto = final.get('autoscale') or {}
            actions = int(auto.get('actions', 0) or 0)
            damped = actions <= int(bound)
            report['checks']['autoscale_damped'] = (
                'ok (%d action(s): outs %d ins %d, suppressed %d)'
                % (actions, int(auto.get('scale_outs', 0) or 0),
                   int(auto.get('scale_ins', 0) or 0),
                   int(auto.get('suppressed', 0) or 0)) if damped
                else 'flapping: %d action(s) > damping bound %d'
                % (actions, int(bound)))
            all_ok = all_ok and damped

        # -- decision-journal survival (ISSUE 20) ----------------------------
        # The restarted dispatcher must still explain the PRE-kill
        # scale-out from its ledger-restored journal: restores lineage,
        # a pre-kill scale_out record, and the determinism cross-check
        # clean over it (the replayed control law agrees with what the
        # dead process recorded).
        if scenario.get('check_decision_journal'):
            from petastorm_tpu.telemetry import decisions as _decisions
            from petastorm_tpu.telemetry import why as _why
            reply = stats.call({'op': 'decisions'}, timeout_s=10.0)
            try:
                records, meta = _why.load_decisions(reply or {})
            except ValueError as e:
                report['checks']['decision_journal'] = 'no journal: %s' % e
                records, meta = [], {}
                all_ok = False
            if records:
                kill_unix = report.get('kill_unix')
                pre_kill = [
                    r for r in _why.filter_records(records,
                                                   actor='autoscaler')
                    if kill_unix is None
                    or r.get('unix_time', 0.0) < kill_unix]
                spawns = [r for r in pre_kill
                          if r.get('action') == 'scale_out'
                          and not r.get('suppressed')]
                verdicts = [_decisions.replay_decision(r)['verdict']
                            for r in spawns]
                survived = int(meta.get('restores', 0) or 0) >= 1
                journal_ok = (survived and bool(spawns)
                              and 'divergent' not in verdicts)
                report['checks']['decision_journal'] = (
                    'ok (restores %d, %d pre-kill spawn record(s), '
                    'replay %s)'
                    % (meta.get('restores', 0), len(spawns), verdicts)
                    if journal_ok else
                    'restores=%s pre_kill_autoscaler=%d spawns=%d '
                    'replay=%s'
                    % (meta.get('restores', 0), len(pre_kill),
                       len(spawns), verdicts))
                all_ok = all_ok and journal_ok

        # Autoscaled workers spawned by a KILLED dispatcher are orphans
        # (their parent died without launcher close()): drain every
        # registered worker through the control plane so they exit
        # before teardown — leaked decode processes would outlive the
        # matrix.
        if overrides.get('autoscale') and use_subproc:
            final = stats.poll() or {}
            for wid in sorted(final.get('workers') or {}):
                stats.call({'op': 'drain', 'worker_id': wid},
                           timeout_s=5.0)
            drain_deadline = time.monotonic() + 25.0
            while time.monotonic() < drain_deadline:
                remaining = (stats.poll() or {}).get('workers') or {}
                if not remaining:
                    break
                time.sleep(0.25)
        report['ok'] = bool(all_ok)
        return report
    finally:
        deactivate()
        if state is not None:
            report['injections'] = {('%s/%s' % key): n
                                    for key, n in state.counts.items()}
        for proc in workers + ([dispatcher_proc] if dispatcher_proc
                               else []):
            if proc.poll() is None:
                proc.send_signal(15)
        for proc in workers + ([dispatcher_proc] if dispatcher_proc
                               else []):
            try:
                proc.wait(timeout=20)
            except Exception:  # noqa: BLE001 — escalate, never hang the matrix
                proc.kill()
                proc.wait(timeout=20)
        if dispatcher is not None:
            dispatcher.stop()
            dispatcher.join()
        if stats is not None:
            stats.close()
        # Aggregate the subprocess workers' injection counts (dumped at
        # their clean exit; a SIGKILLed victim's die with it).
        for path in _ledger_tmp_siblings(counts_prefix):
            try:
                with open(path) as f:
                    for key, n in (json.load(f) or {}).items():
                        report['injections'][key] = \
                            report['injections'].get(key, 0) + int(n)
            except (OSError, ValueError):
                pass
        # -- zero-residue sweep (part of the report, not an exception) -------
        shm_plane.sweep_orphans()
        shm_left = _shm_residue() - shm_before
        tmp_left = [p for p in _ledger_tmp_residue(ledger_path)]
        report['checks']['zero_residue'] = (
            'ok' if not shm_left and not tmp_left else
            'shm=%s tmp=%s' % (sorted(shm_left)[:4], tmp_left[:4]))
        if report.get('ok'):
            report['ok'] = not shm_left and not tmp_left


def _ledger_tmp_siblings(prefix):
    """Files named ``<prefix>.<pid>.json`` (the per-process injection
    count dumps)."""
    directory = os.path.dirname(os.path.abspath(prefix))
    base = os.path.basename(prefix)
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names
            if n.startswith(base + '.') and n.endswith('.json')]


def _ledger_tmp_residue(ledger_path):
    directory = os.path.dirname(os.path.abspath(ledger_path))
    base = os.path.basename(ledger_path)
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return [n for n in names if n.startswith(base + '.')
            and n.endswith('.tmp')]


def _build_fault_fs(fs_spec):
    kind = fs_spec.get('kind')
    factory = FILESYSTEM_FAULTS[kind]
    kwargs = {k: v for k, v in fs_spec.items() if k != 'kind'}
    from fsspec.implementations.local import LocalFileSystem
    return factory(LocalFileSystem(), **kwargs)


def run_matrix(names, dataset_url=None, rows=None, workdir=None, seed=7,
               scenario_overrides=None):
    """Run each named scenario against one dataset + one ground-truth
    digest; returns ``(reports, all_ok)``.  ``scenario_overrides`` maps
    a name to an ad-hoc scenario dict (the ``--spec-json`` path) used
    instead of the catalogue entry."""
    import shutil
    import tempfile
    owned = workdir is None
    ok = False
    if owned:
        workdir = tempfile.mkdtemp(prefix='petastorm-tpu-chaos-')
    try:
        if dataset_url is None:
            dataset_url, rows = make_chaos_dataset(
                os.path.join(workdir, 'dataset'), seed=seed)
        expected = direct_read_digest(dataset_url)
        reports = []
        for name in names:
            t0 = time.monotonic()
            report = run_scenario(name, dataset_url, rows, workdir,
                                  seed=seed, expected_digest=expected,
                                  scenario=(scenario_overrides or
                                            {}).get(name))
            report['elapsed_s'] = round(time.monotonic() - t0, 1)
            reports.append(report)
            logger.info('scenario %-20s %s (%.1fs)', name,
                        'PASS' if report['ok'] else 'FAIL',
                        report['elapsed_s'])
        ok = all(r['ok'] for r in reports)
        return reports, ok
    finally:
        if owned:
            if ok:
                shutil.rmtree(workdir, ignore_errors=True)
            else:
                # Keep the workdir of a failed matrix: the ledgers and
                # dataset ARE the repro artifacts.
                logger.info('matrix artifacts kept at %s', workdir)


def render_report(reports):
    lines = ['petastorm-tpu-chaos — %d scenario(s)' % len(reports)]
    for report in reports:
        lines.append('%-20s %s  (%.1fs)  digest=%s'
                     % (report['scenario'],
                        'PASS' if report['ok'] else 'FAIL',
                        report.get('elapsed_s', 0.0),
                        report.get('digest', '-')))
        for check, verdict in sorted(report['checks'].items()):
            lines.append('    %-14s %s' % (check, verdict))
        if report.get('injections'):
            lines.append('    injections     %s' % ', '.join(
                '%s=%d' % kv for kv in sorted(
                    report['injections'].items())))
    return '\n'.join(lines)


def main(argv=None):
    """``petastorm-tpu-chaos`` — list scenarios / run one / run the
    matrix.  Exit 0 = every executed scenario preserved its invariants,
    1 = at least one failed, 2 = usage error."""
    import argparse
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    logging.basicConfig(level=logging.INFO,
                        format='%(asctime)s %(name)s %(levelname)s '
                               '%(message)s')
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-chaos',
        description='Fleet chaos harness: run the data service under '
                    'seeded fault scenarios and assert delivery digest, '
                    'exactly-once, and zero residue.')
    sub = parser.add_subparsers(dest='command', required=True)
    sub.add_parser('list', help='print the scenario catalogue')
    for cmd in ('run', 'matrix'):
        p = sub.add_parser(
            cmd, help=('run one scenario' if cmd == 'run'
                       else 'run a scenario set'))
        if cmd == 'run':
            p.add_argument('scenario', nargs='?', default=None,
                           choices=sorted(SCENARIOS))
            p.add_argument('--spec-json', default=None, metavar='PATH',
                           help='run an ad-hoc scenario from a JSON spec '
                                'file instead of the catalogue (the '
                                'petastorm-tpu-model --chaos-spec '
                                'counterexample bridge emits these)')
        else:
            p.add_argument('--scenarios', default=None,
                           help='comma-separated names (default: all)')
            p.add_argument('--smoke', action='store_true',
                           help='the fast CI set: %s'
                                % ', '.join(SMOKE_SCENARIOS))
        p.add_argument('--dataset-url', default=None,
                       help='existing dataset (default: generate a tiny '
                            'one in a temp dir)')
        p.add_argument('--rows', type=int, default=None,
                       help='row count of --dataset-url (required with '
                            'it; the exactly-once assert needs ids '
                            '0..rows-1)')
        p.add_argument('--seed', type=int, default=7)
        p.add_argument('--json', action='store_true')
    args = parser.parse_args(argv)

    if args.command == 'list':
        for name, scenario in SCENARIOS.items():
            print('%-20s %s' % (name, scenario['summary']))
        return 0
    if args.dataset_url is not None and args.rows is None:
        parser.error('--dataset-url requires --rows')
    scenario_overrides = None
    if args.command == 'run':
        if (args.scenario is None) == (args.spec_json is None):
            parser.error('run takes a scenario name or --spec-json '
                         '(exactly one)')
        if args.spec_json is not None:
            try:
                name, scenario = load_spec_json(args.spec_json)
            except (OSError, ValueError) as e:
                parser.error('bad --spec-json %s: %s' % (args.spec_json, e))
            names = [name]
            scenario_overrides = {name: scenario}
        else:
            names = [args.scenario]
    elif args.smoke:
        names = list(SMOKE_SCENARIOS)
    elif args.scenarios:
        names = [n.strip() for n in args.scenarios.split(',') if n.strip()]
        unknown = sorted(set(names) - set(SCENARIOS))
        if unknown:
            parser.error('unknown scenario(s): %s' % ', '.join(unknown))
    else:
        names = list(SCENARIOS)
    reports, ok = run_matrix(names, dataset_url=args.dataset_url,
                             rows=args.rows, seed=args.seed,
                             scenario_overrides=scenario_overrides)
    if args.json:
        print(json.dumps(reports, sort_keys=True, default=str))
    else:
        print(render_report(reports))
    return 0 if ok else 1


if __name__ == '__main__':
    import sys
    sys.exit(main())
