"""Public test utilities for downstream users of the framework.

Parity: reference ``petastorm/test_util/reader_mock.py :: ReaderMock`` —
a synthetic in-memory reader so adapter/integration tests don't need a
materialized Parquet dataset.
"""

from petastorm_tpu.test_util.emulation import BandwidthLimitedFilesystem  # noqa: F401
from petastorm_tpu.test_util.fault_injection import (  # noqa: F401
    FlakyOpenFilesystem, FlakyReadFilesystem, is_data_file,
)
from petastorm_tpu.test_util.reader_mock import ReaderMock, schema_data_generator  # noqa: F401
