"""Fault-injection filesystems for exercising the retry/poisoning plane.

No reference equivalent (SURVEY.md §5.3: the reference has no fault
injection hooks); these are the public counterpart to the framework's
transient-retry + ``PoisonedRowGroupError`` machinery — wrap any fsspec
filesystem and pass it as ``make_reader(..., filesystem=...)`` to simulate
GCS flakes deterministically.

Only *data* files (``*.parquet`` not starting with ``_``) are failed:
footer/metadata reads happen at reader construction, which deliberately has
no retry layer.
"""

from petastorm_tpu.utils.locks import make_lock


def is_data_file(path):
    """True for row-group data files (``*.parquet`` not ``_``-prefixed)."""
    name = path.rsplit('/', 1)[-1]
    return name.endswith('.parquet') and not name.startswith('_')


_is_data_file = is_data_file  # module-internal alias


class FlakyOpenFilesystem(object):
    """Delegating fs whose first ``fail_times`` opens of each data file raise
    OSError."""

    def __init__(self, real_fs, fail_times):
        self._real = real_fs
        self._fail_times = fail_times
        self._counts = {}
        self._lock = make_lock('test_util.fault_injection.FlakyOpenFilesystem._lock')

    # Documented to ride ``make_reader(..., filesystem=...)``, which the
    # ProcessPool pickles into worker args — the lock (and the injection
    # counts, which are per-process bookkeeping) must stay behind.
    def __getstate__(self):
        state = self.__dict__.copy()
        del state['_lock']
        # Counts consumed in the parent (e.g. the construction-time footer
        # read) must not eat a worker's injection budget.
        del state['_counts']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._counts = {}
        self._lock = make_lock('test_util.fault_injection.FlakyOpenFilesystem._lock')

    def open(self, path, *args, **kwargs):
        if _is_data_file(path):
            with self._lock:
                n = self._counts.get(path, 0)
                self._counts[path] = n + 1
            if n < self._fail_times:
                raise OSError('injected transient open failure #%d on %s' % (n, path))
        return self._real.open(path, *args, **kwargs)

    def __getattr__(self, name):
        if name == '_real':  # mid-unpickle: not yet restored
            raise AttributeError(name)
        return getattr(self._real, name)


class FlakyReadFilesystem(FlakyOpenFilesystem):
    """First open of each data file succeeds but the handle dies on first
    read — exercises eviction of a wedged cached handle."""

    def open(self, path, *args, **kwargs):
        handle = self._real.open(path, *args, **kwargs)
        if _is_data_file(path):
            with self._lock:
                n = self._counts.get(path, 0)
                self._counts[path] = n + 1
            if n < self._fail_times:
                return _DyingFile(handle)
        return handle


class _DyingFile(object):
    def __init__(self, inner):
        self._inner = inner

    def read(self, *args, **kwargs):
        raise OSError('injected read failure')

    def __getattr__(self, name):
        return getattr(self._inner, name)
