"""Back-compat re-exports of the fault-injection filesystems.

The flaky filesystems were promoted into the chaos plane's seam
registry (``petastorm_tpu/test_util/chaos.py``, ISSUE 15 — the PR 14
``BandwidthLimitedFilesystem`` promotion precedent): they are the
public counterpart to the framework's transient-retry +
``PoisonedRowGroupError`` machinery and now live next to the rest of
the deterministic fault inventory, with direct unit tests
(``tests/test_chaos.py``).  This module keeps the historical import
path working; new code should import from ``test_util.chaos``.
"""

from petastorm_tpu.test_util.chaos import (FlakyOpenFilesystem,  # noqa: F401
                                           FlakyReadFilesystem,
                                           _DyingFile, is_data_file)

__all__ = ['FlakyOpenFilesystem', 'FlakyReadFilesystem', 'is_data_file']

_is_data_file = is_data_file  # historical module-internal alias
