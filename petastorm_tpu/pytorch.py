"""PyTorch adapters: row DataLoader, columnar BatchedDataLoader, in-memory loader.

Parity: reference ``petastorm/pytorch.py :: decimal_friendly_collate,
DataLoader, BatchedDataLoader, InMemBatchedDataLoader``.  Torch here is CPU
only (the TPU path is ``petastorm_tpu.jax``); these adapters exist so
reference users can migrate incrementally.
"""

import decimal
from collections.abc import Mapping, Sequence

import numpy as np

from petastorm_tpu.reader_impl.shuffling_buffer import (NoopShufflingBuffer,
                                                        RandomShufflingBuffer)

_TORCH_STRING_ERROR = (
    'Cannot convert a string field to a torch tensor; project it away with '
    "schema_fields or transform it (reference behavior is the same TypeError)")


def decimal_friendly_collate(batch):
    """Collate that converts ``decimal.Decimal`` cells to floats first.

    Parity: ``petastorm/pytorch.py :: decimal_friendly_collate``.
    """
    import torch
    first = batch[0]
    if isinstance(first, decimal.Decimal):
        return torch.as_tensor([float(x) for x in batch])
    if isinstance(first, np.ndarray):
        return torch.as_tensor(np.stack(batch))
    if isinstance(first, (str, bytes)):
        return list(batch)
    if isinstance(first, Mapping):
        return {key: decimal_friendly_collate([d[key] for d in batch]) for key in first}
    if hasattr(first, '_fields'):  # namedtuple
        return type(first)(*(decimal_friendly_collate([getattr(d, f) for d in batch])
                             for f in first._fields))
    if isinstance(first, Sequence) and not isinstance(first, (str, bytes)):
        transposed = zip(*batch)
        return [decimal_friendly_collate(samples) for samples in transposed]
    if first is None:
        return list(batch)
    return torch.as_tensor(np.asarray(batch))


class _LoaderBase(object):
    def __init__(self, reader):
        self.reader = reader
        self._in_iter = False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.reader.stop()
        self.reader.join()

    def stop(self):
        self.reader.stop()

    def join(self):
        self.reader.join()


class DataLoader(_LoaderBase):
    """Row-path loader: iterate rows, optional shuffling reservoir, collate.

    Parity: ``petastorm/pytorch.py :: DataLoader`` (same constructor args).
    """

    def __init__(self, reader, batch_size=1, collate_fn=decimal_friendly_collate,
                 shuffling_queue_capacity=0, min_after_retrieve=None, seed=None):
        super(DataLoader, self).__init__(reader)
        if getattr(reader, 'batched_output', False):
            raise ValueError('DataLoader requires a row reader (make_reader); '
                             'use BatchedDataLoader for batch readers')
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self._shuffle_capacity = shuffling_queue_capacity
        self._min_after_retrieve = (min_after_retrieve if min_after_retrieve is not None
                                    else shuffling_queue_capacity // 2)
        self._seed = seed

    def __iter__(self):
        if self._shuffle_capacity > 0:
            buffer = RandomShufflingBuffer(self._shuffle_capacity,
                                           self._min_after_retrieve, seed=self._seed)
        else:
            buffer = NoopShufflingBuffer()
        batch = []
        for row in self.reader:
            buffer.add_many([row])
            while buffer.can_retrieve():
                batch.append(buffer.retrieve())
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
        buffer.finish()
        while not buffer.finished:
            batch.append(buffer.retrieve())
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch:
            yield self.collate_fn(batch)


class BatchedDataLoader(_LoaderBase):
    """Columnar loader over batch readers: no per-row python loop.

    Parity: ``petastorm/pytorch.py :: BatchedDataLoader`` — rebatching via
    numpy slicing of column chunks, torch tensors per column.
    ``transform_fn`` maps the dict of column tensors (e.g. to device).
    """

    def __init__(self, reader, batch_size=1, transform_fn=None,
                 shuffling_queue_capacity=0, seed=None):
        super(BatchedDataLoader, self).__init__(reader)
        if not getattr(reader, 'batched_output', False):
            raise ValueError('BatchedDataLoader requires a batch/columnar reader '
                             '(make_batch_reader or make_reader(columnar_decode=True))')
        self.batch_size = batch_size
        self._transform_fn = transform_fn
        self._shuffle_capacity = shuffling_queue_capacity
        self._seed = seed

    def __iter__(self):
        import torch
        rng = np.random.default_rng(self._seed)
        shuffle = self._shuffle_capacity > 0
        columns = None
        count = 0

        def emit(take):
            nonlocal columns, count
            batch = {}
            for k, chunks in columns.items():
                merged = np.concatenate(chunks) if len(chunks) > 1 else chunks[0]
                picked = merged[take]
                keep = np.ones(len(merged), dtype=bool)
                keep[take] = False
                columns[k] = [merged[keep]]
                batch[k] = (torch.as_tensor(picked) if picked.dtype != object
                            else picked.tolist())
            count -= len(take)
            if self._transform_fn is not None:
                batch = self._transform_fn(batch)
            return batch

        for chunk in self.reader:
            chunk_dict = chunk._asdict() if hasattr(chunk, '_asdict') else dict(chunk)
            n = len(next(iter(chunk_dict.values())))
            if columns is None:
                columns = {k: [np.asarray(v)] for k, v in chunk_dict.items()}
            else:
                for k, v in chunk_dict.items():
                    columns[k].append(np.asarray(v))
            count += n
            threshold = max(self.batch_size, self._shuffle_capacity if shuffle else 0)
            while count >= threshold and count >= self.batch_size:
                take = (rng.permutation(count)[:self.batch_size] if shuffle
                        else np.arange(self.batch_size))
                yield emit(take)
        while count >= self.batch_size:
            take = (rng.permutation(count)[:self.batch_size] if shuffle
                    else np.arange(self.batch_size))
            yield emit(take)
        if count:
            yield emit(np.arange(count) if not shuffle else rng.permutation(count))


class InMemBatchedDataLoader(_LoaderBase):
    """Caches the full epoch in RAM once, then serves ``num_epochs`` shuffled
    passes without re-reading Parquet.

    Parity: ``petastorm/pytorch.py :: InMemBatchedDataLoader``.
    """

    def __init__(self, reader, batch_size=1, num_epochs=1, rows_capacity=None,
                 shuffle=True, transform_fn=None, seed=None):
        super(InMemBatchedDataLoader, self).__init__(reader)
        if not getattr(reader, 'batched_output', False):
            raise ValueError('InMemBatchedDataLoader requires a batch/columnar reader')
        self.batch_size = batch_size
        self._num_epochs = num_epochs
        self._rows_capacity = rows_capacity
        self._shuffle = shuffle
        self._transform_fn = transform_fn
        self._seed = seed
        self._columns = None

    def _materialize(self):
        chunks = {}
        total = 0
        for chunk in self.reader:
            chunk_dict = chunk._asdict() if hasattr(chunk, '_asdict') else dict(chunk)
            n = len(next(iter(chunk_dict.values())))
            if self._rows_capacity is not None and total + n > self._rows_capacity:
                n = self._rows_capacity - total
                chunk_dict = {k: v[:n] for k, v in chunk_dict.items()}
            for k, v in chunk_dict.items():
                chunks.setdefault(k, []).append(np.asarray(v))
            total += n
            if self._rows_capacity is not None and total >= self._rows_capacity:
                break
        self._columns = {k: (np.concatenate(v) if len(v) > 1 else v[0])
                         for k, v in chunks.items()}

    def __iter__(self):
        import torch
        if self._columns is None:
            self._materialize()
        total = len(next(iter(self._columns.values()))) if self._columns else 0
        rng = np.random.default_rng(self._seed)
        for _epoch in range(self._num_epochs):
            order = rng.permutation(total) if self._shuffle else np.arange(total)
            for start in range(0, total - self.batch_size + 1, self.batch_size):
                take = order[start:start + self.batch_size]
                batch = {k: (torch.as_tensor(v[take]) if v.dtype != object
                             else v[take].tolist())
                         for k, v in self._columns.items()}
                if self._transform_fn is not None:
                    batch = self._transform_fn(batch)
                yield batch
