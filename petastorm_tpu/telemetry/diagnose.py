"""``petastorm-tpu-diagnose`` — ranked, actionable verdicts for a fleet.

``top`` shows the numbers; this tool reads them.  It ingests any of the
three observability artifacts the plane produces —

* a **live fleet** (``--dispatcher tcp://host:port``): one ``stats``
  RPC, whose reply now carries the dispatcher's fleet health report;
* a **flight-recorder dump** (``--flight path.json``): the bounded ring
  a process persisted before dying (``telemetry/flight.py``);
* a **test-suite watchdog artifact** (``--artifact path.json``): the
  ``telemetry.dump_state()`` file ``tests/conftest.py`` writes on hang
  or failure (registries + trace timelines + flight frames);

— normalizes them into one evidence dict, runs the verdict rules, and
prints a ranked report: *what is wrong, how bad, which knob to turn*::

    $ petastorm-tpu-diagnose --dispatcher tcp://dispatch:7777
    petastorm-tpu-diagnose — live fleet tcp://dispatch:7777
     1. [crit] decode-bound — decode active for 94% of the stalled time;
        fleet decode_split p99 41.0 ms vs delivery p99 2.0 ms
        -> raise workers_count / add service decode workers; enable the
           epoch-cache plane (cache_plane=True) ...

Each rule is unit-tested against synthetic regime fixtures
(``tests/test_health_diagnose.py``) — the verdict catalogue with the
counters/thresholds each rule reads lives in ``docs/observability.md``.
Exit codes: 0 verdicts produced (including a clean bill of health),
1 input unreachable/unparseable, 2 usage error.
"""

import argparse
import json
import sys
import time

from petastorm_tpu.telemetry import health as _health
from petastorm_tpu.telemetry.registry import (merge_snapshots,
                                              snapshot_delta)
from petastorm_tpu.telemetry.spans import attribute_stalls

__all__ = ['diagnose', 'run_rules', 'evidence_from_stats',
           'evidence_from_flight', 'evidence_from_artifact',
           'render_report', 'main']

_SEVERITY_RANK = {'crit': 3, 'warn': 2, 'info': 1, 'ok': 0}

#: The knob each regime verdict recommends (docs/observability.md keeps
#: the same catalogue prose-side).
_REGIME_ACTIONS = {
    'decode-bound': (
        'raise workers_count / add service decode workers; enable the '
        'epoch-cache plane (cache_plane=True) so repeat epochs serve '
        'warm instead of re-decoding'),
    'link-bound': (
        "enable the transfer plane (transfer='auto' off-CPU), narrow "
        "wire dtypes (wire_dtypes='auto'), deepen ring_slots/prefetch "
        'so transfer overlaps the step'),
    'lease-starved': (
        'add decode workers and verify they register + heartbeat '
        '(petastorm-tpu-top worker rows); check dispatcher logs for '
        'lease churn; smaller rowgroups_per_split shortens fill time'),
    'cache-degraded': (
        'check cache_plane_dir writability, tier caps '
        '(cache_plane_ram_bytes / cache_plane_disk_bytes) and /dev/shm '
        'headroom — the plane is refusing work, every refused piece '
        're-decodes at full cost'),
    'cluster-cache-degraded': (
        'the fleet is re-decoding a dataset a peer already holds '
        'decoded: peer fetches are failing back to direct decode — '
        'check worker data-endpoint reachability between hosts '
        '(advertise_host / firewalls), that '
        'PETASTORM_TPU_NO_CLUSTER_CACHE is not set on part of the '
        'fleet, and plane tier caps (a full plane cannot accept '
        'peer-filled entries)'),
    'shm-degraded': (
        'raise the shm arena capacity or /dev/shm size; a slow consumer '
        'pinning slabs also fills the arena — check client drain rate'),
    'skew-bound': (
        "enable the adaptive out-of-order scheduler (scheduling="
        "'adaptive' on make_reader / ServiceConfig): slow pieces launch "
        'early and fast pieces backfill the stall window — adding '
        'workers would idle just the same; '
        'PETASTORM_TPU_NO_ADAPTIVE_SCHED=1 is the kill switch'),
    'tenant-starved': (
        'the shared fleet is granting leases to other tenants while '
        'this one starves (ISSUE 16): raise the starved tenant\'s '
        'weight (register_tenant_job(weight=)), check whether its '
        'splits are being affinity-deferred onto one saturated worker, '
        'and whether a per-tenant quota is degrading its every chunk; '
        'if the whole fleet is saturated, add workers (or enable the '
        'autoscaler) instead of re-dividing them'),
    'control-plane-degraded': (
        'the control plane itself is the fault domain: if the '
        'dispatcher is restarting, read its logs/ledger lineage for the '
        'crash cause (the ledger keeps delivery exactly-once through '
        'restarts, but every restart pauses lease traffic); if drains '
        'are timing out, raise drain_timeout_s past the real in-flight '
        'split time or shrink rowgroups_per_split; if retry_giveups is '
        'climbing fleet-wide, workers are exhausting retry budgets '
        'against the dispatcher (heartbeat backoff episodes) or whole '
        'holder lists are failing peer fetches — check the dispatcher '
        'endpoint and peer data-plane reachability before adding '
        'capacity'),
    'control-flapping': (
        'an autonomous controller is oscillating — opposing actions '
        '(scale_out/scale_in, admit/evict) inside one window pay both '
        'transition costs and deliver neither steady state: widen the '
        "actor's hysteresis (autoscale_cooldown_s, the autoscale_idle_s "
        'vs autoscale_starve_s gap, hbm_budget_bytes vs working set); '
        'petastorm-tpu-why --actor <actor> names each rule that fired '
        'and the inputs it read'),
    'fetch-bound': (
        'cold-read I/O is on the critical path: deepen the ingest '
        "readahead (ingest_window on make_reader, or let the DataLoader "
        'autotuner move it), check that the ingest plane is actually on '
        "(ingest='auto' stays off on local filesystems; "
        'PETASTORM_TPU_NO_INGEST_PLANE=1 kills it), and if '
        'ingest_degraded is climbing, root-cause the fetch failures — '
        'every degraded piece pays object-store first-byte latency on a '
        'decode worker'),
}

#: |clock_drift_ms| above this breaks cross-process span ordering at
#: log2-bucket resolution.
CLOCK_DRIFT_WARN_MS = 50.0


# -- evidence extraction ------------------------------------------------------

def evidence_from_stats(stats, source='live fleet'):
    """Normalize a dispatcher ``stats`` reply (the live-fleet input)."""
    workers = stats.get('workers') or {}
    meta = {key: stats.get(key, 0) for key in
            ('pending', 'leased', 'done', 'failed', 'lease_churn')}
    # Registered is not alive: the dispatcher never forgets a worker, so
    # count rows whose heartbeat is recent (the reply's `age_s`) — a
    # fully-crashed fleet must read as 0 here or lease starvation is
    # unreachable.  Only the health FALLBACK below reads this; a modern
    # reply ships the dispatcher's own (lease-ttl-aware) health report.
    meta['workers_alive'] = sum(
        1 for row in workers.values()
        if isinstance(row.get('age_s'), (int, float))
        and row['age_s'] < 60.0)
    # Fair-share evidence (ISSUE 16): re-derive the dispatcher's
    # starved-tenant signal from its per-tenant rollup (pending work +
    # zero grants while the rest of the fleet was granted) so the
    # health FALLBACK below can classify tenant-starved too.
    tenants = stats.get('tenants') or {}
    fleet_moving = any(int(row.get('grants_delta', 0) or 0) > 0
                       for row in tenants.values())
    meta['starved_tenants'] = sorted(
        tid for tid, row in tenants.items()
        if int(row.get('pending', 0) or 0) > 0
        and int(row.get('grants_delta', 0) or 0) == 0 and fleet_moving)
    meta['tenant_count'] = len(tenants)
    counters = {}
    counters.update(stats.get('cache') or {})
    counters.update(stats.get('shm') or {})
    # Cluster tier rollup: only the COUNTER fields (the rollup also
    # carries directory metadata booleans no health rule reads).
    counters.update({k: v for k, v in
                     (stats.get('cluster_cache') or {}).items()
                     if isinstance(v, int)})
    report = stats.get('health')
    if report is None:
        report = _health.health_report(
            {'counters': counters, 'histograms': {}}, meta=meta)
    return {
        'source': source,
        'stages': stats.get('stages') or {},
        'counters': counters,
        'stall_pct': None,
        'meta': meta,
        'workers': workers,
        'health': report,
        'span_residue': None,
        'reason': None,
        # Crash-survivable control plane rollup (ISSUE 15): ledger
        # lineage, drain traffic, fleet retry counters — the restart /
        # drain-timeout rules read it.
        'control_plane': stats.get('control_plane') or {},
        # Multi-tenant serving tier (ISSUE 16): per-tenant grant/queue
        # rollup + the autoscaler's action counters.
        'tenants': tenants,
        'autoscale': stats.get('autoscale') or {},
        # Decision-journal rollup (ISSUE 20): per-actor action /
        # suppression counts + last real action — the control-flapping
        # verdict cites the actual journaled decision from it.
        'decisions': stats.get('decisions') or {},
    }


def evidence_from_flight(dump, window_s=None, stall_pct=None):
    """Normalize a flight-recorder dump (one process's bounded ring).
    One windowing pass (``flight.window_frames``) feeds BOTH the
    stage/counter evidence and the health report, so they can never
    describe different windows."""
    from petastorm_tpu.telemetry.flight import window_frames
    frames = dump.get('frames') or []
    if not frames:
        raise ValueError('flight dump has no frames')
    old, newest = window_frames(frames, window_s)
    delta = snapshot_delta(newest.get('snapshot'),
                           old.get('snapshot') if old else None)
    measured = (newest['t_mono'] - old['t_mono']) if old else None
    label = dump.get('label') or 'pid %s' % dump.get('pid')
    return {
        'source': 'flight recorder (%s, %d frames)' % (label, len(frames)),
        'stages': _health.summarize_stages(delta.get('histograms')),
        'counters': dict(delta.get('counters') or {}),
        'stall_pct': stall_pct,
        'meta': {},
        'workers': {},
        'health': _health.health_report(delta, stall_pct=stall_pct,
                                        window_s=measured),
        'span_residue': newest.get('span_residue'),
        'reason': dump.get('reason'),
        # Per-batch provenance (ISSUE 13): the newest frame's rolling
        # worst-K summaries — the refs the slow-batch rule cites.
        'provenance_worst': newest.get('provenance_worst'),
    }


def evidence_from_artifact(artifact, window_s=None):
    """Normalize a conftest watchdog artifact (``telemetry.dump_state``
    shape: ``registries`` + ``trace_events`` + ``span_residue`` +
    ``flight``), the postmortem input.  Flight frames (when the dumping
    process had the recorder on) give windowed deltas; the trace
    timelines give span-level stall attribution — joined, they are the
    strongest evidence this tool sees."""
    stall = _best_stall_breakdown(artifact.get('trace_events') or [])
    flight = artifact.get('flight')
    if flight and flight.get('frames'):
        evidence = evidence_from_flight(flight, window_s=window_s,
                                        stall_pct=stall)
    else:
        merged = merge_snapshots(artifact.get('registries') or [])
        evidence = {
            'stages': _health.summarize_stages(merged.get('histograms')),
            'counters': dict(merged.get('counters') or {}),
            'stall_pct': stall, 'meta': {}, 'workers': {},
            'health': _health.health_report(merged, stall_pct=stall),
            'span_residue': None,
        }
    evidence['source'] = 'watchdog artifact (reason: %s)' % (
        artifact.get('reason'),)
    evidence['reason'] = artifact.get('reason')
    if evidence.get('span_residue') is None:
        evidence['span_residue'] = len(artifact.get('span_residue') or ())
    if not evidence.get('provenance_worst'):
        # Artifact-level journals (telemetry.dump_state ships them in
        # full): summarize their worst records with the SAME canonical
        # shape flight frames carry (provenance.summarize_record), so
        # both ingestion paths cite a slow batch identically.
        from petastorm_tpu.telemetry.provenance import summarize_record
        worst = [summarize_record(record)
                 for journal in artifact.get('provenance') or ()
                 for record in (journal.get('worst') or ())[:3]]
        worst.sort(key=lambda row: -(row.get('latency_ms') or 0.0))
        evidence['provenance_worst'] = worst[:4] or None
    return evidence


def _best_stall_breakdown(trace_batches):
    """attribute_stalls per recorder batch (mixing batches would mix
    monotonic origins); keep the breakdown covering the most wait."""
    best, best_wait = None, 0.0
    for batch in trace_batches:
        if isinstance(batch, dict):
            events = batch.get('events') or []
        else:
            events = batch
        breakdown = attribute_stalls(events)
        if breakdown and breakdown['total_wait_s'] > best_wait:
            best, best_wait = breakdown['pct'], breakdown['total_wait_s']
    return best


# -- verdict rules ------------------------------------------------------------

def _stage_p99(stages, names):
    vals = [stages[n].get('p99_ms') for n in names
            if n in stages and stages[n].get('p99_ms') is not None]
    return max(vals) if vals else None


def _regime_verdicts(evidence):
    """One verdict per health candidate, enriched with the canonical
    stage numbers so the report reads like the example verdicts the
    rules were specified against."""
    report = evidence.get('health') or {}
    stages = evidence.get('stages') or {}
    verdicts = []
    for candidate in report.get('candidates', ()):
        regime = candidate['regime']
        action = _REGIME_ACTIONS.get(regime)
        if action is None:
            continue
        evidence_bits = [candidate['evidence']]
        if regime == 'decode-bound':
            decode = _stage_p99(stages, ('decode_split', 'decode',
                                         'cache_fill', 'host_batch'))
            delivery = _stage_p99(stages, ('serialize', 'shm_publish'))
            if decode is not None:
                evidence_bits.append(
                    'fleet decode p99 %s ms vs delivery p99 %s ms'
                    % (decode, delivery if delivery is not None else '-'))
            exemplar = _stage_exemplar(stages, ('decode_split', 'decode',
                                                'host_batch'))
            if exemplar is not None:
                # Tail exemplar (ISSUE 13): the p99 is not anonymous —
                # it names a journaled batch petastorm-tpu-explain can
                # reconstruct.
                evidence_bits.append(
                    'p99 exemplar: journal step %s (%s ms) — '
                    'petastorm-tpu-explain --step %s names its '
                    'file/rowgroup/worker'
                    % (exemplar['ref'].get('step'), exemplar.get('ms'),
                       exemplar['ref'].get('step')))
        elif regime == 'link-bound':
            link = _stage_p99(stages, ('h2d_commit', 'h2d_dispatch',
                                       'device_put'))
            stage = _stage_p99(stages, ('h2d_stage',))
            if link is not None or stage is not None:
                evidence_bits.append(
                    'h2d (link) p99 %s ms vs h2d_stage (host copy) '
                    'p99 %s ms' % (link, stage))
        elif regime == 'fetch-bound':
            wait = _stage_p99(stages, ('ingest_wait',))
            fetch = _stage_p99(stages, ('ingest_fetch',))
            if wait is not None or fetch is not None:
                evidence_bits.append(
                    'decode blocked on fetches p99 %s ms vs fetch wall '
                    'p99 %s ms' % (wait, fetch))
        elif regime == 'skew-bound':
            for name in ('decode', 'decode_split'):
                stage = stages.get(name)
                if stage and stage.get('p99_ms') is not None:
                    evidence_bits.append(
                        '%s p50 %s ms vs p99 %s ms over %d items'
                        % (name, stage.get('p50_ms'), stage.get('p99_ms'),
                           stage.get('count', 0)))
                    break
        elif regime == 'cluster-cache-degraded':
            worker = _worst_worker(evidence, 'cache_peer_degraded')
            if worker:
                evidence_bits.append(
                    'worst worker %s: cache_peer_degraded %d — its '
                    'misses name entries a live peer advertises but '
                    'cannot deliver' % (worker[0], worker[1]))
        elif regime == 'cache-degraded':
            worker = _worst_worker(evidence, 'cache_degraded')
            if worker:
                evidence_bits.append(
                    'worst worker %s: cache_degraded %d with %d hits '
                    '(a plane silently OFF keeps degrading while hits '
                    'look plausible)' % worker)
        elif regime == 'tenant-starved':
            rows = evidence.get('tenants') or {}
            granted = sorted(
                (tid for tid, row in rows.items()
                 if int(row.get('grants_delta', 0) or 0) > 0),
                key=lambda t: -int(rows[t].get('grants_delta', 0) or 0))
            if granted:
                top = granted[0]
                evidence_bits.append(
                    'meanwhile tenant %r took %d grant(s) this window '
                    '(weight %.1f)'
                    % (top, int(rows[top].get('grants_delta', 0) or 0),
                       float(rows[top].get('weight', 1.0) or 1.0)))
        elif regime == 'control-flapping':
            # Cite the actual journaled decision, not just the pair
            # count: the flapping actor's last real action with its
            # rule, subject, and age (ISSUE 20).
            rows = evidence.get('decisions') or {}
            for actor in sorted(rows):
                if actor not in candidate['evidence']:
                    continue
                last = (rows[actor] or {}).get('last')
                if last:
                    subject = last.get('worker_id') or last.get('tenant')
                    evidence_bits.append(
                        'last journaled %s action: %s%s (rule %s, '
                        '%.0fs ago) — petastorm-tpu-why --actor %s '
                        'replays the timeline'
                        % (actor, last.get('action'),
                           ' %s' % subject if subject else '',
                           last.get('rule'),
                           float(last.get('age_s', 0.0) or 0.0), actor))
                break
        elif regime == 'shm-degraded':
            worker = _worst_worker(evidence, 'shm_degraded')
            if worker:
                evidence_bits.append('worst worker %s: shm_degraded %d '
                                     '(shm_chunks %d)'
                                     % (worker[0], worker[1],
                                        (evidence.get('workers') or {})
                                        .get(worker[0], {})
                                        .get('shm_chunks', 0)))
        verdicts.append({
            'id': regime,
            'severity': 'crit' if candidate['severity'] >= 0.75 else 'warn',
            'score': candidate['severity'],
            'summary': regime,
            'evidence': '; '.join(evidence_bits),
            'action': action,
        })
    return verdicts


def _stage_exemplar(stages, names):
    """The first tail exemplar carried by one of the named stage
    summaries (``summarize_hist`` attaches them when the source
    histogram recorded any), with a usable ``ref``."""
    for name in names:
        exemplar = (stages.get(name) or {}).get('exemplar')
        if exemplar and isinstance(exemplar.get('ref'), dict):
            return exemplar
    return None


def _worst_worker(evidence, key):
    rows = evidence.get('workers') or {}
    worst = None
    for wid, row in rows.items():
        value = int(row.get(key, 0) or 0)
        if value > 0 and (worst is None or value > worst[1]):
            worst = (wid, value, int(row.get('cache_hits', 0) or 0))
    return worst


def rule_failed_splits(evidence):
    failed = int((evidence.get('meta') or {}).get('failed', 0) or 0)
    if not failed:
        return None
    return {
        'id': 'failed-splits', 'severity': 'crit', 'score': 1.0,
        'summary': '%d split(s) terminally failed' % failed,
        'evidence': 'the dispatcher exhausted max_split_attempts on '
                    'them; consumers of those splits raise ServiceError',
        'action': 'inspect worker logs for the decode error (poisoned '
                  'row group, bad codec); fix or filter the data, then '
                  'restart the job',
    }


def rule_clock_drift(evidence):
    rows = evidence.get('workers') or {}
    drifting = {wid: row['clock_drift_ms'] for wid, row in rows.items()
                if abs(row.get('clock_drift_ms') or 0.0)
                >= CLOCK_DRIFT_WARN_MS}
    if not drifting:
        return None
    worst = max(drifting.items(), key=lambda kv: abs(kv[1]))
    return {
        'id': 'clock-drift', 'severity': 'warn',
        'score': min(1.0, abs(worst[1]) / 1000.0),
        'summary': 'worker clock drift up to %.0f ms (%s)' % (worst[1],
                                                              worst[0]),
        'evidence': 'EWMA offset moved vs the registration handshake on '
                    '%d worker(s): %s' % (len(drifting), sorted(drifting)),
        'action': 'cross-process span alignment is unreliable past the '
                  'log2 bucket resolution on the affected timelines; '
                  'trust counters/histograms, re-run the job for traces',
    }


def rule_span_residue(evidence):
    residue = evidence.get('span_residue')
    if not residue or residue < 64:
        return None
    return {
        'id': 'span-residue', 'severity': 'info',
        'score': min(1.0, residue / 4096.0),
        'summary': '%d spans recorded but never drained' % residue,
        'evidence': 'the process span buffer holds completed spans no '
                    'ack/heartbeat channel shipped',
        'action': 'an instrumented subsystem runs without its return '
                  'channel (bounded, so harmless — but its telemetry is '
                  'invisible upstream)',
    }


def rule_watchdog_reason(evidence):
    reason = evidence.get('reason')
    if not reason or not str(reason).startswith('watchdog'):
        return None
    return {
        'id': 'suite-hang', 'severity': 'crit', 'score': 1.0,
        'summary': 'artifact written by the suite watchdog (%s)' % reason,
        'evidence': 'the run hung past the watchdog window; the stderr '
                    'thread dump names the wedged frame, this artifact '
                    'holds the telemetry trajectory before it',
        'action': 'read the faulthandler stacks next to this artifact; '
                  'the regime verdicts below say what the data plane was '
                  'doing as it hung',
    }


def rule_slow_batches(evidence):
    """Per-batch provenance (ISSUE 13): when the input carries a
    journal's rolling worst-K, name the slowest batch and point at
    ``petastorm-tpu-explain`` — the per-batch causal chain is stronger
    evidence than any aggregate."""
    worst = evidence.get('provenance_worst')
    if not worst:
        return None
    head = worst[0]
    detail = ', '.join(
        '%s=%s' % (key, head[key])
        for key in ('worker_pid', 'piece', 'cache', 'transport')
        if head.get(key) is not None)
    return {
        'id': 'slow-batch-provenance', 'severity': 'info',
        'score': min(1.0, (head.get('latency_ms') or 0.0) / 10000.0),
        'summary': 'slowest journaled batch: step %s at %s ms'
                   % (head.get('step'), head.get('latency_ms')),
        'evidence': 'rolling worst-K from the provenance journal: %s'
                    % (detail or 'no identity fields recorded'),
        'action': 'petastorm-tpu-explain --step %s against this '
                  'artifact reconstructs the full causal chain (stages, '
                  'worker, file + rowgroup, scheduling decision)'
                  % head.get('step'),
    }


def rule_dispatcher_restarts(evidence):
    """ISSUE 15: the ledger lineage counts every control-plane restart
    of this job.  One restart is survivable news (that is what the
    ledger is FOR); a repeat offender is a crash loop."""
    control = evidence.get('control_plane') or {}
    restarts = int(control.get('ledger_restores', 0) or 0)
    if not restarts:
        return None
    adopted = int(control.get('ledger_adoptions', 0) or 0)
    requeued = int(control.get('ledger_requeues', 0) or 0)
    return {
        'id': 'dispatcher-restarts',
        'severity': 'crit' if restarts >= 3 else 'warn',
        'score': min(1.0, 0.3 + 0.2 * restarts),
        'summary': 'dispatcher restarted %d time(s) (ledger lineage)'
                   % restarts,
        'evidence': 'restore reconciliation: %d orphan lease(s) '
                    'resumed by re-registering workers, %d requeued '
                    'attempt-intact' % (adopted, requeued),
        'action': 'delivery stayed exactly-once through the ledger '
                  'restore, but every restart pauses lease traffic — '
                  'find the crash cause in the dispatcher logs; a '
                  'climbing count means a crash loop, not bad luck',
    }


def rule_drain_timeouts(evidence):
    """ISSUE 15: a drain that overran its deadline left splits to
    requeue (attempt+1) — the graceful scale-in path is not actually
    graceful at this drain_timeout_s."""
    control = evidence.get('control_plane') or {}
    timeouts = int(control.get('drain_timeouts', 0) or 0)
    if not timeouts:
        return None
    drains = int(control.get('drains', 0) or 0)
    return {
        'id': 'drain-timeout', 'severity': 'warn',
        'score': min(1.0, 0.4 + 0.2 * timeouts),
        'summary': 'worker drain timed out %d time(s) (of %d drains)'
                   % (timeouts, drains),
        'evidence': 'the worker deregistered with splits still in '
                    'flight; the dispatcher requeued them at attempt+1',
        'action': 'raise drain_timeout_s past the real worst-case '
                  'in-flight split time (decode + stream + client ack), '
                  'or shrink rowgroups_per_split so splits finish '
                  'faster; orchestrators must set '
                  'terminationGracePeriod above drain_timeout_s',
    }


_RULES = (rule_failed_splits, rule_watchdog_reason, rule_clock_drift,
          rule_span_residue, rule_slow_batches, rule_dispatcher_restarts,
          rule_drain_timeouts)


def run_rules(evidence):
    """Every applicable verdict, ranked most severe first; never empty —
    a clean fleet gets an explicit bill of health (verdict id
    ``healthy``), because "no output" is indistinguishable from a broken
    tool."""
    verdicts = _regime_verdicts(evidence)
    for rule in _RULES:
        verdict = rule(evidence)
        if verdict is not None:
            verdicts.append(verdict)
    verdicts.sort(key=lambda v: (_SEVERITY_RANK.get(v['severity'], 0),
                                 v['score']), reverse=True)
    if not any(v['severity'] in ('crit', 'warn') for v in verdicts):
        report = evidence.get('health') or {}
        verdicts.insert(0, {
            'id': report.get('regime', 'healthy'), 'severity': 'ok',
            'score': 0.0,
            'summary': report.get('regime', 'healthy'),
            'evidence': report.get('regime_evidence',
                                   'no signal above threshold'),
            'action': 'nothing to do',
        })
    return verdicts


def diagnose(evidence):
    """Evidence dict -> full report dict (the ``--json`` shape)."""
    return {'source': evidence.get('source'),
            'health': evidence.get('health'),
            'verdicts': run_rules(evidence)}


def render_report(report):
    lines = ['petastorm-tpu-diagnose — %s' % report.get('source')]
    health = report.get('health')
    if health:
        lines.append(_health.format_health_line(health))
    for i, verdict in enumerate(report['verdicts'], 1):
        lines.append('%2d. [%s] %s — %s'
                     % (i, verdict['severity'], verdict['summary'],
                        verdict['evidence']))
        lines.append('      -> %s' % verdict['action'])
    return '\n'.join(lines)


# -- CLI ----------------------------------------------------------------------

def _poll_stats(addr, timeout_s):
    import zmq

    from petastorm_tpu.service.worker import _Rpc
    context = zmq.Context()
    rpc = _Rpc(context, addr, timeout_s=timeout_s)
    try:
        return rpc.call({'op': 'stats'})
    finally:
        rpc.close()
        context.term()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-diagnose',
        description=__doc__.split('\n\n')[0])
    source = parser.add_mutually_exclusive_group(required=True)
    source.add_argument('--dispatcher',
                        help='live fleet: dispatcher endpoint '
                             '(tcp://host:port)')
    source.add_argument('--flight',
                        help='flight-recorder dump file (JSON)')
    source.add_argument('--artifact',
                        help='conftest watchdog / telemetry dump file '
                             '(JSON)')
    parser.add_argument('--window', type=float, default=60.0,
                        help='delta window in seconds for ring inputs')
    parser.add_argument('--json', action='store_true',
                        help='emit the report as JSON')
    parser.add_argument('--rpc-timeout', type=float, default=10.0)
    args = parser.parse_args(argv)

    try:
        if args.dispatcher:
            t0 = time.monotonic()
            stats = _poll_stats(args.dispatcher, args.rpc_timeout)
            evidence = evidence_from_stats(
                stats, source='live fleet %s (stats rpc %.0f ms)'
                % (args.dispatcher, 1e3 * (time.monotonic() - t0)))
        elif args.flight:
            with open(args.flight) as f:
                evidence = evidence_from_flight(json.load(f),
                                                window_s=args.window)
        else:
            with open(args.artifact) as f:
                evidence = evidence_from_artifact(json.load(f),
                                                  window_s=args.window)
    except Exception as e:  # noqa: BLE001 — report, exit nonzero
        print('cannot ingest input: %s: %s' % (type(e).__name__, e),
              file=sys.stderr)
        return 1
    report = diagnose(evidence)
    if args.json:
        print(json.dumps(report, sort_keys=True, default=str))
    else:
        print(render_report(report))
    return 0


if __name__ == '__main__':
    sys.exit(main())
