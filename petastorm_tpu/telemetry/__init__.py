"""Cross-process telemetry plane (ISSUE 5) + fleet health & diagnosis
plane (ISSUE 7).

Five pillars, one package:

* **Metrics registry** (``registry.py``) — process-local counters /
  gauges / histograms with fixed log2 buckets, so merging registries
  from other processes is pure addition.  The ad-hoc diagnostics dicts
  (``Reader.diagnostics``, ``DataLoader.diagnostics``, pool
  ``shm_results``, cache-plane hits/misses, dispatcher ``stats``) are
  VIEWS over these registries; worker-side registries snapshot into the
  existing return channels (ProcessPool acks, service heartbeats) and
  merge in the parent.  ``summarize_hist`` is the ONE canonical
  histogram summary every surface prints.
* **Correlated spans** (``spans.py``) — bounded per-process span
  buffers keyed by correlation id (ventilator item position / service
  ``split/seq``), shipped over the existing ZMQ frames and merged into
  ONE ``benchmark.TraceRecorder`` timeline with per-process
  ``time.monotonic()`` clock-offset alignment.
* **Flight recorder** (``flight.py``) — an always-on bounded ring of
  periodic registry-snapshot frames per process, periodically persisted
  so a postmortem sees the minutes BEFORE a crash, not just the final
  totals.
* **Health engine** (``health.py``) — windowed snapshot deltas
  classified into actionable regimes (decode-bound / link-bound /
  lease-starved / cache-degraded / shm-degraded) with per-component
  scores, surfaced by dispatcher ``stats``, ``top``, and Prometheus
  gauges.
* **Introspection & diagnosis** (``top.py`` / ``diagnose.py``) — the
  ``petastorm-tpu-top`` live view and the ``petastorm-tpu-diagnose``
  verdict CLI over live fleets, flight dumps, and watchdog artifacts.

See ``docs/observability.md`` for the registry model, the span
catalogue, the verdict catalogue, and scrape examples.
"""

from petastorm_tpu.telemetry import decisions  # noqa: F401
from petastorm_tpu.telemetry import flight  # noqa: F401
from petastorm_tpu.telemetry import health  # noqa: F401
from petastorm_tpu.telemetry import provenance  # noqa: F401
from petastorm_tpu.telemetry.registry import (  # noqa: F401
    MetricsRegistry, hist_quantile, merge_snapshots, snapshot_all,
    snapshot_delta, summarize_hist)
from petastorm_tpu.telemetry.spans import (  # noqa: F401
    SpanBuffer, attribute_stalls, current_buffer, measure_clock_offset,
    merge_into_recorder)

__all__ = ['MetricsRegistry', 'merge_snapshots', 'hist_quantile',
           'snapshot_all', 'snapshot_delta', 'summarize_hist',
           'SpanBuffer', 'current_buffer', 'merge_into_recorder',
           'measure_clock_offset', 'attribute_stalls', 'dump_state',
           'decisions', 'flight', 'health', 'provenance']


def dump_state():
    """One JSON-able dict of every live registry snapshot, every live
    ``TraceRecorder``'s events, the span-buffer residue, and the flight
    recorder's frame ring in this process — the crash-artifact dump the
    test-suite watchdog writes (``tests/conftest.py``), so the next
    silent-death bug ships with a timeline AND the minutes before it
    attached.  ``petastorm-tpu-diagnose --artifact`` ingests this shape."""
    from petastorm_tpu.benchmark.trace import all_recorder_events
    return {'registries': snapshot_all(),
            'trace_events': all_recorder_events(),
            'span_residue': current_buffer().peek(),
            'flight': flight.dump_current(),
            # Per-batch provenance journals (ISSUE 13): the causal
            # chains `petastorm-tpu-explain --artifact` reconstructs.
            'provenance': provenance.dump_journals(),
            # Control-plane decision journals (ISSUE 20): the records
            # `petastorm-tpu-why --artifact` explains.
            'decisions': decisions.dump_journals()}
