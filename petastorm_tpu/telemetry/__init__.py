"""Cross-process telemetry plane (ISSUE 5).

Three pillars, one package:

* **Metrics registry** (``registry.py``) — process-local counters /
  gauges / histograms with fixed log2 buckets, so merging registries
  from other processes is pure addition.  The ad-hoc diagnostics dicts
  (``Reader.diagnostics``, ``DataLoader.diagnostics``, pool
  ``shm_results``, cache-plane hits/misses, dispatcher ``stats``) are
  VIEWS over these registries; worker-side registries snapshot into the
  existing return channels (ProcessPool acks, service heartbeats) and
  merge in the parent.
* **Correlated spans** (``spans.py``) — bounded per-process span
  buffers keyed by correlation id (ventilator item position / service
  ``split/seq``), shipped over the existing ZMQ frames and merged into
  ONE ``benchmark.TraceRecorder`` timeline with per-process
  ``time.monotonic()`` clock-offset alignment.
* **Live introspection** (``top.py``) — the ``petastorm-tpu-top``
  console script polling the dispatcher ``stats`` RPC, plus
  ``MetricsRegistry.render_prometheus()`` for any scraper.

See ``docs/observability.md`` for the registry model, the span
catalogue, and scrape examples.
"""

from petastorm_tpu.telemetry.registry import (  # noqa: F401
    MetricsRegistry, hist_quantile, merge_snapshots, snapshot_all)
from petastorm_tpu.telemetry.spans import (  # noqa: F401
    SpanBuffer, attribute_stalls, current_buffer, measure_clock_offset,
    merge_into_recorder)

__all__ = ['MetricsRegistry', 'merge_snapshots', 'hist_quantile',
           'snapshot_all', 'SpanBuffer', 'current_buffer',
           'merge_into_recorder', 'measure_clock_offset',
           'attribute_stalls', 'dump_state']


def dump_state():
    """One JSON-able dict of every live registry snapshot and every live
    ``TraceRecorder``'s events in this process — the crash-artifact dump
    the test-suite watchdog writes (``tests/conftest.py``), so the next
    silent-death bug ships with a timeline attached."""
    from petastorm_tpu.benchmark.trace import all_recorder_events
    return {'registries': snapshot_all(),
            'trace_events': all_recorder_events(),
            'span_residue': current_buffer().peek()}
